"""TcpBackend: block payloads inline on the control connection.

The cross-pod/DCN fallback every peer pair supports. Frames are the
shared framing (transfer/framing.py); the byte-pack host-syncs device
gathers, so it runs in an executor — headers alone ride the loop.
"""

from __future__ import annotations

import asyncio
from typing import List, Optional, Tuple

import numpy as np

from .framing import decode_blocks, encode_blocks, pack_frame, read_exact


class TcpBackend:
    """Payload path: raw k/v bytes framed behind the header."""

    name = "tcp"

    @staticmethod
    async def send_blocks(writer: asyncio.StreamWriter, header: dict,
                          k: np.ndarray, v: np.ndarray,
                          packed: Optional[Tuple] = None) -> int:
        """Write one block frame; returns payload bytes. ``packed`` lets
        a pump that already encoded off-loop skip the executor hop."""
        if packed is None:
            loop = asyncio.get_running_loop()
            packed = await loop.run_in_executor(None, encode_blocks, k, v)
        kb, vb, shape, dtype_name = packed
        header = dict(header)
        header.update(shape=shape, dtype=dtype_name,
                      k_bytes=len(kb), v_bytes=len(vb))
        pack_frame(writer, header, kb, vb)
        await writer.drain()
        return len(kb) + len(vb)

    @staticmethod
    async def recv_blocks(reader: asyncio.StreamReader,
                          header: dict) -> Tuple[np.ndarray, np.ndarray]:
        """Read the payload a block-frame header announced."""
        k_raw = await read_exact(reader, header["k_bytes"])
        v_raw = await read_exact(reader, header["v_bytes"])
        return decode_blocks(k_raw, v_raw, header["shape"], header["dtype"])


def payload_nbytes(header: dict) -> int:
    return int(header.get("k_bytes", 0)) + int(header.get("v_bytes", 0))


def block_ids_of(header: dict) -> List[int]:
    return list(map(int, header.get("block_ids") or []))
