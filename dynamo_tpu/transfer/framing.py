"""Shared wire framing for every KV transfer plane.

4-byte big-endian length-prefixed msgpack header, then raw payload
bytes announced by the header (``k_bytes``/``v_bytes``). This module is
the single home of the framing that used to be triplicated across
disagg/transfer.py, kv/fabric.py, and recovery/migration.py — the
header cap, the exact-read helper, the dtype resolution (ml_dtypes for
the fp8/bf16 names numpy doesn't know), and the block-payload
encode/decode pair.

Headers are small (ids, shapes, trace ids) and may be packed on the
event loop; block payloads are NOT — ``encode_blocks`` host-syncs and
copies, so callers run it in an executor (the pack-vs-wire discipline
dynlint's async-blocking rule guards).
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional, Tuple

import msgpack
import numpy as np

MAX_HEADER = 1 << 20


def np_dtype(name: str):
    """Resolve a wire dtype name, falling back to ml_dtypes for the
    accelerator dtypes (bfloat16, float8_*) numpy itself rejects."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


async def read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    return await reader.readexactly(n)


def pack_frame(writer: asyncio.StreamWriter, header: dict,
               *payloads: bytes) -> None:
    """Write one frame: length-prefixed msgpack header + raw payloads.
    The caller drains; payload bytes must already be packed (executor)."""
    data = msgpack.packb(header, use_bin_type=True)
    writer.write(struct.pack(">I", len(data)) + data)
    for p in payloads:
        writer.write(p)


async def read_header(reader: asyncio.StreamReader,
                      what: str = "transfer") -> Optional[dict]:
    """Read one frame header. Returns None on a clean connection end
    (EOF/reset between frames); raises ValueError on an oversized
    header — a corrupt or hostile peer, never recoverable in-stream."""
    try:
        raw_len = await read_exact(reader, 4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (hlen,) = struct.unpack(">I", raw_len)
    if hlen > MAX_HEADER:
        raise ValueError(f"{what} header too large: {hlen}")
    return msgpack.unpackb(await read_exact(reader, hlen), raw=False)


def encode_blocks(k: np.ndarray, v: np.ndarray,
                  ) -> Tuple[bytes, bytes, list, str]:
    """Host-side payload pack: ``(k_bytes, v_bytes, shape, dtype_name)``
    over contiguous copies. Host-syncs — run in an executor."""
    k = np.ascontiguousarray(k)
    v = np.ascontiguousarray(v)
    return k.tobytes(), v.tobytes(), list(k.shape), k.dtype.name


def decode_blocks(k_raw: bytes, v_raw: bytes, shape, dtype_name: str,
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Inverse of :func:`encode_blocks` (zero-copy views over the
    received buffers)."""
    dtype = np_dtype(dtype_name)
    shape = tuple(shape)
    return (np.frombuffer(k_raw, dtype=dtype).reshape(shape),
            np.frombuffer(v_raw, dtype=dtype).reshape(shape))
