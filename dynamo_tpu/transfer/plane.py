"""TransferPlane core: lifecycle, pipelining, poison, unified telemetry.

Everything here is plane- and backend-agnostic; the per-plane handlers
(disagg/transfer.py, kv/fabric.py, recovery/migration.py) compose these
pieces instead of each keeping a private copy:

- :class:`PoisonSet` — the dropped-payload discipline. A request with a
  lost/mis-paired/unauthorized payload frame must have its commit
  NACKED (disagg), its reservation aborted (migration), or its pull
  abandoned (fabric) — resuming over blocks that were never scattered
  silently corrupts the stream. TTL + logged-cap pruning bound it.
- :class:`FramePipe` — the ≤2-frames-in-flight conveyor between a
  chunk/gather producer and one wire pump: ``maxsize=1`` plus the
  pump's one-frame lookahead bounds live host buffers at two
  chunk-sized frames regardless of sequence length.
- :class:`TransferMetrics` — the unified ``dynamo_transfer_*`` family,
  labelled ``{plane, backend}``; replaces the per-plane ad-hoc names
  (retired: dynamo_disagg_transfer_*, dynamo_prefill_worker_transfer_
  bytes_total, dynamo_kv_fabric_prefix_pull_{bytes,duration}_*).
- ``negotiate_backend`` — per-peer-pair payload path selection from
  discovery metadata; tcp is always the safe cross-pod/DCN fallback.
- ``transfer.open`` / ``transfer.poison`` flight events with backend
  attribution.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

logger = logging.getLogger(__name__)

PLANES = ("disagg", "fabric", "migration")

# dropped-payload bookkeeping: ids are removed when their commit is
# nacked; requests that never commit would otherwise accumulate forever.
# TTL >> any sane commit delay (the decode side's prefill timeout is
# 120 s), so expiry never un-poisons a commit that could still arrive;
# the count cap is a last-resort bound and LOGS what it evicts.
MAX_DROPPED = 4096
DROPPED_TTL_S = 600.0

# the chaos site every plane's client (and the fabric's serve side)
# consults between chunk frames — one seam, one env knob
CONN_DROP_FAULT = "transfer_conn_drop"


def record_open(plane: str, backend: str, peer: str = "",
                trace_id: Optional[str] = None) -> None:
    """``transfer.open`` flight event: one channel dialled (or adopted)
    with the negotiated payload backend — the attribution that makes a
    'why was this pull slow' triage a one-ring read."""
    from ..telemetry.flight import flight_recorder

    flight_recorder().record(
        "transfer.open", plane=plane, backend=backend, peer=peer or None,
        trace_id=trace_id,
    )


def maybe_drop_connection(plane: str) -> bool:
    """The ``transfer_conn_drop`` chaos seam, shared by every plane's
    chunk loop: returns True when the armed fault fires — the caller
    closes its writer and raises, exercising the receiver's poison
    path. One call site per chunk keeps the drop mid-stream-able."""
    from ..utils import faults

    return faults.fire(CONN_DROP_FAULT)


class PoisonSet:
    """Request ids whose payload stream can no longer be trusted.

    Insertion-ordered (``dict``) so TTL expiry is a prefix scan; the
    cap eviction LOGS — un-poisoning is the corruption this set exists
    to prevent, so silent eviction would be worse than the memory.
    """

    def __init__(self, plane: str):
        self.plane = plane
        self._dropped: Dict[str, float] = {}

    def __len__(self) -> int:
        return len(self._dropped)

    def __contains__(self, request_id: str) -> bool:
        return request_id in self._dropped

    def mark(self, request_id: str, trace_id: Optional[str] = None,
             backend: str = "tcp", reason: str = "") -> None:
        from ..telemetry.flight import flight_recorder

        now = time.monotonic()
        flight_recorder().record(
            "transfer.poison", plane=self.plane, backend=backend,
            request_id=request_id, trace_id=trace_id,
            reason=reason or None,
        )
        self._dropped.pop(request_id, None)
        self._dropped[request_id] = now
        # TTL expiry (insertion order == time order): anything this old
        # can no longer see a commit — the other side gave up on the
        # request minutes ago
        for rid, t in list(self._dropped.items()):
            if now - t <= DROPPED_TTL_S:
                break
            del self._dropped[rid]
        while len(self._dropped) > MAX_DROPPED:
            rid, _ = next(iter(self._dropped.items()))
            del self._dropped[rid]
            logger.error(
                "dropped-payload set over cap (%d); evicting %s — a late "
                "commit for it would now be accepted", MAX_DROPPED, rid,
            )

    def pop(self, request_id: str) -> bool:
        """Consume a poison mark at commit time: True → nack."""
        return self._dropped.pop(request_id, None) is not None


class FramePipe:
    """Bounded conveyor between the chunk loop and one transfer pump.

    The producer dispatches device gathers and enqueues
    (k_dev, v_dev, dst_ids) frames; the pump coroutine drains them to
    the wire. ``maxsize=1`` plus the pump's one-frame lookahead bounds
    live buffers: at most two chunk-sized frames exist in host memory
    at any point (one being packed, one on the wire), regardless of
    sequence length. On the ici backend payloads never reach the host
    at all — the pipe then bounds in-flight *device* frames the same
    way (one collective in flight, one gather dispatched behind it).
    """

    def __init__(self, depth: int, frame_blocks: int):
        self.depth = depth  # 1 = strictly serial frames, 2 = double-buffered
        self.frame_blocks = frame_blocks  # max KV blocks per frame
        self.q: asyncio.Queue = asyncio.Queue(maxsize=1)
        self.closed = False  # pump consumed the end-of-stream sentinel
        self.error: Optional[BaseException] = None
        self.nbytes = 0
        self.frames = 0
        self.first_frame_t: Optional[float] = None
        self.live_host_frames = 0
        self.max_live_host_frames = 0
        self.task: Optional[asyncio.Task] = None

    async def put(self, frame) -> None:
        if self.error is not None:
            raise self.error
        if self.first_frame_t is None:
            self.first_frame_t = time.monotonic()
        await self.q.put(frame)
        # the pump may have failed while we were blocked on the queue
        if self.error is not None:
            raise self.error

    async def drain(self) -> int:
        """Flush: every enqueued frame is on the wire (or the pump's
        failure is re-raised). Must be awaited before the commit frame."""
        await self.q.put(None)
        await self.task
        if self.error is not None:
            raise self.error
        return self.nbytes

    async def shutdown(self) -> None:
        """Abnormal-exit cleanup: the happy path already joined the pump
        via drain(); anything else is an error/cancel path where the
        connection is being torn down anyway — cancel outright."""
        if self.task is not None and not self.task.done():
            self.task.cancel()
            try:
                await self.task
            # dynlint: allow(silent-except) - cancel-join of an abandoned pump; the originating error already propagated via pipe.error
            except BaseException:
                pass


class TransferMetrics:
    """The unified ``dynamo_transfer_*`` instrument family.

    One instance per component registry; every sample carries
    ``plane`` (disagg|fabric|migration) and ``backend`` (tcp|ici —
    plus ``local`` for the fabric's cold-tier rehydrates, which move
    bytes without a wire). Separate component processes each register
    the family into their own exposition; label sets disambiguate."""

    def __init__(self, registry, plane: Optional[str] = None):
        self.plane = plane
        self._bytes = registry.counter(
            "dynamo_transfer_bytes_total",
            "KV payload bytes moved across the unified transfer plane, "
            "labelled plane=disagg|fabric|migration and backend=tcp|ici|"
            "local",
        )
        self._duration = registry.histogram(
            "dynamo_transfer_duration_seconds",
            "One transfer end to end (first frame enqueued/dialled → "
            "commit acked or last block installed), labelled "
            "{plane, backend}",
        )
        self._exposed = registry.histogram(
            "dynamo_transfer_exposed_seconds",
            "Non-overlapped transfer tail: wire time AFTER the covering "
            "compute finished (commit RTT included; 0 = fully hidden "
            "behind compute), labelled {plane, backend}",
        )
        self._channels = registry.gauge(
            "dynamo_transfer_channels",
            "Open transfer channels (control connections), labelled "
            "{plane, backend}",
        )

    def _labels(self, backend: str, plane: Optional[str]) -> dict:
        return {"plane": plane or self.plane or "?", "backend": backend}

    def add_bytes(self, n: int, backend: str,
                  plane: Optional[str] = None) -> None:
        self._bytes.inc(n, **self._labels(backend, plane))

    def observe_duration(self, seconds: float, backend: str,
                         plane: Optional[str] = None) -> None:
        self._duration.observe(seconds, **self._labels(backend, plane))

    def observe_exposed(self, seconds: float, backend: str,
                        plane: Optional[str] = None) -> None:
        self._exposed.observe(seconds, **self._labels(backend, plane))

    def channel_opened(self, backend: str,
                       plane: Optional[str] = None) -> None:
        self._channels.inc(1, **self._labels(backend, plane))

    def channel_closed(self, backend: str,
                       plane: Optional[str] = None) -> None:
        self._channels.dec(1, **self._labels(backend, plane))


def negotiate_backend(descriptor: Optional[dict], ici,
                      peer_role: str = "receiver") -> str:
    """Pick the payload backend for one peer pair.

    ``descriptor`` is the peer's discovery record ({modes, ici_rank});
    ``ici`` the LOCAL collective plane (None, or abandoned → tcp);
    ``peer_role`` names the role the PEER plays on that plane
    ("receiver" when we send — disagg push, migration; "sender" when we
    pull — fabric). ici applies only when the peer advertises the mode
    AND its rank matches the local plane's configured opposite role —
    an ici-enabled peer on a different mesh would enter a collective
    that never pairs, stranding both sides. A descriptor without a rank
    predates rank advertisement: trust the mode flag (matches pre-rank
    behavior; a genuine mismatch is only detectable when the peer says
    who it is)."""
    if ici is None or not getattr(ici, "alive", True):
        return "tcp"
    modes = (descriptor or {}).get("modes") or ("tcp",)
    if "ici" not in modes:
        return "tcp"
    rank = (descriptor or {}).get("ici_rank")
    want = getattr(ici, f"{peer_role}_rank", None)
    if rank is not None and want is not None and rank != want:
        logger.warning(
            "peer's ici %s rank %s != configured %s; using tcp",
            peer_role, rank, want,
        )
        return "tcp"
    return "ici"
