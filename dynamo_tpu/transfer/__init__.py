"""Unified zero-copy KV transfer plane.

One framing, one pipelining discipline, one poison vocabulary for every
byte of KV that crosses a worker boundary — the TPU-native analog of
the reference's single NIXL/RDMA data plane. Three *planes* ride it:

- ``disagg``    — streamed remote prefill (disagg/prefill_worker.py →
  disagg/transfer.py), prefill KV pushed into a decode engine's cache.
- ``fabric``    — cluster-KV-fabric prefix pulls (kv/fabric.py), a
  peer's committed prefix pulled into a reserved run of blocks.
- ``migration`` — live request migration (recovery/migration.py), a
  draining engine's committed KV shipped to a healthy peer.

and two *backends* move the payload bytes:

- ``tcp`` (transfer/tcp.py) — length-prefixed msgpack headers with the
  raw k/v bytes inline; packing and host syncs ride the executor.
- ``ici`` (transfer/ici.py) — headers still ride the TCP control
  connection (ordering + ids), but payloads move device-to-device over
  the collective interconnect: the host touches headers only, one
  collective in flight, sequence numbers cross-checked header-vs-
  payload so a died-mid-stream sender can never mis-scatter.

The backend is negotiated per peer pair from discovery metadata
(``negotiate_backend``): same-pod pairs whose collective planes line up
use ici; everything else (cross-pod, DCN, version skew) falls back to
tcp. See docs/transfer_plane.md.
"""

from .framing import (  # noqa: F401
    MAX_HEADER,
    np_dtype,
    pack_frame,
    read_exact,
    read_header,
)
from .plane import (  # noqa: F401
    FramePipe,
    PoisonSet,
    TransferMetrics,
    maybe_drop_connection,
    negotiate_backend,
    record_open,
)
from .tcp import TcpBackend  # noqa: F401
from .ici import (  # noqa: F401
    IciBackend,
    LoopbackIciTransfer,
    bounded_collective_recv,
    call_in_daemon_thread,
)
