"""IciBackend: block payloads device-to-device over the interconnect.

Generalizes disagg/ici_transfer.py's pipelined collective path into a
backend every plane can negotiate: headers (ids, seq, offsets) still
ride the TCP control connection — they carry ordering and
authorization — while the k/v bytes enter the jitted collective and
move HBM→HBM, the host touching nothing but headers. The discipline
that makes this safe is concentrated here:

- **one collective in flight** — entries are strictly ordered and
  payloads pair with headers 1:1, so a sender writes header i+1 only
  after collective i resolved; receivers serialize entries behind a
  lock.
- **seq cross-check** — the sequence number rides IN the collective
  payload and is compared against the header's: a sender that died
  between header and collective leaves an entry that pairs with a
  LATER send, and the mismatch drops the mis-paired payload instead of
  scattering bytes under the wrong block ids.
- **bounded receive** — a stranded collective recv owns its thread
  forever; it runs on a daemon thread behind ``asyncio.wait_for``, and
  a timeout abandons the plane receiver-side (stop advertising "ici";
  in-flight requests poison, future transfers ride tcp).
- **poison/balancing on send failure** — a failure BEFORE entering the
  collective leaves the receiver with an unpaired entry: pair it with
  a poison payload (seq -1 never matches) and keep the plane. A
  failure AFTER entering (or unknowable) abandons the plane — the
  distributed runtime is suspect, tcp from now on.

:class:`LoopbackIciTransfer` is the in-process stand-in with the same
interface — the loopback differentials (tests/test_transfer_plane.py)
and the ``xla:k8:ici-pull`` bench lever run the full negotiation,
framing, and poison discipline on CPU without a second host.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import queue as _queue
import threading
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

DEFAULT_RECV_TIMEOUT_S = 120.0


def call_in_daemon_thread(fn, *args) -> "concurrent.futures.Future":
    """Run fn on a fresh DAEMON thread. A stranded collective recv
    blocks its thread forever; ThreadPoolExecutor workers are
    non-daemon and joined by an atexit hook, so a wedged one would
    hang interpreter shutdown — daemon threads don't."""
    fut: concurrent.futures.Future = concurrent.futures.Future()

    def work():
        try:
            result = fn(*args)
        except BaseException as e:
            if not fut.cancelled():
                fut.set_exception(e)
        else:
            if not fut.cancelled():
                fut.set_result(result)

    threading.Thread(target=work, daemon=True, name="ici-recv").start()
    return fut


async def bounded_collective_recv(recv: Callable[[int], tuple],
                                  nblocks: int,
                                  timeout_s: float) -> tuple:
    """One collective receive, bounded: ``recv(nblocks)`` runs on a
    daemon thread (it may never return — see above) behind
    ``asyncio.wait_for``. Raises ``asyncio.TimeoutError`` when the
    sender was lost after its header; the caller abandons the plane."""
    return await asyncio.wait_for(
        asyncio.wrap_future(call_in_daemon_thread(recv, nblocks)),
        timeout=timeout_s,
    )


async def settle_collective_send(loop, plane, fut, ndst: int,
                                 on_abandon: Callable[[], None]) -> None:
    """Await a collective send entered via an executor and, on failure,
    run the pairing discipline: pre-entry failures get a balancing
    poison entry (plane stays usable); entered/unknowable failures
    abandon the plane via ``on_abandon``. Always re-raises the failure
    — the caller's transfer is lost either way and must fall back."""
    from ..disagg.ici_transfer import IciSendError

    try:
        await fut
    except IciSendError as e:
        if not e.entered:
            # receiver holds an unpaired entry for this header — pair
            # it with a poison payload (seq -1 never matches) so the
            # plane stays 1:1 and REMAINS usable for the retry
            try:
                await loop.run_in_executor(
                    None, lambda n=ndst: plane.send_balancing_entry(n)
                )
                logger.warning(
                    "collective send failed before entering; balanced "
                    "the plane and keeping it"
                )
            except BaseException:
                logger.exception(
                    "balancing entry failed; abandoning the collective "
                    "plane (tcp fallback)"
                )
                on_abandon()
        else:
            # the collective itself failed — both sides' entries
            # unwound, but the distributed runtime is now suspect
            logger.exception(
                "ici collective failed; abandoning the plane "
                "(tcp fallback)"
            )
            on_abandon()
        raise
    except BaseException:
        # not even classifiable as an IciSendError (loopback doubles,
        # interpreter teardown): pairing state unknowable → abandon
        logger.exception(
            "collective send failed unclassifiably; abandoning the plane"
        )
        on_abandon()
        raise


class IciBackend:
    """One plane's handle on a collective transfer endpoint.

    Wraps an ``IciKvTransfer``-shaped object (``send``/``recv``/
    ``send_balancing_entry``/``buckets``/ranks) with the bounded-recv,
    seq-allocation, and abandonment discipline. ``alive`` flips False
    on abandonment — negotiation then routes new transfers over tcp.
    """

    name = "ici"

    def __init__(self, plane, recv_timeout_s: float = DEFAULT_RECV_TIMEOUT_S):
        self.plane = plane
        self.alive = True
        self.recv_timeout_s = recv_timeout_s
        self._seq = 0
        # collective entries are strictly ordered — serialize receives
        # across connections (the payloads pair with headers 1:1)
        self.recv_lock = asyncio.Lock()

    @property
    def sender_rank(self):
        return getattr(self.plane, "sender_rank", None)

    @property
    def receiver_rank(self):
        return getattr(self.plane, "receiver_rank", None)

    @property
    def buckets(self) -> Sequence[int]:
        return self.plane.buckets

    def abandon(self) -> None:
        self.alive = False

    def next_seq(self) -> int:
        self._seq += 1
        return self._seq

    async def send(self, k_dev, v_dev, seq: int, ndst: int) -> int:
        """Enter the collective with one frame's device arrays; returns
        payload bytes moved. Raises on failure AFTER running the
        pairing discipline (balance or abandon)."""
        loop = asyncio.get_running_loop()
        fut = loop.run_in_executor(
            None, lambda a=k_dev, b=v_dev, s=seq: self.plane.send(a, b, s)
        )
        await settle_collective_send(loop, self.plane, fut, ndst,
                                     self.abandon)
        return int(k_dev.nbytes) + int(v_dev.nbytes)

    async def recv(self, nblocks: int) -> Tuple:
        """One bounded, serialized collective receive → (k, v, seq).
        A timeout abandons the plane and re-raises — the stranded recv
        owns the plane's ordering, so it is unusable from here on."""
        try:
            async with self.recv_lock:
                return await bounded_collective_recv(
                    self.plane.recv, nblocks, self.recv_timeout_s
                )
        except asyncio.TimeoutError:
            logger.error(
                "collective recv timed out after %.0fs (sender lost "
                "after header?) — abandoning the ici plane on the "
                "receiver side", self.recv_timeout_s,
            )
            self.abandon()
            raise


class LoopbackIciTransfer:
    """In-process collective-plane double with IciKvTransfer's surface.

    One object is BOTH endpoints: ``send`` (executor thread on the
    sending side) hands device arrays to ``recv`` (daemon thread on the
    receiving side) through a depth-1 queue — the real plane's
    one-collective-in-flight pairing, minus the mesh. Arrays are passed
    by reference: nothing is host-synced or copied, so a loopback
    transfer is as zero-copy as the CPU backend allows, and tests can
    assert no whole-sequence host buffer ever materializes.

    ``fail_next_send`` arms a one-shot failure for chaos tests:
    ``"pre"`` raises before pairing (balancing discipline), ``"post"``
    after (abandonment discipline).
    """

    def __init__(self, sender_rank: int = 0, receiver_rank: int = 1,
                 buckets: Sequence[int] = (16,)):
        self.sender_rank = sender_rank
        self.receiver_rank = receiver_rank
        self.buckets = list(buckets)
        self._q: _queue.Queue = _queue.Queue(maxsize=1)
        self.fail_next_send: Optional[str] = None
        self.sent = 0
        self.balanced = 0

    def _eff(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def send(self, k, v, seq: int = 0) -> None:
        from ..disagg.ici_transfer import IciSendError

        if self.fail_next_send == "pre":
            self.fail_next_send = None
            raise IciSendError(RuntimeError("loopback chaos: pre-entry"),
                               entered=False)
        self._q.put((k, v, int(seq)))
        self.sent += 1
        if self.fail_next_send == "post":
            self.fail_next_send = None
            raise IciSendError(RuntimeError("loopback chaos: post-entry"),
                               entered=True)

    def send_balancing_entry(self, nblocks: int) -> None:
        n = self._eff(nblocks)
        self._q.put((np.zeros((1, n, 1, 1, 1), np.float32),
                     np.zeros((1, n, 1, 1, 1), np.float32), -1))
        self.balanced += 1

    def recv(self, nblocks: int) -> Tuple:
        k, v, seq = self._q.get()
        return k[:, :nblocks], v[:, :nblocks], seq
