"""The simulated fleet: real control plane, virtual time, byte-model
workers.

``SimFleet`` wires the REAL classes together exactly as the serving
edge does — ``AdmissionController`` gates concurrency, ``TenantQuotas``
meter tenants, ``PoolManager``/``PoolPolicy`` run cold start and
scale-to-zero, ``KvScheduler`` routes on prefix overlap, ``SlaPolicy``
inside a real ``Planner`` scales/sheds, and one real
``RecoveryController`` per worker runs the drain→respawn ladder when
the sim watchdog trips a wedge. The only simulated parts are the
workers (sim/worker.py) and the actuator that turns ScaleActions into
spawned/retired sim workers.
"""

from __future__ import annotations

import asyncio
import dataclasses
import itertools
import logging
from typing import Dict, List, Optional, Tuple

from ..kv_router.indexer import OverlapScores
from ..kv_router.scheduler import AllWorkersBusy, KvScheduler
from ..planner.admission import (
    AdmissionConfig, AdmissionController, AdmissionRejected,
)
from ..planner.actuation import LocalActuator
from ..planner.planner import Planner, PlannerConfig
from ..planner.policy import (
    PolicyConfig, RebalanceAction, ScaleAction, SlaPolicy,
)
from ..recovery.controller import RecoveryConfig, RecoveryController
from ..registry.cards import ModelCard
from ..registry.policy import PoolPolicy, PoolPolicyConfig
from ..registry.pools import ColdStartTimeout, PoolConfig, PoolManager
from ..registry.registry import ModelRegistry
from ..registry.tenants import TenantQuota, TenantQuotas
from ..telemetry.flight import FlightRecorder
from ..telemetry.registry import MetricsRegistry
from ..telemetry.slo import SloTracker
from ..utils import faults
from .metrics import SimMetrics
from .worker import SimRequest, SimWorker, WorkerSchedAdapter, WorkerSpec
from .workload import Request

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ChaosEvent:
    """Wedge one worker at a virtual time via the DYN_FAULT vocabulary."""

    at_s: float
    site: str = "decode_burst_hang"
    worker_index: int = 0


@dataclasses.dataclass
class FleetConfig:
    """One scenario's fleet shape + control-plane tuning."""

    primary_model: str = "sim-model"
    spec: WorkerSpec = dataclasses.field(default_factory=WorkerSpec)
    # model → initial worker count (primary included); every model gets
    # a registry card so PoolManager treats it as a pool citizen
    pools: Dict[str, int] = dataclasses.field(default_factory=dict)
    admission: AdmissionConfig = dataclasses.field(
        default_factory=lambda: AdmissionConfig(
            limit=48, queue_depth=64, queue_timeout_s=15.0))
    policy: PolicyConfig = dataclasses.field(
        default_factory=lambda: PolicyConfig(
            min_replicas=1, max_replicas=6,
            scale_up_cooldown_s=30.0, scale_down_cooldown_s=240.0))
    pool_policy: PoolPolicyConfig = dataclasses.field(
        default_factory=lambda: PoolPolicyConfig(
            idle_to_zero_s=300.0, cooldown_s=60.0))
    recovery: RecoveryConfig = dataclasses.field(
        default_factory=lambda: RecoveryConfig(
            migrate=False, respawn_backoff_s=1.0, seize_timeout_s=2.0))
    quota_default: TenantQuota = dataclasses.field(
        default_factory=lambda: TenantQuota())
    quota_overrides: Dict[str, TenantQuota] = dataclasses.field(
        default_factory=dict)
    slo_ttft_s: float = 4.0
    slo_itl_s: float = 0.25
    slo_window_s: float = 60.0
    planner_interval_s: float = 5.0
    scrape_interval_s: float = 2.0
    pool_step_every: int = 5              # scrape cycles per pools.step()
    watchdog_stall_s: float = 15.0
    max_attempts: int = 8
    chaos: List[ChaosEvent] = dataclasses.field(default_factory=list)


class SimScaleActuator:
    """Applies the planner's ScaleActions to the simulated fleet —
    the in-sim stand-in for KubeActuator, with the same ``apply`` /
    ``replicas`` protocol."""

    def __init__(self, fleet: "SimFleet") -> None:
        self.fleet = fleet

    def replicas(self) -> Dict[str, int]:
        return self.fleet.planner_replicas()

    async def apply(self, action) -> bool:
        if isinstance(action, RebalanceAction):
            # the sim has no disagg router; acknowledge the rebalance so
            # the policy's pacing state stays truthful, and keep it on
            # the timeline for the report
            self.fleet.record_event(
                "rebalance",
                max_local_prefill_length=action.max_local_prefill_length,
                max_prefill_queue_size=action.max_prefill_queue_size,
                reason=action.reason)
            return True
        if not isinstance(action, ScaleAction) or action.role != "decode":
            return False
        fleet = self.fleet
        fleet.metrics.scale_actions.inc(
            role=action.role, direction=action.direction)
        fleet.record_event(
            "scale", role=action.role, direction=action.direction,
            from_replicas=action.current_replicas,
            to_replicas=action.target_replicas, reason=action.reason)
        delta = action.target_replicas - action.current_replicas
        if delta > 0:
            for _ in range(delta):
                fleet.provision(fleet.cfg.primary_model)
        else:
            fleet.retire(fleet.cfg.primary_model, -delta)
        return True


class SimFleet:
    def __init__(self, cfg: FleetConfig, clock) -> None:
        self.cfg = cfg
        self.clock = clock
        self.registry = MetricsRegistry()
        self.flight = FlightRecorder(capacity=8192)
        self.cold_store: set = set()
        self.workers: Dict[str, SimWorker] = {}
        self.controllers: Dict[str, RecoveryController] = {}
        self.events: List[dict] = []
        self.records: List[dict] = []
        self.kv_series: List[Tuple[float, float]] = []
        self.replica_series: List[Tuple[float, int]] = []
        self.resubmits = 0
        self._worker_seq = itertools.count()
        self._provisioning: Dict[str, int] = {}
        self._tasks: set = set()
        self._serve_tasks: List[asyncio.Task] = []
        self._respawned: Dict[str, str] = {}
        self.running = False

        self.metrics = SimMetrics(
            self.registry, clock, self.replica_map)
        self.models = ModelRegistry(registry=self._child())
        self.admission = AdmissionController(
            config=cfg.admission, registry=self._child(),
            flight=self.flight, clock=clock)
        self.slo = SloTracker(
            ttft_s=cfg.slo_ttft_s, itl_s=cfg.slo_itl_s,
            window_s=cfg.slo_window_s, registry=self._child(),
            clock=clock)
        self.quotas = TenantQuotas(
            default=cfg.quota_default, overrides=cfg.quota_overrides,
            clock=clock, registry=self._child())
        self.quotas.bind_admissions(self.admission.registry)
        self.ks = KvScheduler(
            block_size=cfg.spec.block_size,
            staleness_bound_s=10.0 * cfg.scrape_interval_s, clock=clock)
        self.policy = SlaPolicy(config=cfg.policy, clock=clock)
        self.planner = Planner(
            policy=self.policy,
            sources=[self.admission.snapshot, self.slo.snapshot,
                     self._fleet_signals],
            actuators=[SimScaleActuator(self),
                       LocalActuator(admission=self.admission)],
            config=PlannerConfig(interval_s=cfg.planner_interval_s),
            registry=self._child(), flight=self.flight, clock=clock)
        self.recovery_registry = self._child()
        self.pools = PoolManager(
            self.models, pool_size=self.pool_size,
            spawner=self._pool_spawner, drainer=self._pool_drainer,
            config=PoolConfig(cold_start_deadline_s=90.0, poll_s=0.5,
                              retry_kick_s=2.0),
            policy=PoolPolicy(cfg.pool_policy, clock=clock),
            clock=clock, registry=self._child())
        if not cfg.pools:
            cfg.pools = {cfg.primary_model: 2}
        for model in sorted(cfg.pools):
            self.models.put(ModelCard(name=model, endpoint=f"dyn://sim.{model}"))

    def _child(self) -> MetricsRegistry:
        child = MetricsRegistry()
        self.registry.attach(child)
        return child

    # ------------------------------------------------------------------
    # fleet state views
    # ------------------------------------------------------------------

    def live_workers(self, model: Optional[str] = None) -> List[SimWorker]:
        return [
            w for _, w in sorted(self.workers.items())
            if not w.halted and (model is None or w.model == model)
        ]

    def replica_map(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for w in self.live_workers():
            out[w.model] = out.get(w.model, 0) + 1
        return out

    def planner_replicas(self) -> Dict[str, int]:
        n = len(self.live_workers(self.cfg.primary_model))
        return {"decode": n + self._provisioning.get(
            self.cfg.primary_model, 0)}

    def pool_size(self, model: str) -> int:
        return len(self.live_workers(model))

    def record_event(self, kind: str, **data) -> None:
        self.events.append({"t": self.clock(), "kind": kind, **data})

    def _fleet_signals(self) -> Dict[str, float]:
        live = [w for w in self.live_workers() if not w.wedged]
        total = sum(w.spec.slots for w in live)
        active = sum(len(w.active) + len(w.prefilling) for w in live)
        waiting = sum(len(w.pending) for w in live)
        kv_total = sum(w.spec.kv_blocks for w in live)
        kv_active = sum(w.used_blocks for w in live)
        waits = [w.mean_queue_wait_s() for w in live]
        trips = sum(1 for w in self.workers.values() if w.tripped)
        return {
            "decode.slot_busy_ratio": active / total if total else 0.0,
            "decode.waiting": float(waiting),
            "kv.usage_ratio": kv_active / kv_total if kv_total else 0.0,
            "prefill.queue_depth": float(waiting),
            "prefill.queue_wait_s": (sum(waits) / len(waits)
                                     if waits else 0.0),
            "watchdog.trips": float(trips),
        }

    # ------------------------------------------------------------------
    # worker lifecycle
    # ------------------------------------------------------------------

    def _spawn_worker(self, model: str,
                      with_recovery: bool = True) -> SimWorker:
        seq = next(self._worker_seq)
        wid = f"{model}-w{seq}"
        # consecutively spawned workers share an ICI domain; a respawn
        # or scale-up lands in whatever pod its spawn index falls into
        pod = (f"pod-{seq // self.cfg.spec.pod_size}"
               if self.cfg.spec.pod_size > 0 else None)
        w = SimWorker(wid, model, self.cfg.spec, self.clock,
                      self.cold_store, pod=pod)
        self.workers[wid] = w
        w.start()
        self.ks.update_metrics(wid, w.metrics())
        if with_recovery:
            self.controllers[wid] = self._make_controller(w)
        return w

    def _make_controller(self, w: SimWorker) -> RecoveryController:
        wid = w.worker_id

        async def deregister() -> None:
            self.ks.remove_worker(wid)
            self.workers.pop(wid, None)

        async def respawner():
            await asyncio.sleep(self.cfg.spec.provision_delay_s)
            fresh = self._spawn_worker(w.model)
            self._respawned[wid] = fresh.worker_id
            return WorkerSchedAdapter(fresh)

        async def register() -> None:
            self.record_event(
                "respawn", worker=wid,
                replacement=self._respawned.get(wid, ""))

        return RecoveryController(
            engine_id=wid,
            scheduler=WorkerSchedAdapter(w),
            respawner=respawner,
            deregister=deregister,
            register=register,
            config=self.cfg.recovery,
            registry=self.recovery_registry,
            flight=self.flight,
        )

    def provision(self, model: str) -> None:
        self._provisioning[model] = self._provisioning.get(model, 0) + 1

        async def _provision() -> None:
            try:
                await asyncio.sleep(self.cfg.spec.provision_delay_s)
                self._spawn_worker(model)
            finally:
                self._provisioning[model] -= 1

        self._hold(asyncio.get_running_loop().create_task(
            _provision(), name=f"sim-provision-{model}"))

    def retire(self, model: str, count: int = 1) -> None:
        victims = [w for w in reversed(self.live_workers(model))
                   if not w.draining][:count]
        for w in victims:
            w.draining = True

            async def _retire(worker: SimWorker = w) -> None:
                while True:
                    # a draining worker never admits its queue; bounce
                    # queued requests back to the client for resubmit
                    while worker.pending:
                        worker.pending.popleft().fail("drained")
                    if not (worker.active or worker.prefilling):
                        break
                    await asyncio.sleep(0.5)
                self.ks.remove_worker(worker.worker_id)
                self.workers.pop(worker.worker_id, None)
                await worker.halt()

            self._hold(asyncio.get_running_loop().create_task(
                _retire(), name=f"sim-retire-{w.worker_id}"))

    def _hold(self, task: asyncio.Task) -> None:
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _pool_spawner(self, card: ModelCard) -> None:
        self.record_event("cold_start", model=card.name)
        await asyncio.sleep(self.cfg.spec.provision_delay_s)
        self._spawn_worker(card.name)

    async def _pool_drainer(self, model: str) -> None:
        self.record_event("scale_to_zero", model=model)
        self.retire(model, count=len(self.live_workers(model)))

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------

    def _overlap(self, req: Request) -> OverlapScores:
        scores: Dict[str, int] = {}
        cold: Dict[str, int] = {}
        live = self.live_workers(req.model)
        hashes = (live[0].prefix_hashes(req) if live else [])
        if not hashes:
            return OverlapScores()
        n = len(hashes)
        cold_run_at: Dict[int, int] = {}
        for w in live:
            run = w.cached_run(hashes)
            if run:
                scores[w.worker_id] = run
            # the cold-tier run past a given hot-run length is the same
            # for every worker; scan each start index once
            extra = cold_run_at.get(run)
            if extra is None:
                extra = 0
                i = run
                while i < n and hashes[i] in self.cold_store:
                    extra += 1
                    i += 1
                cold_run_at[run] = extra
            if extra:
                cold[w.worker_id] = extra
        return OverlapScores(scores=scores, cold_scores=cold)

    # ------------------------------------------------------------------
    # request lifecycle
    # ------------------------------------------------------------------

    def _dispatch(self, req: Request) -> None:
        self._serve_tasks.append(asyncio.get_running_loop().create_task(
            self._serve(req), name=f"sim-req-{req.request_id}"))

    async def _serve(self, req: Request) -> None:
        rec = {
            "id": req.request_id, "arrival_s": req.arrival_s,
            "model": req.model, "tenant": req.tenant,
            "priority": req.priority, "isl": req.isl, "osl": req.osl,
            "outcome": "failed", "attempts": 0, "resubmits": 0,
        }
        self.pools.note_request(req.model)
        try:
            self.quotas.admit(req.tenant, req.request_id)
            await self.admission.acquire(req.priority, req.request_id)
        except AdmissionRejected as e:
            rec["outcome"] = e.outcome
            self._finish(rec)
            return
        try:
            if self.pool_size(req.model) <= 0:
                await self.pools.await_capacity(req.model)
            await self._serve_admitted(req, rec)
        except ColdStartTimeout:
            rec["outcome"] = "cold_start_timeout"
        finally:
            self.admission.release()
            self._finish(rec)

    async def _serve_admitted(self, req: Request, rec: dict) -> None:
        for attempt in range(self.cfg.max_attempts):
            rec["attempts"] = attempt + 1
            try:
                decision = self.ks.schedule(
                    req.isl, self._overlap(req),
                    pool={w.worker_id
                          for w in self.live_workers(req.model)})
            except AllWorkersBusy:
                if self.pool_size(req.model) <= 0:
                    # recovery or scale-down emptied the pool; lean on
                    # the pool manager's demand-driven cold start
                    # (ColdStartTimeout propagates to _serve)
                    self.pools.note_request(req.model)
                    await self.pools.await_capacity(req.model)
                else:
                    await asyncio.sleep(0.5 * (attempt + 1))
                continue
            worker = self.workers.get(decision.worker_id)
            if worker is None or worker.halted or worker.draining:
                await asyncio.sleep(0.1)
                continue
            sr = SimRequest(req, arrival_t=self.clock())
            worker.enqueue(sr, decision)
            if sr.pulled_blocks:
                # negotiate the pull's payload backend the way the real
                # transfer plane does (docs/transfer_plane.md): same
                # pod → the collective plane; anything else → tcp/DCN
                src = self.workers.get(decision.best_prefix_worker)
                if (src is not None and worker.pod is not None
                        and src.pod == worker.pod):
                    sr.pull_backend = "ici"
            await sr.done.wait()
            if sr.outcome == "completed":
                rec.update(
                    outcome="completed",
                    worker=worker.worker_id,
                    end_s=self.clock(),
                    ttft_s=sr.ttft_s,
                    itl_max_s=sr.itl_max_s,
                    tokens=req.osl,
                    prefix_hit_tokens=sr.prefix_hit_tokens,
                    pulled_blocks=sr.pulled_blocks,
                    pull_backend=(sr.pull_backend
                                  if sr.pulled_blocks else None),
                    pull_transfer_s=sr.pull_transfer_s,
                    cold_blocks=sr.cold_blocks,
                    slo_met=self.slo.observe(
                        sr.ttft_s, sr.itl_max_s, req.osl),
                )
                self.quotas.charge_tokens(req.tenant, req.osl)
                self.metrics.tokens.inc(req.osl, phase="decode")
                return
            # drained out from under us (wedge / scale-down): resubmit,
            # the way a client retries a 502
            rec["resubmits"] += 1
            self.resubmits += 1
            self.metrics.retries.inc()
            await asyncio.sleep(0.2)

    def _finish(self, rec: dict) -> None:
        if rec.get("_recorded"):
            return
        rec["_recorded"] = True
        self.metrics.requests.inc(
            outcome=rec["outcome"], priority=str(rec["priority"]))
        self.records.append(rec)

    # ------------------------------------------------------------------
    # background loops
    # ------------------------------------------------------------------

    async def _scrape_loop(self) -> None:
        cycle = 0
        while self.running:
            await asyncio.sleep(self.cfg.scrape_interval_s)
            cycle += 1
            now = self.clock()
            for wid, w in sorted(self.workers.items()):
                if w.halted or w.wedged:
                    continue  # a wedged endpoint stops answering scrapes
                self.ks.update_metrics(wid, w.metrics())
            # the sim watchdog: heartbeat-staleness trip into the REAL
            # recovery controller
            for wid, w in sorted(self.workers.items()):
                if w.halted or w.tripped or w.draining:
                    continue
                busy = bool(w.active or w.prefilling or w.pending)
                if (busy and now - w.last_progress_t
                        > self.cfg.watchdog_stall_s):
                    w.tripped = True
                    self.metrics.trips.inc()
                    self.record_event("watchdog_trip", worker=wid)
                    ctrl = self.controllers.get(wid)
                    if ctrl is not None:
                        ctrl.on_trip({"reason": "decode_stall"})
            live = self.live_workers()
            kv_total = sum(w.spec.kv_blocks for w in live)
            kv_active = sum(w.used_blocks for w in live)
            usage = kv_active / kv_total if kv_total else 0.0
            if cycle % 5 == 0:
                self.kv_series.append((now, usage))
                self.replica_series.append((now, len(live)))
            self.metrics.kv_usage.set(usage)
            if cycle % self.cfg.pool_step_every == 0:
                await self.pools.step()

    async def _chaos_loop(self) -> None:
        for ev in sorted(self.cfg.chaos, key=lambda e: e.at_s):
            delay = ev.at_s - self.clock()
            if delay > 0:
                await asyncio.sleep(delay)
            targets = [w for w in self.live_workers(self.cfg.primary_model)
                       if not w.wedged and not w.draining]
            if not targets:
                continue
            target = targets[ev.worker_index % len(targets)]
            faults.arm(ev.site, "once")
            target.fault_site = ev.site
            target._work.set()
            self.metrics.chaos.inc(site=ev.site)
            self.record_event("chaos", site=ev.site,
                              worker=target.worker_id)

    # ------------------------------------------------------------------
    # run
    # ------------------------------------------------------------------

    async def run(self, requests: List[Request]) -> None:
        self.running = True
        for model in sorted(self.cfg.pools):
            for _ in range(self.cfg.pools[model]):
                self._spawn_worker(model)
        scrape = asyncio.get_running_loop().create_task(
            self._scrape_loop(), name="sim-scrape")
        self._hold(scrape)
        chaos_task = None
        if self.cfg.chaos:
            chaos_task = asyncio.get_running_loop().create_task(
                self._chaos_loop(), name="sim-chaos")
            self._hold(chaos_task)
        self.planner.start()
        try:
            # one call_at timer per arrival (instead of a dispatcher
            # coroutine sleeping per request) — same dispatch instants,
            # a third of the event-loop handles
            loop = asyncio.get_running_loop()
            start_t = self.clock()
            last_at = start_t
            for req in sorted(requests,
                              key=lambda r: (r.arrival_s, r.request_id)):
                at = max(req.arrival_s, start_t)
                last_at = max(last_at, at)
                loop.call_at(at, self._dispatch, req)
            if requests:
                # the epsilon orders this barrier after every dispatch
                # timer at last_at regardless of heap tie-breaks
                await asyncio.sleep(last_at - self.clock() + 1e-6)
            if self._serve_tasks:
                await asyncio.gather(*self._serve_tasks,
                                     return_exceptions=False)
            # let in-flight recoveries finish their respawn ladders
            for ctrl in list(self.controllers.values()):
                t = ctrl._recover_task
                if t is not None and not t.done():
                    await t
        finally:
            self.running = False
            self.planner.stop()
            scrape.cancel()
            if chaos_task is not None:
                chaos_task.cancel()
            for t in list(self._tasks):
                t.cancel()
            await asyncio.gather(*self._tasks, return_exceptions=True)
            for w in list(self.workers.values()):
                await w.halt()
            await self.pools.stop()
            n = len([r for r in self.records
                     if r["outcome"] == "completed" and r.get("slo_met")])
            d = len([r for r in self.records
                     if r["outcome"] == "completed"])
            self.metrics.attainment.set(n / d if d else 0.0)
            for summary in self.recovery_summaries():
                self.metrics.recoveries.inc(reason=summary["reason"])

    def recovery_summaries(self) -> List[dict]:
        """Recovery-ladder outcomes with the wall-clock duration field
        stripped — everything that enters a report must be virtual."""
        out = []
        for wid in sorted(self.controllers):
            for s in self.controllers[wid].recoveries:
                out.append({
                    "worker": wid,
                    "reason": s.get("reason"),
                    "hard": s.get("hard"),
                    "finished": s.get("finished"),
                    "migrated": s.get("migrated"),
                    "failed": s.get("failed"),
                    "respawned": s.get("respawned"),
                })
        return out

    def flight_kinds(self) -> List[str]:
        """Chronological flight-event kind sequence from the private
        ring (timestamps are wall-clock and stay out of reports)."""
        return [e.get("kind", "") for e in self.flight.snapshot()]
