"""Report anatomy for a finished sim run.

Everything here is derived from virtual-time state only — record
timestamps, event timelines, and windows are all in virtual seconds, so
``json.dumps(report, sort_keys=True)`` of two same-seed runs compares
byte-identical. Floats are rounded to 6 places to keep accumulation
order from leaking into the JSON.

The headline artifact is the capacity curve: completed windows bucketed
by offered QPS, each bucket's SLO attainment, and ``capacity_qps`` —
the highest offered load the fleet shape sustained at or above the
scenario's attainment floor.
"""

from __future__ import annotations

import math
from typing import Dict, List

WINDOW_S = 60.0


def _r(x) -> float:
    return round(float(x), 6)


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(math.ceil(q * len(sorted_vals))) - 1))
    return sorted_vals[idx]


def build_report(scenario: str, seed: int, fleet, slo_floor: float,
                 duration_s: float) -> dict:
    records = fleet.records
    completed = [r for r in records if r["outcome"] == "completed"]
    shed_outcomes = ("shed", "queue_full", "timeout", "draining",
                     "quota", "cold_start_timeout")

    # -- per-window series + capacity curve -------------------------------
    n_windows = max(1, int(math.ceil(duration_s / WINDOW_S)))
    windows = []
    for wi in range(n_windows):
        lo, hi = wi * WINDOW_S, (wi + 1) * WINDOW_S
        offered = [r for r in records if lo <= r["arrival_s"] < hi]
        done = [r for r in offered if r["outcome"] == "completed"]
        met = [r for r in done if r.get("slo_met")]
        shed = [r for r in offered if r["outcome"] in shed_outcomes]
        windows.append({
            "window_s": [_r(lo), _r(hi)],
            "offered_qps": _r(len(offered) / WINDOW_S),
            "completed": len(done),
            "shed": len(shed),
            "slo_attainment": _r(len(met) / len(done)) if done else None,
        })
    replicas_by_window: Dict[int, int] = {}
    for t, n in fleet.replica_series:
        replicas_by_window[int(t // WINDOW_S)] = n
    for wi, w in enumerate(windows):
        w["replicas"] = replicas_by_window.get(wi)

    curve: Dict[float, List[dict]] = {}
    for w in windows:
        if w["slo_attainment"] is None:
            continue
        qps = _r(round(w["offered_qps"] * 2) / 2)   # 0.5-QPS buckets
        curve.setdefault(qps, []).append(w)
    capacity_curve = []
    for qps in sorted(curve):
        ws = curve[qps]
        att = [w["slo_attainment"] for w in ws]
        capacity_curve.append({
            "offered_qps": qps,
            "windows": len(ws),
            "slo_attainment": _r(sum(att) / len(att)),
            "shed_rate": _r(
                sum(w["shed"] for w in ws)
                / max(1, sum(w["shed"] + w["completed"] for w in ws))),
        })
    sustained = [p["offered_qps"] for p in capacity_curve
                 if p["slo_attainment"] >= slo_floor]
    capacity_qps = _r(max(sustained)) if sustained else 0.0

    # -- shed attribution --------------------------------------------------
    def _rates(key: str) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for r in records:
            k = str(r[key])
            row = out.setdefault(
                k, {"offered": 0, "completed": 0, "shed": 0})
            row["offered"] += 1
            if r["outcome"] == "completed":
                row["completed"] += 1
            elif r["outcome"] in shed_outcomes:
                row["shed"] += 1
        for row in out.values():
            row["shed_rate"] = _r(row["shed"] / row["offered"])
        return dict(sorted(out.items()))

    # -- latency + totals --------------------------------------------------
    ttfts = sorted(r["ttft_s"] for r in completed if "ttft_s" in r)
    outcome_totals: Dict[str, int] = {}
    for r in records:
        outcome_totals[r["outcome"]] = outcome_totals.get(
            r["outcome"], 0) + 1
    met = [r for r in completed if r.get("slo_met")]

    report = {
        "scenario": scenario,
        "seed": seed,
        "slo_floor": _r(slo_floor),
        "duration_s": _r(duration_s),
        "totals": {
            "offered": len(records),
            "outcomes": dict(sorted(outcome_totals.items())),
            "slo_attainment": (_r(len(met) / len(completed))
                               if completed else 0.0),
            "resubmits": fleet.resubmits,
            "ttft_p50_s": _r(_percentile(ttfts, 0.50)),
            "ttft_p95_s": _r(_percentile(ttfts, 0.95)),
            "prefix_hit_tokens": sum(
                r.get("prefix_hit_tokens", 0) for r in completed),
            "pulled_blocks": sum(
                r.get("pulled_blocks", 0) for r in completed),
            # backend split of the peer pulls (docs/transfer_plane.md):
            # intra-pod pulls ride ici, cross-pod pulls pay the DCN rate
            "pulled_blocks_ici": sum(
                r.get("pulled_blocks", 0) for r in completed
                if r.get("pull_backend") == "ici"),
            "pull_transfer_s_ici": _r(sum(
                r.get("pull_transfer_s", 0.0) for r in completed
                if r.get("pull_backend") == "ici")),
            "pull_transfer_s_tcp": _r(sum(
                r.get("pull_transfer_s", 0.0) for r in completed
                if r.get("pull_backend") == "tcp")),
            "cold_blocks": sum(
                r.get("cold_blocks", 0) for r in completed),
        },
        "capacity": {
            "floor": _r(slo_floor),
            "capacity_qps": capacity_qps,
            "curve": capacity_curve,
            "meets_floor": bool(
                completed
                and (len(met) / len(completed)) >= slo_floor),
        },
        "windows": windows,
        "shed_by_tenant": _rates("tenant"),
        "shed_by_priority": _rates("priority"),
        "timeline": [
            {k: (_r(v) if isinstance(v, float) else v)
             for k, v in ev.items()}
            for ev in fleet.events
        ],
        "kv_pressure": {
            "series": [[_r(t), _r(u)] for t, u in fleet.kv_series],
            "peak": _r(max((u for _, u in fleet.kv_series),
                           default=0.0)),
        },
        "recoveries": fleet.recovery_summaries(),
        "flight_kinds": fleet.flight_kinds(),
    }
    return report


def render_table(report: dict) -> str:
    """The human half of the report: a fixed-width text summary."""
    lines = []
    t = report["totals"]
    cap = report["capacity"]
    lines.append(
        f"scenario={report['scenario']} seed={report['seed']} "
        f"duration={report['duration_s']:.0f}s")
    lines.append(
        f"offered={t['offered']} "
        f"completed={t['outcomes'].get('completed', 0)} "
        f"attainment={t['slo_attainment']:.3f} "
        f"(floor {report['slo_floor']:.2f}) "
        f"capacity={cap['capacity_qps']:.2f} qps")
    lines.append(
        f"ttft p50={t['ttft_p50_s'] * 1000:.0f}ms "
        f"p95={t['ttft_p95_s'] * 1000:.0f}ms "
        f"resubmits={t['resubmits']}")
    lines.append("")
    lines.append(f"{'qps':>6} {'windows':>7} {'attain':>7} {'shed%':>6}")
    for p in cap["curve"]:
        lines.append(
            f"{p['offered_qps']:>6.2f} {p['windows']:>7d} "
            f"{p['slo_attainment']:>7.3f} "
            f"{100.0 * p['shed_rate']:>5.1f}%")
    lines.append("")
    lines.append(f"{'tenant':<14} {'offered':>7} {'shed':>5} {'rate':>6}")
    for tenant, row in report["shed_by_tenant"].items():
        lines.append(
            f"{tenant:<14} {row['offered']:>7d} {row['shed']:>5d} "
            f"{100.0 * row['shed_rate']:>5.1f}%")
    lines.append(f"{'priority':<14} {'offered':>7} {'shed':>5} {'rate':>6}")
    for prio, row in report["shed_by_priority"].items():
        lines.append(
            f"{prio:<14} {row['offered']:>7d} {row['shed']:>5d} "
            f"{100.0 * row['shed_rate']:>5.1f}%")
    events = report["timeline"]
    if events:
        lines.append("")
        lines.append("timeline:")
        for ev in events:
            extra = " ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("t", "kind"))
            lines.append(f"  t={ev['t']:>8.1f}s {ev['kind']:<14} {extra}")
    if report["recoveries"]:
        lines.append("recoveries:")
        for s in report["recoveries"]:
            lines.append(
                f"  {s['worker']}: reason={s['reason']} "
                f"respawned={s['respawned']} failed={s['failed']}")
    return "\n".join(lines)
