"""Simulated decode workers for the fleet simulator.

A :class:`SimWorker` is the only simulated component in the harness —
everything above it (admission, planner, pools, recovery, KV routing)
is the real control plane. Its service times come straight from the
measured device-time byte model: each decode burst costs
``DeviceTimeTracker.decode_read_bytes / peak_bytes_per_s`` virtual
seconds, a long prompt costs the PR 14 sequence-parallel ladder's
``sp_prefill_read_bytes``, and every burst is fed back through the real
tracker's ``observe()`` so the sim's roofline numbers are computed by
the same code as a live engine's.

Chaos uses the DYN_FAULT vocabulary: a worker armed with a fault site
consults ``faults.fire(site)`` at its burst seam (the real scheduler's
``decode_burst_hang`` placement) and wedges — no more progress, no more
heartbeats — until the real RecoveryController seizes it.
"""

from __future__ import annotations

import asyncio
import collections
import dataclasses
import math
from typing import Deque, List, Optional

from ..kv_router.protocols import ForwardPassMetrics
from ..telemetry.device_time import DeviceTimeTracker
from ..utils import faults
from .workload import Request

# (model, prefix_group, n_blocks) → block-hash list; the strings are
# pure functions of the key, so sharing across workers/runs is safe
_HASH_CACHE: dict = {}


@dataclasses.dataclass
class WorkerSpec:
    """Fleet-shape knobs: one worker's capacity + the byte model."""

    slots: int = 8
    kv_blocks: int = 2048
    block_size: int = 16
    # llama-8B-bf16-ish defaults; scenarios override for other shapes
    param_bytes: float = 16e9
    kv_bytes_per_token: float = 131072.0
    hbm_gbps: Optional[float] = None      # None → DYN_HBM_GBPS / chip default
    burst_steps: int = 64                 # decode tokens per dispatch burst
    # PR 14 sequence-parallel prefill: prompts past the threshold run the
    # chunked ladder and are costed by sp_prefill_read_bytes
    sp_chunk_tokens: int = 8192
    sp_threshold_tokens: int = 16384
    # KV fabric modeling: pulling a peer's committed prefix vs cold-tier
    # rehydration, in GB/s of transfer bandwidth. The backend split
    # mirrors the unified transfer plane (docs/transfer_plane.md):
    # peer_pull_gbps is the tcp/DCN rate every pair supports,
    # ici_pull_gbps the device-to-device collective rate a pull rides
    # when both workers share a pod. pod_size groups consecutively
    # spawned workers into ICI domains (0 = no pods, everything DCN).
    peer_pull_gbps: float = 40.0
    ici_pull_gbps: float = 400.0
    pod_size: int = 0
    cold_pull_gbps: float = 10.0
    provision_delay_s: float = 20.0       # scale-up / respawn lead time


class _Ctx:
    """Just enough request context for the recovery ladder's _fail path."""

    __slots__ = ("trace_id", "is_stopped", "stages")

    def __init__(self, trace_id: str) -> None:
        self.trace_id = trace_id
        self.is_stopped = False
        self.stages: List[str] = []

    def add_stage(self, name: str) -> None:
        self.stages.append(name)


class _FailureSink:
    """Stands in for an engine request's out_queue: the recovery
    controller's ``_fail`` pushes a terminal ERROR frame here, which the
    fleet observes as "resubmit me"."""

    __slots__ = ("sim_request",)

    def __init__(self, sim_request: "SimRequest") -> None:
        self.sim_request = sim_request

    def put_nowait(self, item) -> None:
        if item is None:
            return
        self.sim_request.fail("drained")


class SimRequest:
    """Runtime state for one offered request's attempt on a worker.

    Shaped so RecoveryController.extract_requests can treat it as an
    engine request: ``request_id`` / ``ctx`` / ``block_ids`` /
    ``finish`` / ``out_queue`` are the fields the real ladder touches.
    """

    def __init__(self, req: Request, arrival_t: float) -> None:
        self.req = req
        self.request_id = req.request_id
        self.arrival_t = arrival_t
        self.ctx = _Ctx(trace_id=req.request_id)
        self.block_ids: List[int] = []
        self.finish = None
        self.out_queue = _FailureSink(self)
        self.done = asyncio.Event()
        self.outcome: Optional[str] = None   # completed | drained
        self.ttft_s: Optional[float] = None
        self.itl_max_s: Optional[float] = None
        self.decoded = 0
        self.last_token_t: Optional[float] = None
        # routing telemetry carried over from the SchedulingDecision
        self.prefix_hit_tokens = 0
        self.pulled_blocks = 0
        self.cold_blocks = 0
        # negotiated payload path for the peer pull (the fleet flips
        # this to "ici" when puller and source share a pod) + the
        # transfer seconds the plan actually charged it
        self.pull_backend = "tcp"
        self.pull_transfer_s = 0.0
        self.enqueue_t: Optional[float] = None

    def fail(self, reason: str) -> None:
        if self.outcome is None:
            self.outcome = reason
        self.done.set()

    def complete(self) -> None:
        if self.outcome is None:
            self.outcome = "completed"
        self.done.set()


class SimWorker:
    """One simulated engine: slot + paged-KV bookkeeping, an LRU prefix
    cache spilling to the fleet's shared cold tier, and a decode-burst
    loop timed by the byte model."""

    def __init__(
        self,
        worker_id: str,
        model: str,
        spec: WorkerSpec,
        clock,
        cold_store: Optional[set] = None,
        pod: Optional[str] = None,
    ) -> None:
        self.worker_id = worker_id
        self.model = model
        self.spec = spec
        self.pod = pod
        self.clock = clock
        self.cold_store = cold_store if cold_store is not None else set()
        self.tracker = DeviceTimeTracker(
            param_bytes=spec.param_bytes,
            kv_bytes_per_token=spec.kv_bytes_per_token,
            hbm_gbps=spec.hbm_gbps,
            clock=clock,
        )
        self.active: List[SimRequest] = []
        self.prefilling: List[SimRequest] = []
        self.pending: Deque[SimRequest] = collections.deque()
        self.used_blocks = 0
        # prefix cache: (model, group) → hot block count, LRU order;
        # evictions spill to the shared cold tier (the kv/cold_tier.py
        # content-addressed store, modeled as a block-hash set)
        self.cached: "collections.OrderedDict[tuple, int]" = (
            collections.OrderedDict()
        )
        self.cached_blocks_total = 0
        self.draining = False
        self.wedged = False
        self.halted = False
        self.tripped = False
        self.fault_site: Optional[str] = None
        self.last_progress_t = clock()
        self.prefill_tokens = 0
        self.decode_tokens = 0
        self.queue_wait_samples: Deque[float] = collections.deque(maxlen=64)
        self._work = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._aux_tasks: set = set()

    # ------------------------------------------------------------------
    # fleet-facing API
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self._run(), name=f"sim-worker-{self.worker_id}")

    def enqueue(self, sr: SimRequest, decision=None) -> None:
        sr.enqueue_t = self.clock()
        if decision is not None:
            sr.prefix_hit_tokens = decision.prefix_hit_tokens
            sr.cold_blocks = decision.cold_blocks
            if (decision.best_prefix_worker
                    and decision.best_prefix_worker != self.worker_id):
                sr.pulled_blocks = max(
                    0, decision.best_prefix_blocks - decision.matched_blocks)
        self.pending.append(sr)
        self._work.set()

    def metrics(self) -> ForwardPassMetrics:
        total = self.spec.kv_blocks or 1
        return ForwardPassMetrics(
            request_active_slots=len(self.active) + len(self.prefilling),
            request_total_slots=self.spec.slots,
            kv_active_blocks=self.used_blocks,
            kv_total_blocks=self.spec.kv_blocks,
            num_requests_waiting=len(self.pending),
            gpu_cache_usage_perc=min(1.0, self.used_blocks / total),
            gpu_prefix_cache_hit_rate=0.0,
            draining=self.draining,
        )

    def mean_queue_wait_s(self) -> float:
        if not self.queue_wait_samples:
            return 0.0
        return sum(self.queue_wait_samples) / len(self.queue_wait_samples)

    async def halt(self) -> None:
        """Stop the loop for good (seize / scale-down teardown)."""
        self.halted = True
        tasks = [t for t in [self._task, *self._aux_tasks] if t is not None]
        self._task = None
        self._aux_tasks.clear()
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    # ------------------------------------------------------------------
    # the engine loop
    # ------------------------------------------------------------------

    def _blocks_for(self, tokens: int) -> int:
        return max(1, math.ceil(tokens / self.spec.block_size))

    def prefix_hashes(self, req: Request) -> List[str]:
        if not req.prefix_group or req.prefix_tokens <= 0:
            return []
        n = req.prefix_tokens // self.spec.block_size
        key = (req.model, req.prefix_group, n)
        cached = _HASH_CACHE.get(key)
        if cached is None:
            cached = [f"{req.model}/{req.prefix_group}:{i}"
                      for i in range(n)]
            _HASH_CACHE[key] = cached
        return cached

    def _cache_prefix(self, req: Request) -> None:
        if not req.prefix_group or req.prefix_tokens <= 0:
            return
        n = req.prefix_tokens // self.spec.block_size
        key = (req.model, req.prefix_group)
        prev = self.cached.get(key, 0)
        self.cached[key] = max(prev, n)
        self.cached.move_to_end(key)
        self.cached_blocks_total += max(0, n - prev)
        # the cache lives in the block budget left over after pinned
        # request KV; evictions spill to the shared cold tier whole
        # prefix families at a time (they were committed together)
        budget = max(0, self.spec.kv_blocks - self.used_blocks)
        while self.cached_blocks_total > budget and self.cached:
            (model, group), blocks = self.cached.popitem(last=False)
            self.cached_blocks_total -= blocks
            for i in range(blocks):
                self.cold_store.add(f"{model}/{group}:{i}")

    def cached_run(self, hashes: List[str]) -> int:
        """Consecutive leading blocks of ``hashes`` held hot — the
        overlap-score contract the KvScheduler ranks on."""
        if not hashes:
            return 0
        # hashes are "<model>/<group>:<i>" for one family; group-level
        # bookkeeping answers the run length in O(1)
        key_s, _, _ = hashes[0].rpartition(":")
        model, _, group = key_s.partition("/")
        return min(self.cached.get((model, group), 0), len(hashes))

    async def _run(self) -> None:
        spec = self.spec
        while not self.halted:
            if self.wedged:
                # a wedged engine makes no progress and sends no
                # heartbeats; the watchdog trip → recovery seize is the
                # only way out
                self._work.clear()
                await self._work.wait()
                continue
            self._admit()
            if not self.active and not self.prefilling:
                # the loop is alive — only a wedge freezes this stamp,
                # so the fleet watchdog trips wedges, not idle waits
                self.last_progress_t = self.clock()
                if self.pending:
                    # slot- or KV-starved: re-check after a beat
                    await asyncio.sleep(0.2)
                    continue
                self.tracker.idle()
                self._work.clear()
                await self._work.wait()
                continue
            if self.fault_site and faults.fire(self.fault_site):
                self.wedged = True
                continue
            if self.prefilling:
                # prefill-prioritized interleave: the chip runs the
                # queued prefill programs back-to-back before the next
                # burst, so one combined sleep with per-program
                # timestamps is timing-identical to sleeping per
                # program — then the loop re-checks the batch
                await self._prefill_batch(list(self.prefilling))
                continue
            # ---- one decode burst over the whole batch ----
            k = spec.burst_steps
            ctx_sum = sum(sr.req.isl + sr.decoded for sr in self.active)
            read_bytes = self.tracker.decode_read_bytes(k, ctx_sum)
            busy = read_bytes / self.tracker.peak_bytes_per_s
            t0 = self.clock()
            await asyncio.sleep(busy)
            now = self.clock()
            self.tracker.observe(
                "decode_burst", "decode", t0, now,
                read_bytes=read_bytes, tokens=k * len(self.active))
            self.last_progress_t = now
            per_step = busy / k
            finished: List[SimRequest] = []
            for sr in self.active:
                steps = min(k, sr.req.osl - sr.decoded)
                sr.decoded += steps
                self.decode_tokens += steps
                if sr.last_token_t is not None and steps > 0:
                    # tokens emit at per-step cadence inside the burst;
                    # the first one also carries any inter-burst wait
                    # (prefill interleave, queueing) since the row's
                    # previous token
                    gap = max(t0 + per_step - sr.last_token_t, per_step)
                    if sr.itl_max_s is None or gap > sr.itl_max_s:
                        sr.itl_max_s = gap
                if steps > 0:
                    sr.last_token_t = t0 + steps * per_step
                if sr.decoded >= sr.req.osl:
                    finished.append(sr)
            for sr in finished:
                self.active.remove(sr)
                self.used_blocks = max(
                    0, self.used_blocks - len(sr.block_ids))
                sr.block_ids = []
                sr.complete()

    def _admit(self) -> None:
        """Move pending requests into the prefill stage while slot and
        KV budgets allow."""
        while (self.pending and not self.draining
               and len(self.active) + len(self.prefilling)
               < self.spec.slots):
            sr = self.pending[0]
            need = self._blocks_for(sr.req.isl + sr.req.osl)
            if self.used_blocks + need > self.spec.kv_blocks:
                break  # KV-starved; wait for a completion
            self.pending.popleft()
            sr.block_ids = list(range(need))
            self.used_blocks += need
            if sr.enqueue_t is not None:
                self.queue_wait_samples.append(self.clock() - sr.enqueue_t)
            self.prefilling.append(sr)

    def _prefill_plan(self, sr: SimRequest) -> tuple:
        """Cost one request's prefill: (transfer_s, busy_s, read_bytes,
        program, new_tokens) under the byte model."""
        spec = self.spec
        req = sr.req
        transfer_s = 0.0
        block_bytes = spec.block_size * spec.kv_bytes_per_token
        if sr.pulled_blocks:
            gbps = (spec.ici_pull_gbps if sr.pull_backend == "ici"
                    else spec.peer_pull_gbps)
            sr.pull_transfer_s = (sr.pulled_blocks * block_bytes
                                  / (gbps * 1e9))
            transfer_s += sr.pull_transfer_s
        if sr.cold_blocks:
            transfer_s += (sr.cold_blocks * block_bytes
                           / (spec.cold_pull_gbps * 1e9))
        reused = (sr.prefix_hit_tokens
                  + (sr.pulled_blocks + sr.cold_blocks) * spec.block_size)
        new_tokens = max(spec.block_size, req.isl - reused)
        if new_tokens > spec.sp_threshold_tokens:
            chunks = math.ceil(new_tokens / spec.sp_chunk_tokens)
            read_bytes = self.tracker.sp_prefill_read_bytes(
                chunks, new_tokens)
            program = "prefill_sp"
        else:
            read_bytes = (spec.param_bytes
                          + new_tokens * spec.kv_bytes_per_token)
            program = "prefill"
        busy = read_bytes / self.tracker.peak_bytes_per_s
        return transfer_s, busy, read_bytes, program, new_tokens

    async def _prefill_batch(self, batch: List[SimRequest]) -> None:
        t0 = self.clock()
        plans = [(sr, *self._prefill_plan(sr)) for sr in batch]
        total = sum(transfer_s + busy
                    for _, transfer_s, busy, _, _, _ in plans)
        # virtual sleeps wake exactly at their deadline, so the
        # arithmetic per-program spans below land on the same instants
        # the per-program sleeps would have
        await asyncio.sleep(total)
        if self.halted:
            return  # seized while prefilling
        t = t0
        for sr, transfer_s, busy, read_bytes, program, new_tokens in plans:
            start = t
            t += transfer_s + busy
            if sr.outcome is not None:
                continue  # drained while prefilling
            if sr in self.prefilling:
                self.prefilling.remove(sr)
            else:
                continue  # extracted out from under the program
            self.tracker.observe(program, "prefill", start + transfer_s,
                                 t, read_bytes=read_bytes,
                                 tokens=new_tokens)
            self.prefill_tokens += new_tokens
            sr.ttft_s = t - sr.arrival_t
            sr.last_token_t = t
            sr.decoded = 1  # the prefill emits the first token
            self.decode_tokens += 1
            self._cache_prefix(sr.req)
            self.active.append(sr)
        self.last_progress_t = self.clock()
        self._work.set()


# ---------------------------------------------------------------------------
# recovery-ladder adapters
# ---------------------------------------------------------------------------


class _Allocator:
    __slots__ = ("worker",)

    def __init__(self, worker: SimWorker) -> None:
        self.worker = worker

    def free_blocks(self, block_ids: List[int]) -> None:
        self.worker.used_blocks = max(
            0, self.worker.used_blocks - len(block_ids))


class _SchedCfg:
    __slots__ = ("kv_block_size",)

    def __init__(self, kv_block_size: int) -> None:
        self.kv_block_size = kv_block_size


class WorkerSchedAdapter:
    """Presents one SimWorker as the scheduler surface the real
    RecoveryController drains: set_draining / slots / seize /
    extract_requests / allocator / config."""

    def __init__(self, worker: SimWorker) -> None:
        self.worker = worker
        self.allocator = _Allocator(worker)
        self.config = _SchedCfg(worker.spec.block_size)

    def set_draining(self, draining: bool = True) -> None:
        self.worker.draining = draining

    @property
    def slots(self) -> List[Optional[SimRequest]]:
        live = (list(self.worker.active) + list(self.worker.prefilling))
        return live or [None]

    async def seize(self, hard: bool = False,
                    timeout_s: float = 5.0) -> None:
        await self.worker.halt()

    def extract_requests(self) -> List[SimRequest]:
        w = self.worker
        out = list(w.active) + list(w.prefilling) + list(w.pending)
        w.active.clear()
        w.prefilling.clear()
        w.pending.clear()
        return out
