"""Workload side of the fleet simulator: synthetic generators + replay.

Every generator is a pure function of a seeded ``random.Random`` — the
same seed always yields the same arrival list, which is half of the
byte-identical-report determinism contract (the other half is the
virtual clock in sim/clock.py).

Replay loaders accept the repo's own telemetry artifacts: a
DYN_TRACE_JSONL sink (telemetry/tracing.py record shape) or an incident
bundle directory (telemetry/incidents.py — ``traces.json``). Traces
capture *arrival shape* exactly; token sizes ride along when the record
carries ``isl``/``osl`` keys and otherwise derive deterministically from
the request id (crc32, not the salted builtin ``hash``).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
import zlib
from typing import Callable, Dict, List, Optional

DEFAULT_MODEL = "sim-model"


@dataclasses.dataclass
class Request:
    """One offered request, in virtual seconds from scenario start."""

    arrival_s: float
    request_id: str
    model: str = DEFAULT_MODEL
    tenant: str = "default"
    priority: int = 1              # index into planner PRIORITY_CLASSES
    isl: int = 512                 # prompt tokens
    osl: int = 128                 # output tokens
    # shared-prefix family: requests with the same group share
    # ``prefix_tokens`` leading tokens (RAG system prompt / few-shot
    # header), which is what the KV fabric's peer-pull and cold-tier
    # modeling keys on
    prefix_group: Optional[str] = None
    prefix_tokens: int = 0


def _stable_u32(s: str) -> int:
    return zlib.crc32(s.encode("utf-8")) & 0xFFFFFFFF


def _poisson_arrivals(
    rng: random.Random,
    duration_s: float,
    rate_fn: Callable[[float], float],
    peak_rate: float,
) -> List[float]:
    """Nonhomogeneous Poisson arrivals by thinning."""
    out: List[float] = []
    t = 0.0
    peak_rate = max(peak_rate, 1e-9)
    while True:
        t += rng.expovariate(peak_rate)
        if t >= duration_s:
            return out
        if rng.random() < rate_fn(t) / peak_rate:
            out.append(t)


def _pick_priority(rng: random.Random) -> int:
    # 20% low / 60% normal / 20% high — enough low-class volume that a
    # shed episode visibly spares the top class
    r = rng.random()
    if r < 0.2:
        return 0
    if r < 0.8:
        return 1
    return 2


# ---------------------------------------------------------------------------
# synthetic generators
# ---------------------------------------------------------------------------


def diurnal(
    rng: random.Random,
    duration_s: float = 1800.0,
    base_qps: float = 1.0,
    peak_qps: float = 6.0,
    period_s: float = 1200.0,
    burst_factor: float = 2.0,
    burst_window: tuple = (0.5, 0.6),
    isl: int = 512,
    osl: int = 128,
    model: str = DEFAULT_MODEL,
) -> List[Request]:
    """Bursty diurnal traffic: a sinusoidal day with a flash burst."""

    def rate(t: float) -> float:
        r = base_qps + (peak_qps - base_qps) * 0.5 * (
            1.0 - math.cos(2.0 * math.pi * t / period_s)
        )
        if burst_window[0] * duration_s <= t < burst_window[1] * duration_s:
            r *= burst_factor
        return r

    arrivals = _poisson_arrivals(rng, duration_s, rate, peak_qps * burst_factor)
    out = []
    for i, t in enumerate(arrivals):
        out.append(Request(
            arrival_s=t,
            request_id=f"diurnal-{i}",
            model=model,
            priority=_pick_priority(rng),
            isl=max(16, int(rng.lognormvariate(math.log(isl), 0.5))),
            osl=max(8, int(rng.lognormvariate(math.log(osl), 0.4))),
        ))
    return out


def rag(
    rng: random.Random,
    duration_s: float = 900.0,
    qps: float = 4.0,
    n_groups: int = 6,
    prefix_tokens: int = 2048,
    suffix_tokens: int = 256,
    osl: int = 96,
    model: str = DEFAULT_MODEL,
) -> List[Request]:
    """Shared-prefix RAG traffic: a few hot few-shot headers dominate,
    exercising prefix-overlap routing, fabric peer-pull, and cold-tier
    rehydration once eviction kicks in."""
    arrivals = _poisson_arrivals(rng, duration_s, lambda t: qps, qps)
    # zipf-ish popularity over the prefix families
    weights = [1.0 / (g + 1) for g in range(n_groups)]
    total_w = sum(weights)
    out = []
    for i, t in enumerate(arrivals):
        r = rng.random() * total_w
        group = 0
        acc = 0.0
        for g, w in enumerate(weights):
            acc += w
            if r <= acc:
                group = g
                break
        out.append(Request(
            arrival_s=t,
            request_id=f"rag-{i}",
            model=model,
            priority=_pick_priority(rng),
            isl=prefix_tokens + max(16, int(rng.expovariate(1.0 / suffix_tokens))),
            osl=max(8, int(rng.lognormvariate(math.log(osl), 0.3))),
            prefix_group=f"ctx{group}",
            prefix_tokens=prefix_tokens,
        ))
    return out


def long_context(
    rng: random.Random,
    duration_s: float = 900.0,
    qps: float = 2.0,
    long_fraction: float = 0.08,
    long_isl: int = 131072,
    short_isl: int = 512,
    osl: int = 64,
    model: str = DEFAULT_MODEL,
) -> List[Request]:
    """Mostly short prompts with a long tail of 128k sequence-parallel
    prefills — the PR 14 SP byte model dominates the long requests."""
    arrivals = _poisson_arrivals(rng, duration_s, lambda t: qps, qps)
    out = []
    for i, t in enumerate(arrivals):
        is_long = rng.random() < long_fraction
        out.append(Request(
            arrival_s=t,
            request_id=f"lctx-{i}",
            model=model,
            priority=_pick_priority(rng),
            isl=(max(long_isl // 4, int(rng.uniform(0.25, 1.0) * long_isl))
                 if is_long
                 else max(16, int(rng.lognormvariate(math.log(short_isl), 0.5)))),
            osl=max(8, int(rng.lognormvariate(math.log(osl), 0.3))),
        ))
    return out


def tenant_spike(
    rng: random.Random,
    duration_s: float = 900.0,
    base_qps: float = 2.0,
    spike_qps: float = 15.0,
    spike_window: tuple = (0.35, 0.55),
    spike_tenant: str = "burst-tenant",
    isl: int = 384,
    osl: int = 96,
    model: str = DEFAULT_MODEL,
) -> List[Request]:
    """Steady multi-tenant baseline plus one tenant flooding far past
    its quota — the token-bucket 429 path, per-tenant shed attribution."""
    lo, hi = spike_window[0] * duration_s, spike_window[1] * duration_s
    base = _poisson_arrivals(rng, duration_s, lambda t: base_qps, base_qps)
    out = []
    for i, t in enumerate(base):
        out.append(Request(
            arrival_s=t,
            request_id=f"ten-b{i}",
            model=model,
            tenant=rng.choice(("acme", "globex")),
            priority=_pick_priority(rng),
            isl=max(16, int(rng.lognormvariate(math.log(isl), 0.4))),
            osl=max(8, int(rng.lognormvariate(math.log(osl), 0.3))),
        ))
    spike = _poisson_arrivals(
        rng, hi - lo, lambda t: spike_qps, spike_qps)
    for i, t in enumerate(spike):
        out.append(Request(
            arrival_s=lo + t,
            request_id=f"ten-s{i}",
            model=model,
            tenant=spike_tenant,
            priority=0,
            isl=max(16, int(rng.lognormvariate(math.log(isl), 0.4))),
            osl=max(8, int(rng.lognormvariate(math.log(osl), 0.3))),
        ))
    out.sort(key=lambda r: (r.arrival_s, r.request_id))
    return out


def chaos(
    rng: random.Random,
    duration_s: float = 900.0,
    qps: float = 3.0,
    isl: int = 384,
    osl: int = 96,
    model: str = DEFAULT_MODEL,
) -> List[Request]:
    """Steady load for the fault-injection scenario; the wedge schedule
    itself lives in the scenario config (DYN_FAULT vocabulary), not in
    the arrival process."""
    arrivals = _poisson_arrivals(rng, duration_s, lambda t: qps, qps)
    return [
        Request(
            arrival_s=t,
            request_id=f"chaos-{i}",
            model=model,
            priority=_pick_priority(rng),
            isl=max(16, int(rng.lognormvariate(math.log(isl), 0.4))),
            osl=max(8, int(rng.lognormvariate(math.log(osl), 0.3))),
        )
        for i, t in enumerate(arrivals)
    ]


# ---------------------------------------------------------------------------
# trace replay
# ---------------------------------------------------------------------------


def _request_from_trace(
    record: dict,
    t0: float,
    index: int,
    model: Optional[str],
    default_isl: int,
    default_osl: int,
) -> Request:
    rid = str(record.get("request_id") or f"replay-{index}")
    u = _stable_u32(rid)
    return Request(
        arrival_s=max(0.0, float(record.get("time", t0)) - t0),
        request_id=rid,
        model=model or str(record.get("model") or DEFAULT_MODEL),
        tenant=str(record.get("tenant") or "default"),
        priority=int(record.get("priority", 1)),
        # honor explicit sizes; otherwise derive a stable spread from
        # the request id so replay is seed-independent reproducible
        isl=int(record.get("isl") or (default_isl // 2 + u % default_isl)),
        osl=int(record.get("osl") or (default_osl // 2 + (u >> 8) % default_osl)),
    )


def load_trace_jsonl(
    path: str,
    model: Optional[str] = None,
    default_isl: int = 512,
    default_osl: int = 128,
) -> List[Request]:
    """A DYN_TRACE_JSONL sink (one telemetry/tracing.py record per line)
    → offered requests, arrival-normalized to t=0."""
    records: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return _requests_from_records(records, model, default_isl, default_osl)


def load_incident_bundle(
    bundle_dir: str,
    model: Optional[str] = None,
    default_isl: int = 512,
    default_osl: int = 128,
) -> List[Request]:
    """An incident bundle (telemetry/incidents.py) → the traffic that
    led into the failure, replayed from ``traces.json``."""
    path = os.path.join(bundle_dir, "traces.json")
    with open(path, "r", encoding="utf-8") as f:
        traces = json.load(f)
    records = [t for t in traces if isinstance(t, dict)]
    return _requests_from_records(records, model, default_isl, default_osl)


def _requests_from_records(
    records: List[dict],
    model: Optional[str],
    default_isl: int,
    default_osl: int,
) -> List[Request]:
    timed = [r for r in records if isinstance(r.get("time"), (int, float))]
    t0 = min((float(r["time"]) for r in timed), default=0.0)
    out = [
        _request_from_trace(rec, t0, i, model, default_isl, default_osl)
        for i, rec in enumerate(records)
    ]
    out.sort(key=lambda r: (r.arrival_s, r.request_id))
    return out


GENERATORS: Dict[str, Callable] = {
    "diurnal": diurnal,
    "rag": rag,
    "long_context": long_context,
    "tenant_spike": tenant_spike,
    "chaos": chaos,
}
