"""Scenario vocabulary: named (workload, fleet shape, SLO floor)
triples, plus the seeded entrypoint that runs one against the real
control plane and returns the report dict.

Determinism contract: ``run_scenario(name, seed)`` seeds the global
``random`` module (the KvScheduler tie-break uses it), resets the fault
registry, builds a fresh ``VirtualClock``, and never reads wall time —
so the same (name, seed, overrides) always produces a byte-identical
report JSON.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Callable, Dict, List, Optional

from ..planner.admission import AdmissionConfig
from ..planner.policy import PolicyConfig
from ..registry.policy import PoolPolicyConfig
from ..registry.tenants import TenantQuota
from ..utils import faults
from .clock import VirtualClock, run_virtual
from .fleet import ChaosEvent, FleetConfig, SimFleet
from .report import build_report
from .worker import WorkerSpec
from .workload import GENERATORS, Request


@dataclasses.dataclass
class Scenario:
    """A named scenario: how traffic arrives + what fleet serves it."""

    name: str
    description: str
    slo_floor: float                       # capacity-curve attainment bar
    duration_s: float
    fleet: Callable[[], FleetConfig]       # fresh config per run
    workload: Optional[Callable[[random.Random, float], List[Request]]] = None


def _base_policy(**kw) -> PolicyConfig:
    base = dict(
        min_replicas=1, max_replicas=6, scale_step=1,
        scale_up_cooldown_s=60.0, scale_down_cooldown_s=300.0,
        decode_busy_up=0.85, decode_busy_down=0.25,
        shed_step_cooldown_s=10.0, relax_after_clear_s=60.0,
    )
    base.update(kw)
    return PolicyConfig(**base)


def _diurnal_fleet() -> FleetConfig:
    # two-model fleet: the primary rides the diurnal wave while a small
    # aux pool goes idle after its early traffic and scales to zero
    return FleetConfig(
        pools={"sim-model": 2, "sim-aux": 1},
        spec=WorkerSpec(),
        policy=_base_policy(),
        pool_policy=PoolPolicyConfig(idle_to_zero_s=300.0, cooldown_s=60.0),
        admission=AdmissionConfig(limit=40, queue_depth=64,
                                  queue_timeout_s=20.0),
    )


def _diurnal_workload(rng: random.Random,
                      duration_s: float) -> List[Request]:
    reqs = GENERATORS["diurnal"](rng, duration_s=duration_s)
    # a thin trickle to the aux model that stops a third of the way in,
    # leaving the pool idle long enough for scale-to-zero to fire
    aux = GENERATORS["diurnal"](
        rng, duration_s=duration_s / 3.0, base_qps=0.2, peak_qps=0.5,
        burst_factor=1.0, model="sim-aux")
    for i, r in enumerate(aux):
        r.request_id = f"aux-{i}"
    out = reqs + aux
    out.sort(key=lambda r: (r.arrival_s, r.request_id))
    return out


def _rag_fleet() -> FleetConfig:
    # small cache → evictions → cold-tier rehydration
    spec = WorkerSpec(kv_blocks=1024)
    return FleetConfig(
        pools={"sim-model": 3},
        spec=spec,
        policy=_base_policy(max_replicas=5),
        admission=AdmissionConfig(limit=48, queue_depth=96,
                                  queue_timeout_s=20.0),
    )


def _rag_pod_fleet() -> FleetConfig:
    # the same RAG fleet, but every worker shares one ICI domain: peer
    # pulls negotiate the collective backend and the per-block transfer
    # cost collapses by ici_pull_gbps / peer_pull_gbps (the fleet-scale
    # twin of the unified transfer plane's backend negotiation —
    # docs/transfer_plane.md)
    cfg = _rag_fleet()
    cfg.spec = dataclasses.replace(cfg.spec, pod_size=8)
    return cfg


def _rag_workload(rng: random.Random, duration_s: float) -> List[Request]:
    return GENERATORS["rag"](rng, duration_s=duration_s)


def _long_context_fleet() -> FleetConfig:
    # 128k prompts need headroom: 131072/16 = 8192 blocks just for one
    # prompt's KV, so provision deep pools and SP-friendly thresholds
    spec = WorkerSpec(kv_blocks=16384, slots=6)
    return FleetConfig(
        pools={"sim-model": 2},
        spec=spec,
        policy=_base_policy(max_replicas=5),
        admission=AdmissionConfig(limit=24, queue_depth=48,
                                  queue_timeout_s=30.0),
        slo_ttft_s=20.0,                  # SP prefill of 128k is slow
        slo_itl_s=1.0,                    # SP interleave gaps are legit
        watchdog_stall_s=30.0,
    )


def _tenant_spike_fleet() -> FleetConfig:
    return FleetConfig(
        pools={"sim-model": 2, "sim-burst": 0},
        spec=WorkerSpec(),
        policy=_base_policy(max_replicas=5),
        admission=AdmissionConfig(limit=32, queue_depth=48,
                                  queue_timeout_s=15.0),
        quota_default=TenantQuota(),      # unlimited baseline
        quota_overrides={
            "burst-tenant": TenantQuota(requests_per_s=2.0, burst_s=4.0),
        },
        pool_policy=PoolPolicyConfig(idle_to_zero_s=600.0,
                                     cooldown_s=60.0),
    )


def _tenant_spike_workload(rng: random.Random,
                           duration_s: float) -> List[Request]:
    reqs = GENERATORS["tenant_spike"](rng, duration_s=duration_s)
    # a late burst at the zero-replica aux pool exercises cold start
    # through PoolManager.await_capacity
    cold = GENERATORS["diurnal"](
        rng, duration_s=duration_s / 4.0, base_qps=0.3, peak_qps=0.6,
        burst_factor=1.0, model="sim-burst")
    for i, r in enumerate(cold):
        r.request_id = f"cold-{i}"
        r.arrival_s += duration_s / 2.0
        r.tenant = "acme"
    out = reqs + cold
    out.sort(key=lambda r: (r.arrival_s, r.request_id))
    return out


def _chaos_fleet() -> FleetConfig:
    # two workers so the wedge halves capacity: the outage genuinely
    # overloads the admission edge (shed ladder engages, low classes
    # first) until the watchdog→drain→respawn ladder restores it
    return FleetConfig(
        pools={"sim-model": 2},
        spec=WorkerSpec(),
        policy=_base_policy(max_replicas=4),
        admission=AdmissionConfig(limit=40, queue_depth=64,
                                  queue_timeout_s=20.0),
        watchdog_stall_s=12.0,
        chaos=[ChaosEvent(at_s=400.0, site="decode_burst_hang",
                          worker_index=0)],
    )


SCENARIOS: Dict[str, Scenario] = {
    "diurnal": Scenario(
        name="diurnal",
        description="bursty diurnal wave + aux pool scaling to zero",
        slo_floor=0.7,
        duration_s=1800.0,
        fleet=_diurnal_fleet,
        workload=_diurnal_workload,
    ),
    "rag": Scenario(
        name="rag",
        description="shared-prefix RAG: overlap routing, peer pull, "
                    "cold-tier rehydration",
        slo_floor=0.7,
        duration_s=900.0,
        fleet=_rag_fleet,
    ),
    "rag_pod": Scenario(
        name="rag_pod",
        description="the rag scenario inside one ICI pod: peer pulls "
                    "ride the collective plane instead of DCN",
        slo_floor=0.7,
        duration_s=900.0,
        fleet=_rag_pod_fleet,
        workload=_rag_workload,
    ),
    "long_context": Scenario(
        name="long_context",
        description="long-tail 128k SP prefills over a short-prompt "
                    "baseline",
        slo_floor=0.5,
        duration_s=900.0,
        fleet=_long_context_fleet,
    ),
    "tenant_spike": Scenario(
        name="tenant_spike",
        description="tenant floods past its token-bucket quota; cold "
                    "start of a scale-to-zero pool",
        slo_floor=0.6,
        duration_s=900.0,
        fleet=_tenant_spike_fleet,
        workload=_tenant_spike_workload,
    ),
    "chaos": Scenario(
        name="chaos",
        description="worker wedge mid-run: watchdog trip, drain, "
                    "respawn via the real recovery ladder",
        slo_floor=0.5,
        duration_s=900.0,
        fleet=_chaos_fleet,
    ),
    "replay": Scenario(
        name="replay",
        description="recorded traffic (DYN_TRACE_JSONL sink or "
                    "incident bundle) against a standard fleet",
        slo_floor=0.5,
        duration_s=900.0,
        fleet=_diurnal_fleet,
    ),
}


def run_scenario(
    name: str,
    seed: int = 0,
    duration_s: Optional[float] = None,
    requests: Optional[List[Request]] = None,
    fleet_cfg: Optional[FleetConfig] = None,
    slo_floor: Optional[float] = None,
    on_fleet=None,
) -> dict:
    """Run one scenario to completion in virtual time; return the
    report dict (see sim/report.py for its anatomy).

    ``requests`` overrides the scenario's generator (trace replay);
    ``duration_s`` shortens/stretches a synthetic run (tests use this).
    """
    if name not in SCENARIOS:
        raise KeyError(
            f"unknown scenario {name!r}; have {sorted(SCENARIOS)}")
    scn = SCENARIOS[name]
    dur = float(duration_s if duration_s is not None else scn.duration_s)
    random.seed(seed)                     # scheduler tie-breaks
    faults.reset()
    rng = random.Random(seed)
    if requests is None:
        if scn.workload is not None:
            requests = scn.workload(rng, dur)
        elif name in GENERATORS:
            requests = GENERATORS[name](rng, duration_s=dur)
        else:
            raise ValueError(
                f"scenario {name!r} has no synthetic generator — "
                "pass requests= (trace replay)")
    elif duration_s is None and requests:
        # replayed traces define their own horizon
        dur = max(dur, max(r.arrival_s for r in requests) + 60.0)
    cfg = fleet_cfg if fleet_cfg is not None else scn.fleet()
    if duration_s is not None and cfg.chaos:
        # keep chaos inside a shortened run
        for ev in cfg.chaos:
            if ev.at_s >= dur:
                ev.at_s = dur * 0.4
    clock = VirtualClock()
    fleet = SimFleet(cfg, clock)

    async def _main() -> None:
        await fleet.run(requests)

    run_virtual(_main, clock=clock)
    if on_fleet is not None:
        # post-run hook: callers render /metrics, inspect workers, etc.
        on_fleet(fleet)
    floor = float(slo_floor if slo_floor is not None else scn.slo_floor)
    return build_report(name, seed, fleet, floor, dur)
