"""``dynamo_sim_*`` instrument family: a long replay is itself
observable through the standard ``/metrics`` plumbing.

The fleet attaches every real component's registry (admission, planner,
registry/pools, tenants, SLO) plus this family to one root
MetricsRegistry, so ``render()`` of a sim run is a legal exposition a
live scrape job could ingest — and scripts/check_metric_names.py lints
these names like any other registration in the package.
"""

from __future__ import annotations

from typing import Callable

from ..telemetry.registry import MetricsRegistry


class SimMetrics:
    """Counters/gauges for one simulator run."""

    def __init__(self, registry: MetricsRegistry,
                 clock: Callable[[], float],
                 replica_fn: Callable[[], dict]) -> None:
        self.registry = registry
        self.requests = registry.counter(
            "dynamo_sim_requests_total",
            "Offered requests by terminal outcome= (completed|shed|"
            "queue_full|timeout|draining|quota|cold_start_timeout|"
            "failed) and priority= class",
        )
        self.tokens = registry.counter(
            "dynamo_sim_tokens_total",
            "Simulated tokens processed, labelled phase=prefill|decode",
        )
        self.scale_actions = registry.counter(
            "dynamo_sim_scale_actions_total",
            "Planner scale actions the sim actuated, labelled role= "
            "and direction=up|down",
        )
        self.chaos = registry.counter(
            "dynamo_sim_chaos_injections_total",
            "Chaos events injected into simulated workers, labelled "
            "site= (DYN_FAULT vocabulary)",
        )
        self.recoveries = registry.counter(
            "dynamo_sim_recoveries_total",
            "Recovery ladders the real controller completed inside the "
            "sim, labelled reason=",
        )
        self.trips = registry.counter(
            "dynamo_sim_watchdog_trips_total",
            "Simulated watchdog trips (stalled-worker detections) that "
            "started a recovery ladder",
        )
        self.retries = registry.counter(
            "dynamo_sim_resubmits_total",
            "Requests the simulated client resubmitted after a drain "
            "failed their first attempt",
        )
        self.attainment = registry.gauge(
            "dynamo_sim_slo_attainment_ratio",
            "SLO-met fraction of completed requests for the finished "
            "run (the report's headline number)",
        )
        self.kv_usage = registry.gauge(
            "dynamo_sim_kv_usage_ratio",
            "Fleet KV block usage at the last sample of the run",
        )
        registry.callback_gauge(
            "dynamo_sim_virtual_time_seconds",
            "Virtual seconds the scenario has advanced",
            clock,
        )
        registry.callback_gauge(
            "dynamo_sim_workers_replicas",
            "Live simulated workers per model= pool",
            lambda: [({"model": m}, float(n))
                     for m, n in sorted(replica_fn().items())],
        )
