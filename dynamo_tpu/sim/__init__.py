"""Trace-driven fleet simulator.

A discrete-event harness that drives the REAL control plane — SlaPolicy,
AdmissionController, PoolManager, RecoveryController, KvScheduler — on a
virtual clock against simulated workers parameterized by the measured
device-time byte model (telemetry/device_time.py). No decision logic is
forked or mocked; the sim only substitutes time and the data plane.

Entry points:

- ``scripts/fleetsim.py`` — CLI: scenario -> capacity-curve report
- :func:`dynamo_tpu.sim.scenarios.run_scenario` — programmatic runs
- :mod:`dynamo_tpu.sim.workload` — synthetic generators + trace replay

See docs/simulator.md for the scenario vocabulary and report anatomy.
"""

from dynamo_tpu.sim.clock import VirtualClock, run_virtual
from dynamo_tpu.sim.scenarios import SCENARIOS, run_scenario
from dynamo_tpu.sim.workload import Request

__all__ = [
    "VirtualClock",
    "run_virtual",
    "SCENARIOS",
    "run_scenario",
    "Request",
]
