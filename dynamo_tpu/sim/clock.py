"""Virtual time for the fleet simulator.

The control plane already takes an injectable ``clock`` everywhere, but
its waiting primitives are asyncio timers (``asyncio.sleep`` in the pool
poll loop and respawn backoff, ``asyncio.wait_for`` on the admission
queue). To run those at 1000x real time without forking any logic, the
sim installs an event loop whose ``time()`` is a :class:`VirtualClock`
and whose selector advances that clock by the pending-timer deadline
whenever no I/O is ready — the textbook discrete-event skip. Real file
descriptors still work (they are polled with a zero timeout), so the
loop degrades gracefully if a scenario ever touches sockets.

Nothing in this module (or anywhere under ``sim/``) reads the wall
clock; determinism tests pin that.
"""

from __future__ import annotations

import asyncio
import selectors
from typing import Any, Awaitable, Callable, List, Optional, TypeVar

T = TypeVar("T")

# When the loop blocks with no timers at all (timeout=None) the virtual
# clock cannot know how far to skip; advance in coarse fixed steps so a
# stray wait still terminates instead of spinning at +0.
_IDLE_STEP_S = 1.0

# Hard ceiling on total virtual seconds a single run may advance; a
# scenario that sleeps past this is wedged, not slow.
MAX_VIRTUAL_S = 10_000_000.0


class VirtualClock:
    """A monotonically advancing virtual timebase.

    Instances are callables returning virtual seconds, matching the
    ``clock: Callable[[], float]`` parameter every control-plane class
    accepts (``SlaPolicy``, ``AdmissionController``, ``PoolManager``,
    ``KvScheduler``, ``SloTracker``, ``TenantQuotas``...).
    """

    __slots__ = ("_now",)

    def __init__(self, start: float = 0.0) -> None:
        self._now = float(start)

    def __call__(self) -> float:
        return self._now

    @property
    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> None:
        if dt > 0.0:
            self._now += dt
        if self._now > MAX_VIRTUAL_S:
            raise RuntimeError(
                f"virtual clock ran past {MAX_VIRTUAL_S:.0f}s — "
                "scenario is not terminating"
            )


class _TimeWarpSelector:
    """Selector wrapper: poll real FDs without blocking, then convert the
    requested wait into a virtual-clock jump."""

    def __init__(self, clock: VirtualClock) -> None:
        self._real = selectors.DefaultSelector()
        self._clock = clock
        self._spins = 0

    # -- plain delegation -------------------------------------------------
    def register(self, fileobj: Any, events: int, data: Any = None):
        return self._real.register(fileobj, events, data)

    def unregister(self, fileobj: Any):
        return self._real.unregister(fileobj)

    def modify(self, fileobj: Any, events: int, data: Any = None):
        return self._real.modify(fileobj, events, data)

    def get_key(self, fileobj: Any):
        return self._real.get_key(fileobj)

    def get_map(self):
        return self._real.get_map()

    def close(self) -> None:
        self._real.close()

    # -- the time warp ----------------------------------------------------
    def select(self, timeout: Optional[float] = None):
        # Real FDs only matter for signal wakeups and the rare scenario
        # that touches sockets; an OS poll per iteration costs more than
        # the virtual hop itself. Poll on a decimated cadence — and on
        # every iteration while the loop is otherwise idle, so an FD
        # wait still terminates promptly.
        self._spins += 1
        if timeout is None or self._spins >= 16:
            self._spins = 0
            events = self._real.select(0)
            if events:
                return events
        self._clock.advance(_IDLE_STEP_S if timeout is None else timeout)
        return []


class VirtualTimeEventLoop(asyncio.SelectorEventLoop):
    """A selector event loop whose ``time()`` is virtual.

    ``call_later`` / ``asyncio.sleep`` / ``asyncio.wait_for`` schedule
    against :meth:`time`, and the warped selector advances the clock to
    the earliest deadline whenever nothing else is runnable, so timer
    waits complete in microseconds of wall time regardless of their
    virtual duration.
    """

    def __init__(self, clock: VirtualClock) -> None:
        super().__init__(selector=_TimeWarpSelector(clock))
        self.virtual_clock = clock

    def time(self) -> float:
        return self.virtual_clock()


def run_virtual(
    main: Callable[[], Awaitable[T]],
    clock: Optional[VirtualClock] = None,
) -> T:
    """Run ``main()`` to completion on a fresh virtual-time loop.

    Mirrors ``asyncio.run``: owns the loop for the duration, cancels
    leftover tasks, and closes the loop. Returns the coroutine result.
    """
    clock = clock if clock is not None else VirtualClock()
    loop = VirtualTimeEventLoop(clock)
    try:
        asyncio.set_event_loop(loop)
        return loop.run_until_complete(main())
    finally:
        try:
            _cancel_pending(loop)
        finally:
            asyncio.set_event_loop(None)
            loop.close()


def _cancel_pending(loop: asyncio.AbstractEventLoop) -> None:
    pending: List[asyncio.Task] = [
        t for t in asyncio.all_tasks(loop) if not t.done()
    ]
    for task in pending:
        task.cancel()
    if pending:
        loop.run_until_complete(
            asyncio.gather(*pending, return_exceptions=True)
        )
