"""Processor: the OpenAI↔token-level bridge with KV-aware routing.

The distributed serving shape (reference flagship graph, SURVEY.md §3.2:
Frontend → Processor → Router → Worker):

  frontend (OpenAI passthrough) → THIS component:
    preprocess (template+tokenize) → KvRouter.schedule(token_ids) →
    direct() the PreprocessedRequest to the chosen token-level worker →
    detokenize the EngineOutput stream back into OpenAI chunks.

``KvRoutedClient`` is the terminal engine of that pipeline: it owns the
routing decision (KV-aware when a router is attached, else the client's
round-robin/random mode).
"""

from __future__ import annotations

import logging
from typing import Any, AsyncIterator, Optional

from ..kv_router.router import KvRouter
from ..protocols.common import PreprocessedRequest
from ..runtime.client import Client
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import build_pipeline
from .backend import Backend
from .model_card import ModelDeploymentCard
from .preprocessor import OpenAIPreprocessor
from .tokenizer import HFTokenizer

logger = logging.getLogger(__name__)


class KvRoutedClient(AsyncEngine):
    """Routes token-level requests to workers, KV-aware when possible."""

    def __init__(self, client: Client, router: Optional[KvRouter] = None):
        self.client = client
        self.router = router

    async def generate(self, request: Context[Any]) -> AsyncIterator[Any]:
        from ..runtime.client import NoInstancesError

        req = request.payload
        token_ids = (
            req.token_ids if isinstance(req, PreprocessedRequest) else req["token_ids"]
        )
        model = (req.model if isinstance(req, PreprocessedRequest)
                 else req.get("model"))
        if model is not None:
            # per-model pool partition (registry/): the KV router scopes
            # prefix scoring to the model's pool, and the client's
            # fallback/round-robin pick stays inside it too
            request.baggage["model_pool"] = model
        if self.router is not None:
            try:
                decision = await self.router.schedule(
                    token_ids, trace_id=request.trace_id, model=model
                )
                request.baggage["instance_id"] = decision.worker_id
                request.baggage["prefix_hit_tokens"] = decision.prefix_hit_tokens
                # closing-mark span: the routing decision's latency in the
                # stitched timeline (and which worker the hop went to)
                request.add_stage("router.pick")
            except Exception:
                logger.warning("kv scheduling failed; falling back", exc_info=True)
        # explicit aclose on the inner stream: when a downstream consumer
        # (llm/backend.py) closes THIS generator at the finish chunk, the
        # client generator's cleanup — which folds the worker's span
        # export into the request trace — must run synchronously, not at
        # some later GC-driven finalization
        stream = self.client.generate(request)
        try:
            try:
                async for item in stream:
                    yield item
                return
            except NoInstancesError:
                # the KV-chosen worker died between metrics poll and
                # dispatch — retry once, letting the client's own mode
                # pick a live instance
                if "instance_id" not in request.baggage:
                    raise
                logger.warning(
                    "kv-chosen worker %s gone; re-routing",
                    request.baggage.pop("instance_id"),
                )
        finally:
            await stream.aclose()
        retry = self.client.generate(request)
        try:
            async for item in retry:
                yield item
        finally:
            await retry.aclose()

    async def close(self) -> None:
        if self.router is not None:
            await self.router.stop()
        await self.client.close()


def build_processor_pipeline(
    mdc: ModelDeploymentCard,
    worker_client: Client,
    router: Optional[KvRouter] = None,
    tokenizer: Optional[HFTokenizer] = None,
) -> AsyncEngine:
    """OpenAI-level engine: preprocess → route → worker → detokenize."""
    tokenizer = tokenizer or (
        HFTokenizer.from_model_path(mdc.model_path) if mdc.model_path else None
    )
    return build_pipeline(
        [OpenAIPreprocessor(mdc, tokenizer), Backend(tokenizer)],
        KvRoutedClient(worker_client, router),
    )
