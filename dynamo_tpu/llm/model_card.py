"""Model Deployment Card: the single source of truth for a served model.

Reference analog: lib/llm/src/model_card/model.rs:55-360 — display name,
service slug, model info, tokenizer, prompt formatter, context length, KV
block size, and a checksum that lets routers/workers verify they agree on
preprocessing. Built from a local HF snapshot directory (config.json +
tokenizer.json + tokenizer_config.json).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional


def slugify(name: str) -> str:
    return re.sub(r"[^a-z0-9_.-]+", "-", name.lower()).strip("-")


@dataclasses.dataclass
class ModelDeploymentCard:
    display_name: str
    slug: str
    model_path: Optional[str] = None
    context_length: int = 4096
    kv_block_size: int = 16
    chat_template: Optional[str] = None
    bos_token_id: Optional[int] = None
    eos_token_ids: List[int] = dataclasses.field(default_factory=list)
    bos_token: Optional[str] = None
    eos_token: Optional[str] = None
    model_type: str = "chat"  # "chat" | "completions" | "both"
    # how this model emits tool calls (llm/tools.py FORMATS); "auto" probes
    tool_call_format: Optional[str] = "auto"
    config: Dict[str, Any] = dataclasses.field(default_factory=dict)
    checksum: Optional[str] = None

    def __post_init__(self):
        if self.checksum is None:
            self.checksum = self.compute_checksum()

    def compute_checksum(self) -> str:
        """Hash of everything that affects preprocessing agreement."""
        basis = json.dumps(
            {
                "display_name": self.display_name,
                "context_length": self.context_length,
                "kv_block_size": self.kv_block_size,
                "chat_template": self.chat_template,
                "bos_token_id": self.bos_token_id,
                "eos_token_ids": self.eos_token_ids,
            },
            sort_keys=True,
        ).encode()
        return hashlib.sha256(basis).hexdigest()[:16]

    @classmethod
    def from_local_path(
        cls,
        model_dir: str,
        display_name: Optional[str] = None,
        kv_block_size: int = 16,
    ) -> "ModelDeploymentCard":
        name = display_name or os.path.basename(os.path.normpath(model_dir))
        cfg_path = os.path.join(model_dir, "config.json")
        config: Dict[str, Any] = {}
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                config = json.load(f)

        eos = config.get("eos_token_id")
        eos_ids = [] if eos is None else ([eos] if isinstance(eos, int) else list(eos))
        bos = config.get("bos_token_id")
        context_length = int(
            config.get("max_position_embeddings")
            or config.get("n_positions")
            or 4096
        )

        chat_template = None
        bos_token = eos_token = None
        tc_path = os.path.join(model_dir, "tokenizer_config.json")
        if os.path.exists(tc_path):
            with open(tc_path) as f:
                tc = json.load(f)
            chat_template = tc.get("chat_template")
            if isinstance(chat_template, list):  # multi-template form
                named = {t.get("name"): t.get("template") for t in chat_template}
                chat_template = named.get("default") or next(iter(named.values()), None)

            def _tok_str(v):
                return v.get("content") if isinstance(v, dict) else v

            bos_token = _tok_str(tc.get("bos_token"))
            eos_token = _tok_str(tc.get("eos_token"))

        return cls(
            display_name=name,
            slug=slugify(name),
            model_path=os.path.abspath(model_dir),
            context_length=context_length,
            kv_block_size=kv_block_size,
            chat_template=chat_template,
            bos_token_id=bos,
            eos_token_ids=eos_ids,
            bos_token=bos_token,
            eos_token=eos_token,
            config=config,
        )

    def to_wire(self) -> dict:
        d = dataclasses.asdict(self)
        d.pop("config", None)  # big and derivable from model_path
        return d

    @classmethod
    def from_wire(cls, d: dict) -> "ModelDeploymentCard":
        d = dict(d)
        d.setdefault("config", {})
        return cls(**d)
