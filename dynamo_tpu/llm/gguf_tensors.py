"""GGUF tensor data loading + dequantization (numpy, vectorized).

Completes the GGUF path: llm/gguf.py parses metadata/descriptors and
rebuilds the tokenizer; this module reads the actual tensor data so a
``.gguf`` checkpoint can be SERVED, not just described (reference analog:
the reference's gguf crate reads tensor data for its engines,
lib/llm/src/gguf/*; the dequant block formats are the public GGML spec).

Supported ggml dtypes: f32, f16, bf16, q8_0, q4_0, q4_1, q5_0, q5_1 and
the k-quants q4_k, q5_k, q6_k (the formats real-world llama.cpp exports
overwhelmingly use). Everything dequantizes to float32; the engine casts
to its compute dtype (bf16) when staging params.

All dequantizers take the raw block bytes as a uint8 array and the
element count, and return float32 of that length. Block layouts follow
ggml's quants.c; each is implemented as reshape + bit arithmetic over
the block axis, so multi-GB tensors dequantize at memory bandwidth.
"""

from __future__ import annotations

import mmap
from typing import Dict, Iterator, Tuple

import numpy as np

from .gguf import GgufError, GgufFile, GgufTensorInfo

QK = 32       # block size of the simple quants
QK_K = 256    # block size of the k-quants


def _f16(raw: np.ndarray) -> np.ndarray:
    """View consecutive byte pairs as little-endian float16 → float32."""
    return raw.view("<f2").astype(np.float32)


def _nibbles(qs: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(low, high) 4-bit halves of a uint8 array."""
    return (qs & 0x0F).astype(np.int8), (qs >> 4).astype(np.int8)


def _deq_q8_0(blocks: np.ndarray, n: int) -> np.ndarray:
    b = blocks.reshape(-1, 2 + QK)
    d = _f16(b[:, :2].reshape(-1))[:, None]
    q = b[:, 2:].view(np.int8).astype(np.float32)
    return (d * q).reshape(-1)[:n]


def _deq_q4_0(blocks: np.ndarray, n: int) -> np.ndarray:
    b = blocks.reshape(-1, 2 + QK // 2)
    d = _f16(b[:, :2].reshape(-1))[:, None]
    lo, hi = _nibbles(b[:, 2:])
    q = np.concatenate([lo, hi], axis=1).astype(np.float32) - 8.0
    return (d * q).reshape(-1)[:n]


def _deq_q4_1(blocks: np.ndarray, n: int) -> np.ndarray:
    b = blocks.reshape(-1, 4 + QK // 2)
    d = _f16(b[:, 0:2].reshape(-1))[:, None]
    m = _f16(b[:, 2:4].reshape(-1))[:, None]
    lo, hi = _nibbles(b[:, 4:])
    q = np.concatenate([lo, hi], axis=1).astype(np.float32)
    return (d * q + m).reshape(-1)[:n]


def _q5_high_bits(qh_bytes: np.ndarray) -> np.ndarray:
    """[nb, 4] uint8 → [nb, 32] fifth bits (little-endian uint32 bit j)."""
    qh = qh_bytes.copy().view("<u4").reshape(-1, 1)
    j = np.arange(QK, dtype=np.uint32)[None, :]
    return ((qh >> j) & 1).astype(np.int8)


def _deq_q5_0(blocks: np.ndarray, n: int) -> np.ndarray:
    b = blocks.reshape(-1, 2 + 4 + QK // 2)
    d = _f16(b[:, :2].reshape(-1))[:, None]
    hi_bits = _q5_high_bits(b[:, 2:6])
    lo, hi = _nibbles(b[:, 6:])
    q = np.concatenate([lo, hi], axis=1) | (hi_bits << 4)
    return (d * (q.astype(np.float32) - 16.0)).reshape(-1)[:n]


def _deq_q5_1(blocks: np.ndarray, n: int) -> np.ndarray:
    b = blocks.reshape(-1, 4 + 4 + QK // 2)
    d = _f16(b[:, 0:2].reshape(-1))[:, None]
    m = _f16(b[:, 2:4].reshape(-1))[:, None]
    hi_bits = _q5_high_bits(b[:, 4:8])
    lo, hi = _nibbles(b[:, 8:])
    q = np.concatenate([lo, hi], axis=1) | (hi_bits << 4)
    return (d * q.astype(np.float32) + m).reshape(-1)[:n]


def _k_scale_min(scales: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """ggml get_scale_min_k4: [nb, 12] packed 6-bit → ([nb, 8] sc, m)."""
    sc = np.empty(scales.shape[:1] + (8,), np.float32)
    mn = np.empty_like(sc)
    for j in range(4):
        sc[:, j] = (scales[:, j] & 63).astype(np.float32)
        mn[:, j] = (scales[:, j + 4] & 63).astype(np.float32)
    for j in range(4, 8):
        sc[:, j] = ((scales[:, j + 4] & 0x0F) | ((scales[:, j - 4] >> 6) << 4)).astype(np.float32)
        mn[:, j] = ((scales[:, j + 4] >> 4) | ((scales[:, j] >> 6) << 4)).astype(np.float32)
    return sc, mn


def _deq_q4_k(blocks: np.ndarray, n: int) -> np.ndarray:
    # block: d f16, dmin f16, scales[12], qs[128] — 8 sub-blocks of 32
    b = blocks.reshape(-1, 2 + 2 + 12 + QK_K // 2)
    d = _f16(b[:, 0:2].reshape(-1))[:, None]
    dmin = _f16(b[:, 2:4].reshape(-1))[:, None]
    sc, mn = _k_scale_min(b[:, 4:16])
    qs = b[:, 16:].reshape(-1, 4, 32)            # 4 chunks of 32 bytes
    lo = (qs & 0x0F).astype(np.float32)          # sub-blocks 0,2,4,6
    hi = (qs >> 4).astype(np.float32)            # sub-blocks 1,3,5,7
    q = np.stack([lo, hi], axis=2).reshape(-1, 8, 32)  # [nb, sub, 32]
    y = d[:, :, None] * sc[:, :, None] * q - dmin[:, :, None] * mn[:, :, None]
    return y.reshape(-1)[:n]


def _deq_q5_k(blocks: np.ndarray, n: int) -> np.ndarray:
    # block: d f16, dmin f16, scales[12], qh[32], qs[128]
    b = blocks.reshape(-1, 2 + 2 + 12 + QK_K // 8 + QK_K // 2)
    d = _f16(b[:, 0:2].reshape(-1))[:, None]
    dmin = _f16(b[:, 2:4].reshape(-1))[:, None]
    sc, mn = _k_scale_min(b[:, 4:16])
    qh = b[:, 16:48]                              # [nb, 32]
    qs = b[:, 48:].reshape(-1, 4, 32)
    lo = (qs & 0x0F).astype(np.int16)
    hi = (qs >> 4).astype(np.int16)
    # chunk g supplies sub-blocks 2g (low nibbles, qh bit 2g) and 2g+1
    # (high nibbles, qh bit 2g+1)
    g = np.arange(4)
    bit_lo = ((qh[:, None, :] >> (2 * g)[None, :, None]) & 1).astype(np.int16)
    bit_hi = ((qh[:, None, :] >> (2 * g + 1)[None, :, None]) & 1).astype(np.int16)
    q = np.stack([lo | (bit_lo << 4), hi | (bit_hi << 4)], axis=2)
    q = q.reshape(-1, 8, 32).astype(np.float32)
    y = d[:, :, None] * sc[:, :, None] * q - dmin[:, :, None] * mn[:, :, None]
    return y.reshape(-1)[:n]


def _deq_q6_k(blocks: np.ndarray, n: int) -> np.ndarray:
    # block: ql[128], qh[64], scales[16] int8, d f16
    b = blocks.reshape(-1, QK_K // 2 + QK_K // 4 + 16 + 2)
    ql = b[:, :128].reshape(-1, 2, 64)            # [nb, half, 64]
    qh = b[:, 128:192].reshape(-1, 2, 32)         # [nb, half, 32]
    scales = b[:, 192:208].view(np.int8).astype(np.float32)  # [nb, 16]
    d = _f16(b[:, 208:210].reshape(-1))[:, None]
    l32 = np.arange(32)
    out = np.empty((b.shape[0], 2, 128), np.float32)
    sidx = np.empty((2, 128), np.int64)
    for h in (0, 1):
        qlh, qhh = ql[:, h], qh[:, h]
        q1 = (qlh[:, :32] & 0x0F) | (((qhh >> 0) & 3) << 4)
        q2 = (qlh[:, 32:] & 0x0F) | (((qhh >> 2) & 3) << 4)
        q3 = (qlh[:, :32] >> 4) | (((qhh >> 4) & 3) << 4)
        q4 = (qlh[:, 32:] >> 4) | (((qhh >> 6) & 3) << 4)
        out[:, h] = np.concatenate(
            [q1, q2, q3, q4], axis=1
        ).astype(np.float32) - 32.0
        sidx[h] = 8 * h + np.concatenate(
            [l32 // 16, 2 + l32 // 16, 4 + l32 // 16, 6 + l32 // 16]
        )
    y = d[:, None] * scales[:, sidx.reshape(-1)].reshape(-1, 2, 128) * out
    return y.reshape(-1)[:n]


# ggml type id → (bytes per block, elements per block, dequantizer)
_DEQUANT: Dict[int, Tuple[int, int, object]] = {
    0: (4, 1, None),                               # f32
    1: (2, 1, None),                               # f16
    30: (2, 1, None),                              # bf16
    2: (2 + QK // 2, QK, _deq_q4_0),
    3: (4 + QK // 2, QK, _deq_q4_1),
    6: (2 + 4 + QK // 2, QK, _deq_q5_0),
    7: (4 + 4 + QK // 2, QK, _deq_q5_1),
    8: (2 + QK, QK, _deq_q8_0),
    12: (2 + 2 + 12 + QK_K // 2, QK_K, _deq_q4_k),
    13: (2 + 2 + 12 + QK_K // 8 + QK_K // 2, QK_K, _deq_q5_k),
    14: (QK_K // 2 + QK_K // 4 + 16 + 2, QK_K, _deq_q6_k),
}


def tensor_nbytes(info: GgufTensorInfo) -> int:
    if info.ggml_type not in _DEQUANT:
        raise GgufError(
            f"tensor {info.name!r} has unsupported ggml type "
            f"{info.type_name} ({info.ggml_type})"
        )
    block_bytes, block_elems, _ = _DEQUANT[info.ggml_type]
    n = int(np.prod(info.shape)) if info.shape else 1
    if n % block_elems:
        raise GgufError(
            f"tensor {info.name!r}: {n} elements not divisible by "
            f"{info.type_name} block size {block_elems}"
        )
    return n // block_elems * block_bytes


def dequantize(info: GgufTensorInfo, raw: np.ndarray) -> np.ndarray:
    """Raw tensor bytes → numpy array in the tensor's LOGICAL layout.

    GGUF's ne[] lists the contiguous dim first, so the numpy shape is
    ``reversed(info.shape)`` — for a llama.cpp matmul weight that comes
    out as the familiar [out_features, in_features].
    """
    n = int(np.prod(info.shape)) if info.shape else 1
    block_bytes, block_elems, fn = _DEQUANT[info.ggml_type]
    if fn is None:
        dt = {0: "<f4", 1: "<f2", 30: "<u2"}[info.ggml_type]
        flat = raw.view(dt)
        if info.ggml_type == 30:  # bf16: widen via the exponent trick
            flat = (flat.astype(np.uint32) << 16).view(np.float32)
        flat = flat.astype(np.float32)
    else:
        flat = fn(raw, n)
    return flat.reshape(tuple(reversed(info.shape)) if info.shape else ())


def iter_gguf_tensors(
    path: str, g: GgufFile
) -> Iterator[Tuple[str, np.ndarray]]:
    """Stream (name, float32 ndarray) without staging the whole file."""
    with open(path, "rb") as f, mmap.mmap(
        f.fileno(), 0, access=mmap.ACCESS_READ
    ) as mm:
        buf = raw = None
        try:
            buf = np.frombuffer(mm, dtype=np.uint8)
            for info in g.tensors:
                start = g.data_offset + info.offset
                end = start + tensor_nbytes(info)
                if end > buf.size:
                    raise GgufError(
                        f"tensor {info.name!r} data [{start}, {end}) "
                        f"exceeds file size {buf.size}"
                    )
                raw = buf[start:end]
                yield info.name, dequantize(info, raw)
        finally:
            # dequantize returns copies; drop OUR views of the mmap so
            # closing it doesn't hit "exported pointers exist"
            del buf, raw
