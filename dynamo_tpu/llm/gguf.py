"""GGUF metadata reader: model cards + architecture configs from .gguf files.

Pure-Python parser for the GGUF container format (v2/v3) — header,
metadata key/values, and tensor descriptors (names/shapes/types only;
tensor data is not loaded or dequantized here). Enough to build a
ModelDeploymentCard and a ModelConfig from a GGUF checkpoint, mirroring
the reference's GGUF support (reference: lib/llm/src/gguf/* — metadata
parse + model-card creation via ModelDeploymentCard::from_gguf,
lib/llm/src/model_card/create.rs).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, BinaryIO, Dict, List, Optional, Tuple

GGUF_MAGIC = b"GGUF"

# metadata value types (gguf spec)
_T_UINT8, _T_INT8, _T_UINT16, _T_INT16 = 0, 1, 2, 3
_T_UINT32, _T_INT32, _T_FLOAT32, _T_BOOL = 4, 5, 6, 7
_T_STRING, _T_ARRAY, _T_UINT64, _T_INT64, _T_FLOAT64 = 8, 9, 10, 11, 12

_SCALAR_FMT = {
    _T_UINT8: "<B", _T_INT8: "<b", _T_UINT16: "<H", _T_INT16: "<h",
    _T_UINT32: "<I", _T_INT32: "<i", _T_FLOAT32: "<f",
    _T_UINT64: "<Q", _T_INT64: "<q", _T_FLOAT64: "<d",
}

# tensor ggml dtypes we can name (id → name); quantized types included so
# descriptors are informative even when we never load the data
GGML_TYPE_NAMES = {
    0: "f32", 1: "f16", 2: "q4_0", 3: "q4_1", 6: "q5_0", 7: "q5_1",
    8: "q8_0", 9: "q8_1", 10: "q2_k", 11: "q3_k", 12: "q4_k", 13: "q5_k",
    14: "q6_k", 15: "q8_k", 16: "iq2_xxs", 17: "iq2_xs", 18: "iq3_xxs",
    24: "i8", 25: "i16", 26: "i32", 27: "i64", 28: "f64", 30: "bf16",
}


class GgufError(ValueError):
    pass


@dataclasses.dataclass
class GgufTensorInfo:
    name: str
    shape: Tuple[int, ...]
    ggml_type: int
    offset: int

    @property
    def type_name(self) -> str:
        return GGML_TYPE_NAMES.get(self.ggml_type, f"unknown({self.ggml_type})")


@dataclasses.dataclass
class GgufFile:
    version: int
    metadata: Dict[str, Any]
    tensors: List[GgufTensorInfo]
    data_offset: int = 0  # absolute file offset where tensor data begins

    @property
    def architecture(self) -> Optional[str]:
        return self.metadata.get("general.architecture")

    def arch_key(self, suffix: str, default=None):
        """Lookup '{arch}.{suffix}' (e.g. llama.context_length)."""
        arch = self.architecture
        if arch is None:
            return default
        return self.metadata.get(f"{arch}.{suffix}", default)


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    data = f.read(size)
    if len(data) != size:
        raise GgufError("truncated GGUF file")
    return struct.unpack(fmt, data)[0]


def _read_string(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    data = f.read(n)
    if len(data) != n:
        raise GgufError("truncated GGUF string")
    return data.decode("utf-8", errors="replace")


def _remaining(f: BinaryIO) -> int:
    import os

    return os.fstat(f.fileno()).st_size - f.tell()


def _read_value(f: BinaryIO, vtype: int, depth: int = 0) -> Any:
    if vtype in _SCALAR_FMT:
        return _read(f, _SCALAR_FMT[vtype])
    if vtype == _T_BOOL:
        return bool(_read(f, "<B"))
    if vtype == _T_STRING:
        return _read_string(f)
    if vtype == _T_ARRAY:
        if depth > 4:
            raise GgufError("GGUF array nesting too deep")
        item_type = _read(f, "<I")
        count = _read(f, "<Q")
        # every element consumes >= 1 byte: a count beyond the remaining
        # file size is corrupt and would otherwise exhaust memory before
        # the truncation error fires
        if count > _remaining(f):
            raise GgufError(f"implausible GGUF array count {count}")
        return [_read_value(f, item_type, depth + 1) for _ in range(count)]
    raise GgufError(f"unknown GGUF metadata type {vtype}")


def read_gguf(path: str, max_tensors: int = 100_000) -> GgufFile:
    """Parse header + metadata + tensor descriptors (no tensor data)."""
    with open(path, "rb") as f:
        if f.read(4) != GGUF_MAGIC:
            raise GgufError(f"{path} is not a GGUF file")
        version = _read(f, "<I")
        if version not in (2, 3):
            raise GgufError(f"unsupported GGUF version {version} (need 2 or 3)")
        tensor_count = _read(f, "<Q")
        kv_count = _read(f, "<Q")
        if tensor_count > max_tensors:
            raise GgufError(f"implausible tensor count {tensor_count}")
        if kv_count > _remaining(f):
            raise GgufError(f"implausible metadata count {kv_count}")

        metadata: Dict[str, Any] = {}
        for _ in range(kv_count):
            key = _read_string(f)
            vtype = _read(f, "<I")
            metadata[key] = _read_value(f, vtype)

        tensors: List[GgufTensorInfo] = []
        for _ in range(tensor_count):
            name = _read_string(f)
            n_dims = _read(f, "<I")
            if n_dims > 8:
                raise GgufError(f"implausible tensor rank {n_dims}")
            shape = tuple(_read(f, "<Q") for _ in range(n_dims))
            ggml_type = _read(f, "<I")
            offset = _read(f, "<Q")
            tensors.append(GgufTensorInfo(name, shape, ggml_type, offset))
        # tensor data begins at the next alignment boundary; per-tensor
        # offsets (above) are relative to this point
        align = int(metadata.get("general.alignment", 32) or 32)
        data_offset = (f.tell() + align - 1) // align * align
    return GgufFile(
        version=version, metadata=metadata, tensors=tensors,
        data_offset=data_offset,
    )


def hf_config_from_gguf(g: GgufFile) -> Dict[str, Any]:
    """GGUF architecture metadata → HF config.json-shaped dict.

    One translation shared by the MDC (whose ``config`` field rides the
    discovery plane and feeds engine_config_from_mdc) and
    model_config_from_gguf, so a .gguf-backed worker builds the same
    ModelConfig as a snapshot-backed one.

    Only architectures whose converters share the llama graph + q/k
    permute are accepted — anything else must fail HERE, loudly, or the
    llama loader would serve plausible-looking garbage for e.g. a qwen2
    export (biases dropped, unpermute applied that its converter never
    performed).
    """
    arch = g.architecture
    if arch not in ("llama", "mistral"):
        raise GgufError(
            f"unsupported GGUF architecture {arch!r} (supported: llama, "
            "mistral — other families need their own tensor mapping)"
        )
    tokens = g.metadata.get("tokenizer.ggml.tokens")
    heads = g.arch_key("attention.head_count", 32)
    tied = g.metadata.get("general.tie_word_embeddings")
    if tied is None:
        # llama.cpp omits the flag; tied models simply ship no output.weight
        tied = not any(t.name == "output.weight" for t in g.tensors)
    cfg: Dict[str, Any] = {
        "vocab_size": len(tokens) if tokens else g.arch_key("vocab_size", 32000),
        "hidden_size": g.arch_key("embedding_length", 4096),
        "intermediate_size": g.arch_key("feed_forward_length", 11008),
        "num_hidden_layers": g.arch_key("block_count", 32),
        "num_attention_heads": heads,
        "num_key_value_heads": g.arch_key("attention.head_count_kv", heads),
        "rope_theta": float(g.arch_key("rope.freq_base", 10000.0)),
        "rms_norm_eps": float(
            g.arch_key("attention.layer_norm_rms_epsilon", 1e-5)
        ),
        "max_position_embeddings": g.arch_key("context_length", 4096),
        "tie_word_embeddings": bool(tied),
        "architectures": ["LlamaForCausalLM"],
    }
    key_len = g.arch_key("attention.key_length")
    if key_len:
        cfg["head_dim"] = key_len
    scale_type = g.arch_key("rope.scaling.type")
    if scale_type and scale_type != "none":  # llama.cpp writes "none"
        cfg["rope_scaling"] = {
            "rope_type": scale_type,
            "factor": float(g.arch_key("rope.scaling.factor", 1.0) or 1.0),
            "original_max_position_embeddings": g.arch_key(
                "rope.scaling.original_context_length",
                cfg["max_position_embeddings"],
            ),
        }
    experts = g.arch_key("expert_count", 0) or 0
    if experts:
        cfg["num_local_experts"] = experts
        cfg["num_experts_per_tok"] = g.arch_key("expert_used_count", 2) or 2
        cfg["architectures"] = ["MixtralForCausalLM"]
    eos = g.metadata.get("tokenizer.ggml.eos_token_id")
    if eos is not None:
        cfg["eos_token_id"] = eos
    bos = g.metadata.get("tokenizer.ggml.bos_token_id")
    if bos is not None:
        cfg["bos_token_id"] = bos
    return cfg


def model_config_from_gguf(g: GgufFile):
    """Architecture config from GGUF metadata (llama-family keys)."""
    from ..engine.config import ModelConfig

    return ModelConfig.from_hf_config(hf_config_from_gguf(g))


# GGUF tokenizer token_type values (ggml vocab semantics)
_TT_NORMAL, _TT_UNKNOWN, _TT_CONTROL = 1, 2, 3
_TT_USER_DEFINED, _TT_UNUSED, _TT_BYTE = 4, 5, 6


def tokenizer_from_gguf(g: GgufFile):
    """Reconstruct a working tokenizer from GGUF metadata.

    GGUF embeds the full vocab (``tokenizer.ggml.tokens`` + scores/types,
    merges for BPE) rather than a tokenizer.json; rebuild the equivalent
    ``tokenizers.Tokenizer`` so a .gguf model can actually tokenize and
    detokenize (reference: lib/llm/src/gguf/* tokenizer reconstruction).

    - ``tokenizer.ggml.model == "llama"`` → SentencePiece-style Unigram
      with byte fallback and the ▁ whitespace convention;
    - ``"gpt2"`` → byte-level BPE from the embedded merges.
    """
    from tokenizers import Tokenizer, decoders, pre_tokenizers
    from tokenizers.models import BPE

    from .tokenizer import add_spm_added_tokens, build_unigram_tokenizer

    md = g.metadata
    tokens = md.get("tokenizer.ggml.tokens")
    if not tokens:
        raise GgufError("GGUF carries no tokenizer.ggml.tokens")
    model_kind = md.get("tokenizer.ggml.model", "llama")
    types = md.get("tokenizer.ggml.token_type") or [_TT_NORMAL] * len(tokens)

    if model_kind == "gpt2":
        merges_raw = md.get("tokenizer.ggml.merges") or []
        merges = [tuple(m.split(" ", 1)) for m in merges_raw if " " in m]
        vocab = {t: i for i, t in enumerate(tokens)}
        tok = Tokenizer(BPE(vocab=vocab, merges=merges))
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
        # GGUF token_type reuses the SPM piece-type ids (_TT_* == _SPM_*)
        add_spm_added_tokens(tok, tokens, types)
        return tok
    if model_kind == "llama":
        scores = md.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
        unk_id = md.get("tokenizer.ggml.unknown_token_id")
        # SPM-semantics construction shared with tokenizer.model loading
        return build_unigram_tokenizer(
            tokens, [float(s) for s in scores], list(types), unk_id
        )
    raise GgufError(f"unsupported GGUF tokenizer model {model_kind!r}")


def mdc_from_gguf(path: str, display_name: Optional[str] = None,
                  kv_block_size: int = 16):
    """ModelDeploymentCard from a .gguf file (reference:
    model_card/create.rs from_gguf)."""
    from .model_card import ModelDeploymentCard, slugify

    g = read_gguf(path)
    name = display_name or g.metadata.get("general.name") or path
    eos = g.metadata.get("tokenizer.ggml.eos_token_id")
    return ModelDeploymentCard(
        display_name=name,
        slug=slugify(str(name)),
        model_path=path,
        context_length=g.arch_key("context_length", 4096),
        kv_block_size=kv_block_size,
        chat_template=g.metadata.get("tokenizer.chat_template"),
        bos_token_id=g.metadata.get("tokenizer.ggml.bos_token_id"),
        eos_token_ids=[eos] if eos is not None else [],
        # HF-shaped so engine_config_from_mdc builds the same ModelConfig
        # a snapshot-backed worker would
        config=hf_config_from_gguf(g),
    )
