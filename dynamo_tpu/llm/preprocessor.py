"""OpenAI ↔ internal translation: prompt templating, tokenization, deltas.

Forward: render the model's chat template (jinja2), tokenize, merge model
defaults into sampling/stop options → ``PreprocessedRequest``.
Backward: wrap ``BackendOutput`` text deltas into OpenAI chat-completion
chunks / completion chunks (SSE payloads).

Reference analog: lib/llm/src/preprocessor.rs:63-359 (OpenAIPreprocessor +
bidirectional Operator + DeltaGenerator) and preprocessor/prompt/template/*
(minijinja chat-template rendering).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, List, Optional, Union

import jinja2

from ..protocols.annotated import (
    ANNOTATION_FORMATTED_PROMPT,
    ANNOTATION_TOKEN_IDS,
    Annotated,
)
from ..protocols.common import (
    BackendOutput,
    FinishReason,
    OutputOptions,
    PreprocessedRequest,
    SamplingOptions,
    StopConditions,
)
from ..protocols.openai import (
    ChatChoiceDelta,
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatStreamChoice,
    ChoiceLogprobs,
    CompletionChoice,
    CompletionRequest,
    CompletionResponse,
    LogprobEntry,
    Usage,
    new_request_id,
)
from ..runtime.engine import AsyncEngine, Context, EngineError
from ..runtime.pipeline import Operator
from .model_card import ModelDeploymentCard
from .tokenizer import HFTokenizer

logger = logging.getLogger(__name__)

FALLBACK_CHAT_TEMPLATE = (
    "{% for message in messages %}"
    "{{ message.role }}: {{ message.content }}\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}assistant: {% endif %}"
)


def _child_request(preprocessed, i: int, output_options=None):
    """One seeded single-sample child of a fanned-out request (the n-way
    fan-out and the buffered best_of path share this): n=1, seed offset
    by the child index so seeded requests stay reproducible but
    distinct, annotation side-channels off."""
    import dataclasses as _dc

    seed = preprocessed.sampling_options.seed
    samp = _dc.replace(
        preprocessed.sampling_options, n=1,
        seed=(seed + i) if seed is not None else None,
    )
    return _dc.replace(
        preprocessed, sampling_options=samp,
        output_options=output_options or preprocessed.output_options,
        annotation_values={},
    )


class PromptFormatter:
    """Jinja2 chat-template renderer (HF tokenizer_config semantics)."""

    def __init__(self, template: Optional[str], bos_token: str = "", eos_token: str = ""):
        env = jinja2.Environment(
            loader=jinja2.BaseLoader(), keep_trailing_newline=True
        )
        env.globals["raise_exception"] = self._raise
        env.filters.setdefault("tojson", lambda v, **kw: jinja2.utils.htmlsafe_json_dumps(v))
        self.template = env.from_string(template or FALLBACK_CHAT_TEMPLATE)
        self.bos_token = bos_token
        self.eos_token = eos_token

    @staticmethod
    def _raise(msg):
        raise EngineError(f"chat template error: {msg}")

    def render(self, messages: List[dict], add_generation_prompt: bool = True, **extra) -> str:
        return self.template.render(
            messages=messages,
            add_generation_prompt=add_generation_prompt,
            bos_token=self.bos_token,
            eos_token=self.eos_token,
            **extra,
        )


class OpenAIPreprocessor(Operator):
    """Bidirectional operator: OpenAI request in, OpenAI chunks out."""

    def __init__(self, mdc: ModelDeploymentCard, tokenizer: Optional[HFTokenizer] = None):
        self.mdc = mdc
        self.tokenizer = tokenizer or (
            HFTokenizer.from_model_path(mdc.model_path) if mdc.model_path else None
        )
        self.formatter = PromptFormatter(
            mdc.chat_template, mdc.bos_token or "", mdc.eos_token or ""
        )
        # fail at construction, not after a full generation has been spent
        if mdc.tool_call_format is not None:
            from .tools import FORMATS

            if mdc.tool_call_format not in FORMATS:
                raise EngineError(
                    f"unknown tool_call_format {mdc.tool_call_format!r}; "
                    f"use one of {FORMATS} or None to disable"
                )

    # ---------- forward: request translation ----------

    def preprocess_chat(self, req: ChatCompletionRequest) -> PreprocessedRequest:
        self._validate_tool_choice(req)
        use_raw = bool(req.nvext and req.nvext.use_raw_prompt)
        if use_raw and req.messages:
            prompt = "".join(m.text_content() for m in req.messages)
        else:
            prompt = self.formatter.render(
                [m.model_dump(exclude_none=True) for m in req.messages],
                add_generation_prompt=True,
                tools=req.tools,
            )
        token_ids = self._tokenize(prompt)
        return self._build(req, token_ids, prompt, max_tokens=req.effective_max_tokens())

    def preprocess_completion(self, req: CompletionRequest) -> PreprocessedRequest:
        if req.best_of is not None and req.best_of != (req.n or 1):
            # OpenAI semantics: best_of candidates are generated
            # server-side and the n highest-cumulative-logprob ones
            # returned; that selection needs complete outputs, so it
            # cannot stream, and best_of < n has nothing to select
            if req.best_of < (req.n or 1):
                raise EngineError("best_of must be >= n")
            if req.best_of > 20:  # OpenAI's cap; also bounds the fan-out
                raise EngineError("best_of must be <= 20")
            if req.stream:
                raise EngineError("best_of cannot be used with streaming")
            if req.echo:
                raise EngineError("best_of cannot be combined with echo")
            if (req.temperature is not None and req.temperature == 0) or (
                    req.nvext and req.nvext.greed_sampling):
                # greedy candidates are identical: the selection is
                # meaningless and the client pays best_of x the tokens
                raise EngineError(
                    "best_of > n requires sampling (temperature > 0)"
                )
        prompt = req.prompt
        if isinstance(prompt, list) and prompt and isinstance(prompt[0], int):
            token_ids = list(prompt)
            prompt_text = None
        elif isinstance(prompt, str):
            token_ids = self._tokenize(prompt)
            prompt_text = prompt
        else:
            raise EngineError("batch prompts must be dispatched one at a time")
        return self._build(req, token_ids, prompt_text, max_tokens=req.max_tokens)

    def _tokenize(self, prompt: str) -> List[int]:
        if self.tokenizer is None:
            raise EngineError(f"no tokenizer available for {self.mdc.display_name}")
        return self.tokenizer.encode(prompt)

    @staticmethod
    def _validate_tool_choice(req: ChatCompletionRequest) -> None:
        """Reject malformed ``tool_choice`` at the door (the named-
        function and "required" forms the reference's delta layer left
        unimplemented at chat_completions/delta.rs:131 — a full
        generation must not be spent before a bad name 400s)."""
        tc = req.tool_choice
        if tc is None or tc in ("none", "auto", "required"):
            if tc == "required" and not req.tools:
                raise EngineError("tool_choice='required' needs tools")
            return
        if isinstance(tc, dict):
            if tc.get("type") != "function":
                raise EngineError(
                    "tool_choice object must be "
                    '{"type": "function", "function": {"name": ...}}'
                )
            name = (tc.get("function") or {}).get("name")
            if not name or not isinstance(name, str):
                raise EngineError("tool_choice.function.name is required")
            names = {
                (t.get("function") or {}).get("name")
                for t in (req.tools or []) if isinstance(t, dict)
            }
            if name not in names:
                raise EngineError(
                    f"tool_choice function {name!r} is not in tools"
                )
            return
        raise EngineError(f"unsupported tool_choice {tc!r}")

    @staticmethod
    def _guided_choice(req) -> Optional[List[str]]:
        """vLLM-style ``guided_choice`` extra field (top level or nvext):
        constrain the completion to exactly one of the given strings.
        Present-but-empty is rejected — silently dropping the constraint
        would hand unconstrained text to a client that relies on it."""
        choices = (req.model_extra or {}).get("guided_choice")
        if choices is None and req.nvext is not None:
            choices = (req.nvext.model_extra or {}).get("guided_choice")
        if choices is None:
            return None
        if (not isinstance(choices, list) or not choices or not all(
                isinstance(c, str) and c for c in choices)):
            raise EngineError(
                "guided_choice must be a non-empty list of non-empty strings"
            )
        return list(choices)

    @staticmethod
    def _guided_json(req) -> Optional[dict]:
        """Guided JSON spec from ``response_format`` (OpenAI) or the
        vLLM-style ``guided_json`` extra field (whose value IS the
        schema). Validated here by compiling the schema — unsupported
        keywords must 400 at the door, not crash the engine loop."""
        spec = None
        rf = getattr(req, "response_format", None)
        if rf and rf.get("type") == "json_object":
            spec = {"type": "json_object"}
        elif rf and rf.get("type") == "json_schema":
            spec = {"type": "json_schema",
                    "schema": rf["json_schema"]["schema"]}
        else:
            gj = (req.model_extra or {}).get("guided_json")
            if gj is None and req.nvext is not None:
                gj = (req.nvext.model_extra or {}).get("guided_json")
            if gj is not None:
                if not isinstance(gj, dict):
                    raise EngineError(
                        "guided_json must be a JSON-schema object"
                    )
                spec = {"type": "json_schema", "schema": gj}
        if spec is None:
            return None
        from ..engine.guided import compile_schema

        try:
            if spec["type"] == "json_schema":
                compile_schema(spec["schema"])
        except ValueError as e:
            raise EngineError(str(e))
        return spec

    def _guided_choice_ids(
        self, choices: Optional[List[str]]
    ) -> Optional[List[List[int]]]:
        if not choices:
            return None
        if self.tokenizer is None:
            raise EngineError(
                "guided_choice requires a tokenizer (the choices must be "
                "tokenized before the engine can constrain to them)"
            )
        # canonical-tokenization semantics: the engine constrains the
        # output to each choice's whole-string token sequence (no
        # special tokens — the choice is completion text)
        return [
            list(self.tokenizer.encode(c, add_special_tokens=False))
            for c in choices
        ]

    def _stop_token_seqs(
        self, stop_list: Optional[List[str]]
    ) -> Optional[List[List[int]]]:
        """Canonical tokenization of each stop string — the engine's
        device-approximate stop check (the persistent chain's suffix
        ring) matches these token sequences; the backend detokenizer
        jail still catches every OTHER tokenization of the same text,
        so a missing/empty entry only loses the chain fast-path. Best
        effort: a tokenizer-less preprocessor ships None."""
        if not stop_list or self.tokenizer is None:
            return None
        seqs = []
        for s in stop_list:
            try:
                seqs.append(list(
                    self.tokenizer.encode(s, add_special_tokens=False)
                ))
            except Exception:
                # partial coverage reads as unavailable (the request
                # keeps the backend jail; the engine only loses the
                # chain fast-path) — worth a line, not a failure
                logger.debug("stop string %r not tokenizable; engine "
                             "stop-seq fast-path disabled", s)
                return None
        return seqs if all(seqs) else None

    def _build(
        self,
        req: Union[ChatCompletionRequest, CompletionRequest],
        token_ids: List[int],
        prompt_text: Optional[str],
        max_tokens: Optional[int],
    ) -> PreprocessedRequest:
        if len(token_ids) >= self.mdc.context_length:
            raise EngineError(
                f"prompt length {len(token_ids)} exceeds context window "
                f"{self.mdc.context_length}"
            )
        ignore_eos = bool(req.ignore_eos or (req.nvext and req.nvext.ignore_eos))
        # nvext.greed_sampling forces greedy regardless of temperature
        # (reference nvext surface)
        temperature = (
            0.0 if (req.nvext and req.nvext.greed_sampling)
            else req.temperature
        )
        budget = self.mdc.context_length - len(token_ids)
        guided = self._guided_choice(req)
        guided_json = self._guided_json(req)
        if guided and guided_json:
            raise EngineError(
                "guided_choice and guided JSON (response_format/"
                "guided_json) are mutually exclusive"
            )
        stop_list = req.stop_list() or None
        out = PreprocessedRequest(
            token_ids=token_ids,
            stop_conditions=StopConditions(
                # `is not None`: an explicit max_tokens=0 means an EMPTY
                # completion, not the full context budget
                max_tokens=(
                    min(max_tokens, budget) if max_tokens is not None
                    else budget
                ),
                min_tokens=req.min_tokens,
                stop=stop_list,
                ignore_eos=ignore_eos,
                stop_token_seqs=self._stop_token_seqs(stop_list),
            ),
            sampling_options=SamplingOptions(
                n=req.n,
                temperature=temperature,
                top_p=req.top_p,
                top_k=req.top_k,
                min_p=req.min_p,
                frequency_penalty=req.frequency_penalty,
                presence_penalty=req.presence_penalty,
                repetition_penalty=req.repetition_penalty,
                seed=req.seed,
                # OpenAI wire uses string token-id keys; clamp per spec
                logit_bias={
                    int(k): max(-100.0, min(100.0, float(v)))
                    for k, v in req.logit_bias.items()
                } if getattr(req, "logit_bias", None) else None,
                guided_choice=guided,
                guided_choice_token_ids=self._guided_choice_ids(guided),
                guided_json=guided_json,
            ),
            output_options=OutputOptions(
                logprobs=self._logprobs_count(req),
                # OpenAI legacy completions: echo + logprobs returns the
                # prompt tokens' logprobs too (chat has no echo attr)
                prompt_logprobs=(
                    self._logprobs_count(req)
                    if getattr(req, "echo", False)
                    and self._logprobs_count(req) is not None
                    else None
                ),
                echo_prompt=bool(getattr(req, "echo", False)),
            ),
            eos_token_ids=list(self.mdc.eos_token_ids),
            model=req.model,
            mdc_checksum=self.mdc.checksum,
            annotations=list((req.nvext and req.nvext.annotations) or []),
        )
        # payloads for requested annotations (generate() turns them into
        # Annotated events ahead of the stream — reference
        # preprocessor.rs:134-160 formatted_prompt/token_ids)
        if ANNOTATION_FORMATTED_PROMPT in out.annotations and prompt_text is not None:
            out.annotation_values[ANNOTATION_FORMATTED_PROMPT] = prompt_text
        if ANNOTATION_TOKEN_IDS in out.annotations:
            out.annotation_values[ANNOTATION_TOKEN_IDS] = list(token_ids)
        return out

    # ---------- backward: response translation ----------

    @staticmethod
    def _logprobs_count(req) -> Optional[int]:
        """OpenAI logprobs fields → alternatives count (None = off).

        Chat: ``logprobs: true`` + optional ``top_logprobs`` (0 means
        "chosen token only, no alternatives"). Completions: ``logprobs``
        IS the count, 0 included.
        """
        lp = getattr(req, "logprobs", None)
        if isinstance(lp, bool):
            if not lp:
                return None
            top = getattr(req, "top_logprobs", None)
            return int(top) if top is not None else 0
        if isinstance(lp, int):
            return int(lp)
        return None

    async def chat_stream(
        self,
        request_id: str,
        model: str,
        backend_stream: AsyncIterator[BackendOutput],
        prompt_tokens: int,
        include_usage: bool = False,
        tool_format: Optional[str] = None,
        tool_jail: bool = False,
    ) -> AsyncIterator[ChatCompletionChunk]:
        """BackendOutput deltas → OpenAI chat chunks (role chunk first).

        When ``tool_format`` is set (the request carried tools and
        tool_choice != "none"), content is held back and the finished text
        is parsed for tool calls (llm/tools.py): a successful parse emits
        ONE delta carrying ``tool_calls`` with finish_reason="tool_calls"
        — clients never see the raw call syntax as text; a failed parse
        flushes the buffered text as ordinary content. ``tool_jail``
        withholds from token 0: a forced call (tool_choice "required" or
        a named function) means the whole output IS the call, so no
        prose should stream while waiting for a marker."""
        yield ChatCompletionChunk(
            id=request_id,
            model=model,
            choices=[ChatStreamChoice(delta=ChatChoiceDelta(role="assistant"))],
        )
        completion_tokens = 0
        buffered: List[str] = []
        buffered_lps: List[LogprobEntry] = []
        last_finish: Optional[str] = None
        # tool-call jail: with tools enabled, stream prose NORMALLY and
        # withhold text only from a potential call marker onward — holding
        # the whole generation (as a naive buffer-then-parse would) turns
        # TTFT into full-generation latency for plain prose answers
        from .tools import marker_prefix_len as _marker_prefix_len
        from .tools import stream_markers as _tool_stream_markers

        markers = (
            _tool_stream_markers(tool_format) if tool_format is not None
            else ()
        )
        pending = ""    # streamed-side tail that may be a marker prefix
        # logprob entries for exactly the tokens whose text sits in
        # ``pending`` — released text carries its own entries, withheld
        # text buffers its own (no duplication across the jail boundary)
        pending_lps: List[LogprobEntry] = []
        jailed = tool_jail and tool_format is not None
        first_text = True

        def _split_lps(entries: List[LogprobEntry], nchars: int,
                       total_chars: int):
            """Split entries at a character boundary of their joint text.

            When the vocab piece strings sum to the decoded text's length
            (plain-ASCII tokens), a token-length walk is exact; a token
            straddling the boundary goes to the withheld side, matching
            the withheld marker token. Byte-fallback / multi-byte pieces
            decode to different lengths than their piece strings — then
            the split falls back to proportional-by-count: boundary
            placement is approximate but every entry still lands on
            exactly one side (no duplication, no loss)."""
            if not entries:
                return [], []
            if sum(len(e.token or "") for e in entries) == total_chars:
                used = 0
                for i, e in enumerate(entries):
                    tl = len(e.token or "")
                    if used + tl > nchars:
                        return entries[:i], entries[i:]
                    used += tl
                return entries, []
            i = int(round(nchars / max(total_chars, 1) * len(entries)))
            return entries[:i], entries[i:]

        def _chunk(text: str, lp=None, finish=None) -> ChatCompletionChunk:
            return ChatCompletionChunk(
                id=request_id,
                model=model,
                choices=[ChatStreamChoice(
                    delta=ChatChoiceDelta(content=text or None),
                    finish_reason=finish,
                    logprobs=lp,
                )],
            )

        async for out in backend_stream:
            completion_tokens = max(completion_tokens, out.cum_tokens)
            if tool_format is None:
                # out.logprobs without text: the detokenizer held this
                # token's characters (multi-byte piece mid-sequence) —
                # the entry must still reach the client or counts drift
                if out.text or out.finish_reason or out.logprobs:
                    yield _chunk(
                        out.text, self._logprobs(out),
                        out.finish_reason.to_openai() if out.finish_reason
                        else None,
                    )
                continue

            lp = self._logprobs(out)
            if out.finish_reason:
                last_finish = out.finish_reason.to_openai()
            if not jailed and out.text:
                if (first_text and tool_format in ("json", "auto")
                        and out.text.lstrip()[:1] in ("{", "[")):
                    # a leading JSON value is the json tool-call form —
                    # no later marker would flag it. Only those formats:
                    # for hermes/mistral a '[1] footnote...' opener is
                    # ordinary prose and must stream
                    jailed = True
                if out.text.strip():
                    first_text = False
            if jailed:
                if pending:
                    buffered.insert(0, pending)
                    pending = ""
                    buffered_lps[:0] = pending_lps
                    pending_lps = []
                if out.text:
                    buffered.append(out.text)
                if lp and lp.content:
                    buffered_lps.extend(lp.content)
                continue
            pending += out.text or ""
            if lp and lp.content:
                pending_lps.extend(lp.content)
            hit = min(
                (pending.find(m) for m in markers if pending.find(m) >= 0),
                default=-1,
            )
            if hit >= 0:
                # prose before the marker streams WITH its logprob
                # entries; the marker and everything after is withheld
                # for parsing (its entries ride the final parsed chunk)
                jailed = True
                total = len(pending)
                release, held = pending[:hit], pending[hit:]
                pending = ""
                rel_lps, held_lps = _split_lps(pending_lps, hit, total)
                pending_lps = []
                if held:
                    buffered.append(held)
                buffered_lps.extend(held_lps)
            else:
                keep = _marker_prefix_len(pending, markers)
                total = len(pending)
                release = pending[: len(pending) - keep] if keep else pending
                pending = pending[len(pending) - keep:] if keep else ""
                rel_lps, pending_lps = _split_lps(
                    pending_lps, len(release), total
                )
            if release:
                yield _chunk(
                    release,
                    ChoiceLogprobs(content=rel_lps) if rel_lps else None,
                )

        if tool_format is not None:
            from .tools import extract_tool_calls

            if jailed:
                text = "".join(buffered)
                content, calls = extract_tool_calls(text, tool_format)
                final_lps = buffered_lps
            else:
                # no marker ever appeared — whatever tail is pending is
                # plain prose (its entries never buffered: they're here)
                text, content, calls = pending, pending, []
                final_lps = buffered_lps + pending_lps
            lps = ChoiceLogprobs(content=final_lps) if final_lps else None

            def _tc_chunk(entries, finish=None, lp=None):
                return ChatCompletionChunk(
                    id=request_id,
                    model=model,
                    choices=[ChatStreamChoice(
                        delta=ChatChoiceDelta(tool_calls=entries),
                        finish_reason=finish,
                        logprobs=lp,
                    )],
                )

            if calls:
                # the OpenAI streamed tool-call shape (the delta layer the
                # reference left unimplemented at chat_completions/
                # delta.rs:131 — its deltas always carried tool_calls:
                # None; forced tool_choice, handled via tool_jail above,
                # was the remaining piece): per call, a header delta
                # carrying index/id/type/function.name with empty
                # arguments, then argument deltas carrying only
                # {index, function.arguments} fragments for the client to
                # concatenate. The closing chunk carries
                # finish_reason="tool_calls" plus the withheld tokens'
                # logprob entries.
                if content:
                    # prose around the call blocks is real content —
                    # OpenAI responses carry it alongside tool_calls
                    yield _chunk(content)
                for i, call in enumerate(calls):
                    yield _tc_chunk([{
                        "index": i,
                        "id": call["id"],
                        "type": call["type"],
                        "function": {
                            "name": call["function"]["name"],
                            "arguments": "",
                        },
                    }])
                    args = call["function"]["arguments"]
                    if args:
                        yield _tc_chunk([{
                            "index": i,
                            "function": {"arguments": args},
                        }])
                yield _tc_chunk(None, finish="tool_calls", lp=lps)
            else:
                yield _chunk(content, lps, last_finish or "stop")
        if include_usage:
            yield ChatCompletionChunk(
                id=request_id,
                model=model,
                choices=[],
                usage=Usage(
                    prompt_tokens=prompt_tokens,
                    completion_tokens=completion_tokens,
                    total_tokens=prompt_tokens + completion_tokens,
                ),
            )

    def _token_str(self, tid: int) -> str:
        """Display string for one vocab id (chat and legacy-completions
        logprob blocks must render tokens identically)."""
        return (self.tokenizer.id_to_token(tid)
                if self.tokenizer else str(tid)) or str(tid)

    def _logprobs(self, out: BackendOutput) -> Optional[ChoiceLogprobs]:
        if not out.logprobs:
            return None
        entries = []
        for lp in out.logprobs:
            entries.append(
                LogprobEntry(
                    token=self._token_str(lp.token_id),
                    logprob=lp.logprob,
                    top_logprobs=[
                        {"token": self._token_str(t), "logprob": p}
                        for t, p in (lp.top or {}).items()
                    ],
                )
            )
        return ChoiceLogprobs(content=entries)

    def _legacy_logprobs_block(self, entries, offsets) -> dict:
        """tokens / token_logprobs / top_logprobs / text_offset from
        TokenLogprob entries + their text offsets (one rendering shared
        by the streaming chunks and the buffered best_of path)."""
        return {
            "tokens": [self._token_str(e.token_id) for e in entries],
            "token_logprobs": [e.logprob for e in entries],
            # one entry per token even when all None: the aggregator
            # concatenates blocks, so a collapsed list would shift later
            # chunks' top entries onto the wrong tokens
            "top_logprobs": [
                {self._token_str(t): p for t, p in e.top.items()}
                if e.top else None
                for e in entries
            ],
            "text_offset": list(offsets),
        }

    def _completion_logprobs_dict(self, out: BackendOutput) -> Optional[dict]:
        """OpenAI legacy completions logprobs block for one generation
        chunk. Offsets are chunk-relative; with one token per chunk (the
        decode stream's shape) they are exact, and a multi-token chunk
        (the stop-string jail releasing buffered prose) splits the chunk
        text proportionally — same fallback the chat path uses."""
        if not out.logprobs:
            return None
        n = len(out.logprobs)
        text_len = len(out.text or "")
        offs = [int(round(i / n * text_len)) for i in range(n)]
        return self._legacy_logprobs_block(out.logprobs, offs)

    def _prompt_logprobs_dict(self, token_ids, prompt_lps) -> dict:
        """OpenAI legacy completions logprobs block for the echoed prompt:
        tokens / token_logprobs / text_offset (first entry None — the
        first prompt token has no conditioning prefix).

        Offsets index into the DECODED echo text, so each token string is
        the decoded-prefix delta (raw vocab pieces — byte-fallback,
        BPE space markers — have different lengths than the text they
        decode to and would drift every subsequent offset)."""
        token_ids = list(token_ids)
        if self.tokenizer is not None and hasattr(self.tokenizer, "decode"):
            prefixes = [""] + [
                self.tokenizer.decode(token_ids[: i + 1])
                for i in range(len(token_ids))
            ]
            toks = [
                prefixes[i + 1][len(prefixes[i]):]
                for i in range(len(token_ids))
            ]
            offsets = [len(prefixes[i]) for i in range(len(token_ids))]
        else:
            toks = [
                (self.tokenizer.id_to_token(t) if self.tokenizer else str(t))
                or str(t)
                for t in token_ids
            ]
            offsets, pos = [], 0
            for t in toks:
                offsets.append(pos)
                pos += len(t)
        return {
            "tokens": toks,
            "token_logprobs": list(prompt_lps[: len(toks)]),
            # per-token placeholders keep the aggregate list aligned with
            # tokens when generation chunks append their top entries
            "top_logprobs": [None] * len(toks),
            "text_offset": offsets,
        }

    async def completion_stream(
        self,
        request_id: str,
        model: str,
        backend_stream: AsyncIterator[BackendOutput],
        prompt_tokens: int,
        include_usage: bool = False,
        echo_text: Optional[str] = None,
        prompt_token_ids: Optional[List[int]] = None,
    ) -> AsyncIterator[CompletionResponse]:
        completion_tokens = 0
        # with prompt_token_ids the echo chunk waits for the first
        # backend output, which carries the prompt logprobs (the engine
        # computes them during prefill)
        echo_pending = bool(echo_text) and prompt_token_ids is not None
        if echo_text and not echo_pending:
            # OpenAI `echo`: the prompt leads the completion text
            yield CompletionResponse(
                id=request_id,
                model=model,
                choices=[CompletionChoice(text=echo_text, finish_reason=None)],
            )
        async for out in backend_stream:
            completion_tokens = max(completion_tokens, out.cum_tokens)
            if echo_pending:
                echo_pending = False
                lp_dict = (
                    self._prompt_logprobs_dict(
                        prompt_token_ids, out.prompt_logprobs
                    )
                    if out.prompt_logprobs is not None else None
                )
                yield CompletionResponse(
                    id=request_id,
                    model=model,
                    choices=[CompletionChoice(
                        text=echo_text, finish_reason=None, logprobs=lp_dict,
                    )],
                )
            # out.logprobs without text: the detokenizer held this token's
            # characters (multi-byte piece) — its entry must still flow
            if out.text or out.finish_reason or out.logprobs:
                yield CompletionResponse(
                    id=request_id,
                    model=model,
                    choices=[
                        CompletionChoice(
                            text=out.text or "",
                            finish_reason=out.finish_reason.to_openai()
                            if out.finish_reason
                            else None,
                            # legacy logprobs block for this chunk's
                            # tokens; offsets are chunk-relative (the
                            # aggregator rebases onto accumulated text)
                            logprobs=self._completion_logprobs_dict(out),
                        )
                    ],
                )
        if echo_pending:
            # the backend stream ended without a single output (immediate
            # cancel/zero-token completion) — the client still must get the
            # echoed prompt text, just without prompt logprobs
            yield CompletionResponse(
                id=request_id,
                model=model,
                choices=[CompletionChoice(text=echo_text, finish_reason=None)],
            )
        if include_usage:
            yield CompletionResponse(
                id=request_id,
                model=model,
                choices=[],
                usage=Usage(
                    prompt_tokens=prompt_tokens,
                    completion_tokens=completion_tokens,
                    total_tokens=prompt_tokens + completion_tokens,
                ),
            )

    # ---------- Operator impl (dispatches on request type) ----------

    async def generate(
        self,
        request: Context[Union[ChatCompletionRequest, CompletionRequest]],
        next_engine: AsyncEngine,
    ) -> AsyncIterator[Any]:
        req = request.payload
        is_chat = isinstance(req, ChatCompletionRequest)
        request.add_stage("preprocess")
        if is_chat:
            preprocessed = self.preprocess_chat(req)
            request_id = new_request_id()
        else:
            preprocessed = self.preprocess_completion(req)
            request_id = new_request_id("cmpl")
        # requested annotations stream ahead of the data as named events
        for name, value in preprocessed.annotation_values.items():
            yield Annotated.from_annotation(name, value)
        request.add_stage("generate")
        # OpenAI semantics: non-streaming responses ALWAYS carry usage;
        # streaming only includes the final usage chunk on opt-in
        include_usage = bool(
            (req.stream_options and req.stream_options.include_usage)
            or not getattr(req, "stream", False)
        )
        kwargs = {}
        # tool_call_format=None on the card disables parsing entirely
        if (is_chat and req.tools and req.tool_choice != "none"
                and self.mdc.tool_call_format is not None):
            kwargs["tool_format"] = self.mdc.tool_call_format
            if (req.tool_choice == "required"
                    or isinstance(req.tool_choice, dict)):
                # forced call (validated in preprocess): the entire
                # output is expected to be the call — withhold from
                # token 0 rather than waiting for a marker
                kwargs["tool_jail"] = True
        if not is_chat and preprocessed.output_options.echo_prompt:
            kwargs["echo_text"] = (
                req.prompt if isinstance(req.prompt, str)
                else self.tokenizer.decode(preprocessed.token_ids)
                if self.tokenizer else None
            )
            if preprocessed.output_options.prompt_logprobs is not None:
                kwargs["prompt_token_ids"] = list(preprocessed.token_ids)
        translate = self.chat_stream if is_chat else self.completion_stream

        n = preprocessed.sampling_options.n or 1
        best_of = (getattr(req, "best_of", None) or n) if not is_chat else n
        if best_of > n:
            # OpenAI best_of: generate best_of candidates, return the n
            # with the highest cumulative logprob (buffered — selection
            # needs complete outputs; preprocess rejected stream/echo)
            async for chunk in self._best_of(
                best_of, n, request, preprocessed, next_engine,
                request_id, req.model,
            ):
                yield chunk
            return
        if n > 1:
            # n-way fan-out: n independent engine streams, choice indices
            # rewritten per stream, usage summed into one final chunk
            # (reference parity: SamplingOptions carries n,
            # lib/llm/src/protocols/common.rs:248-316)
            async for chunk in self._fan_out(
                n, request, preprocessed, next_engine, translate,
                request_id, req.model, include_usage, kwargs,
            ):
                yield chunk
            return

        backend_stream = next_engine.generate(request.map(preprocessed))
        async for chunk in translate(
            request_id,
            req.model,
            backend_stream,
            prompt_tokens=len(preprocessed.token_ids),
            include_usage=include_usage,
            **kwargs,
        ):
            yield chunk

    async def _best_of(
        self, best_of, n, request, preprocessed, next_engine,
        request_id, model,
    ):
        """OpenAI legacy best_of: run ``best_of`` buffered candidates and
        return the ``n`` highest-cumulative-logprob completions.

        Candidates are forced to compute chosen-token logprobs (the
        ranking signal) even when the client asked for none; blocks are
        attached to the response only when the client did ask. Usage
        counts EVERY candidate's tokens — all of them were generated.
        Reference parity: SamplingOptions carries n/best_of
        (lib/llm/src/protocols/common.rs:248-316).
        """
        import dataclasses as _dc

        from ..runtime.engine import AsyncEngineContext

        prompt_tokens = len(preprocessed.token_ids)
        want_lp = preprocessed.output_options.logprobs
        child_ctxs = [
            AsyncEngineContext(trace_id=request.context.trace_id)
            for _ in range(best_of)
        ]

        async def relay_stop() -> None:
            await request.context.wait_stopped()
            for c in child_ctxs:
                c.stop_generating()

        # ranking needs chosen-token logprobs even when the client asked
        # for none (0 = chosen only, no alternatives)
        oo = _dc.replace(
            preprocessed.output_options,
            logprobs=want_lp if want_lp is not None else 0,
        )

        async def one(i: int):
            sub = _child_request(preprocessed, i, output_options=oo)
            sub_ctx = Context(sub, child_ctxs[i], dict(request.baggage))
            text, cum, ntoks, finish = "", 0.0, 0, None
            entries, offs = [], []
            async for out in next_engine.generate(sub_ctx):
                base, ln = len(text), len(out.text or "")
                if out.text:
                    text += out.text
                if out.logprobs:
                    m = len(out.logprobs)
                    for j, lp in enumerate(out.logprobs):
                        cum += lp.logprob
                        entries.append(lp)
                        offs.append(base + int(round(j / m * ln)))
                ntoks = max(ntoks, out.cum_tokens)
                if out.finish_reason:
                    finish = out.finish_reason.to_openai()
            return text, cum, ntoks, finish, entries, offs

        stop_task = asyncio.ensure_future(relay_stop())
        try:
            results = await asyncio.gather(*(one(i) for i in range(best_of)))
        finally:
            stop_task.cancel()
            for c in child_ctxs:
                c.stop_generating()
            request.context.merge_stages_from(child_ctxs)

        # OpenAI's documented selection: highest log probability PER
        # TOKEN — raw cumulative sums would systematically favor short
        # (early-stopping) candidates
        ranked = sorted(
            results, key=lambda r: r[1] / max(len(r[4]), 1), reverse=True
        )[:n]
        choices = []
        for idx, (text, _cum, _nt, finish, entries, offs) in enumerate(ranked):
            lp_dict = (
                self._legacy_logprobs_block(entries, offs)
                if want_lp is not None and entries else None
            )
            choices.append(CompletionChoice(
                index=idx, text=text, finish_reason=finish, logprobs=lp_dict,
            ))
        completion_tokens = sum(r[2] for r in results)
        yield CompletionResponse(
            id=request_id, model=model, choices=choices,
            usage=Usage(
                prompt_tokens=prompt_tokens,
                completion_tokens=completion_tokens,
                total_tokens=prompt_tokens + completion_tokens,
            ),
        )

    async def _fan_out(
        self, n, request, preprocessed, next_engine, translate,
        request_id, model, include_usage, kwargs,
    ):
        """Run n independent sampled continuations of one prompt.

        Each choice gets its own engine request (n=1, seed offset by the
        choice index so seeded requests stay reproducible but distinct)
        and streams concurrently; chunks are re-indexed per choice and
        usage totals combine at the end."""
        import dataclasses as _dc

        from ..runtime.engine import AsyncEngineContext

        prompt_tokens = len(preprocessed.token_ids)
        # bounded: children block in put() once the consumer lags,
        # restoring the pull-based flow control the single-stream path
        # gets for free. No sentinels ride the queue — completion/errors
        # surface through the gather below, so a cancelled child never
        # wedges on a full queue.
        queue: asyncio.Queue = asyncio.Queue(maxsize=16)
        usage_total = Usage(prompt_tokens=prompt_tokens)
        # each choice gets its OWN engine context: an engine finishing one
        # choice stops that choice's context in its finally, which with a
        # shared context would truncate the sibling streams mid-generation
        child_ctxs = [
            AsyncEngineContext(trace_id=request.context.trace_id)
            for _ in range(n)
        ]

        async def relay_stop() -> None:
            # client disconnect on the parent fans out to every child
            await request.context.wait_stopped()
            for c in child_ctxs:
                c.stop_generating()

        async def one_choice(i: int) -> None:
            sub = _child_request(preprocessed, i)
            sub_ctx = Context(sub, child_ctxs[i], dict(request.baggage))
            async for chunk in translate(
                request_id, model, next_engine.generate(sub_ctx),
                prompt_tokens=prompt_tokens, include_usage=include_usage,
                **kwargs,
            ):
                if getattr(chunk, "usage", None) is not None:
                    usage_total.completion_tokens += chunk.usage.completion_tokens
                    continue
                for choice in chunk.choices:
                    choice.index = i
                await queue.put(chunk)

        tasks = [asyncio.ensure_future(one_choice(i)) for i in range(n)]
        stop_task = asyncio.ensure_future(relay_stop())
        all_done = asyncio.gather(*tasks)
        get_task = None
        try:
            while True:
                get_task = asyncio.ensure_future(queue.get())
                await asyncio.wait(
                    {get_task, all_done}, return_when=asyncio.FIRST_COMPLETED
                )
                if get_task.done():
                    yield get_task.result()
                    continue
                get_task.cancel()
                while not queue.empty():
                    yield queue.get_nowait()
                all_done.result()  # re-raises the first child failure
                break
        finally:
            if get_task is not None:
                get_task.cancel()
            stop_task.cancel()
            all_done.cancel()
            for t in tasks:
                t.cancel()
            for c in child_ctxs:
                c.stop_generating()
            request.context.merge_stages_from(child_ctxs)
        if include_usage:
            usage_total.total_tokens = (
                usage_total.prompt_tokens + usage_total.completion_tokens
            )
            chunk_cls = (
                ChatCompletionChunk
                if translate == self.chat_stream
                else CompletionResponse
            )
            yield chunk_cls(
                id=request_id, model=model, choices=[], usage=usage_total
            )
