"""Out-of-process engine hosting: supervised subprocess + framed IPC.

Reference analog: the reference runs GPU engines as supervised child
processes with an IPC plane and liveness checks (reference:
lib/engines/sglang/src/worker.rs:307-445 spawn/monitor/respawn,
lib/engines/vllm0_7/src/worker.rs:96-115, ZMQ plane
lib/runtime/src/transports/zmq.rs:98-418). Here the same isolation is
built TPU-first: the hazard this quarantines is not a CUDA OOM but a
pathological Mosaic/XLA compile that can hang an entire host process
(and, through it, the worker's lease bookkeeping). The engine child can
hang or die arbitrarily; the hosting worker stays alive, fails the
in-flight requests cleanly through the error prologue, and respawns.

Plane layout (one unix socket per engine, frames are the runtime's
4-byte length-prefixed msgpack maps — same codec as runtime/network.py):

    parent → child:  {t: "init", engine_args}          once, first
                     {t: "req",  id, payload}          start a stream
                     {t: "stop", id} | {t: "kill", id} cancel a stream
                     {t: "ping", n}                    heartbeat
                     {t: "shutdown"}                   graceful exit
    child → parent:  {t: "ready"} | {t: "init_error", error}
                     {t: "data", id, payload}
                     {t: "end",  id} | {t: "error", id, error}
                     {t: "pong", n}

Streams multiplex over the one socket by request id. Heartbeats ride the
same socket on purpose: a child whose event loop is wedged (compile hang
in the import path, user code blocking the loop) stops ponging even
though the process is alive — exactly the failure kill -9 can't detect
from the outside.

Supervision policy: a child that exits, breaks the socket, or misses
``heartbeat_misses`` consecutive pongs is SIGKILLed; every in-flight
request fails with ``EngineError`` (before first output → the network
layer's error prologue) or ``EngineStreamDied`` (mid-stream). The next
``generate`` respawns lazily, up to ``max_restarts`` consecutive
failed spawns with exponential backoff; a successful init resets the
budget.

Engine-author contract: the heartbeat measures the child's EVENT LOOP,
so a ``generate`` that runs long synchronous work inline (a blocking
jit compile, CPU tokenization loops) will stop ponging and be killed as
wedged. Run sync work through ``run_in_executor`` (as
examples/external_engine/engine.py does) — or raise the budget: the
defaults (5s × 6 misses ≈ 30s) and ``init_timeout_s`` are tunable per
engine via the CLI's ``--engine-heartbeat-s/--engine-heartbeat-misses/
--engine-init-timeout-s``.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import sys
import tempfile
import uuid
from typing import Any, AsyncIterator, Dict, Optional

from ...runtime.engine import AsyncEngine, Context, EngineError

logger = logging.getLogger(__name__)


class EngineStreamDied(Exception):
    """The engine process died after the stream had started."""


def _to_wire(payload: Any) -> Any:
    if hasattr(payload, "model_dump"):
        return payload.model_dump(exclude_none=True)
    if hasattr(payload, "to_wire"):
        return payload.to_wire()
    return payload


class SubprocessEngine(AsyncEngine):
    """Hosts a BYO python-file engine (python_file.py contract) in a
    supervised child process behind the AsyncEngine trait."""

    def __init__(
        self,
        path: str,
        engine_args: Optional[dict] = None,
        *,
        init_timeout_s: float = 120.0,
        heartbeat_interval_s: float = 5.0,
        heartbeat_misses: int = 6,
        max_restarts: int = 3,
        restart_backoff_s: float = 0.5,
        child_env: Optional[Dict[str, str]] = None,
        events=None,  # KvEventSink: child "kv" frames replay into it
    ):
        self.path = path
        self.engine_args = engine_args or {}
        self.events = events
        # refreshed by each pong (the child piggybacks engine.metrics()
        # on the heartbeat); read synchronously by stats handlers
        self._last_metrics: dict = {}
        # block hashes the live child has advertised as stored: a child
        # that dies takes its allocator (and every cached block) with
        # it, so the worker-side sink must see them removed or the KV
        # router would route to prefix hits that can never occur
        self._kv_live_hashes: set = set()
        self.init_timeout_s = init_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.heartbeat_misses = heartbeat_misses
        self.max_restarts = max_restarts
        self.restart_backoff_s = restart_backoff_s
        self.child_env = child_env

        self._proc: Optional[asyncio.subprocess.Process] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._reader_task: Optional[asyncio.Task] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._streams: Dict[str, asyncio.Queue] = {}
        self._pong = 0
        self._spawn_lock: Optional[asyncio.Lock] = None
        self._consecutive_failures = 0
        self._closed = False
        # observability for tests/metrics: how many times the child was
        # (re)spawned successfully
        self.spawn_count = 0
        # respawn observability: child deaths were invisible to telemetry
        # — the restart counter (scraped via host_registry) and the
        # engine.respawn flight event make every supervision cycle an
        # auditable fact instead of a log line
        from ...telemetry.registry import MetricsRegistry

        self.host_registry = MetricsRegistry()
        self._restarts = self.host_registry.counter(
            "dynamo_engine_restarts_total",
            "Supervised engine-child respawns, labelled reason="
            "exit|heartbeat|disconnect|manual (what took the previous "
            "child down)",
        )
        self._last_down_kind: Optional[str] = None
        # child-death subscribers (recovery/controller.py): called with
        # the down reason AFTER streams are failed; never during close()
        self._down_listeners: list = []

    @classmethod
    async def load(
        cls, path: str, engine_args: Optional[dict] = None, **kw
    ) -> "SubprocessEngine":
        # "@"-prefixed specs are built-in engines ("@jax"), not files
        if not path.startswith("@") and not os.path.exists(path):
            raise FileNotFoundError(f"python engine file not found: {path}")
        eng = cls(path, engine_args, **kw)
        await eng._ensure_running()
        return eng

    def metrics(self) -> dict:
        """Engine metrics as of the last heartbeat pong (the hosted
        engine's metrics() output; {} until the first pong arrives)."""
        return self._last_metrics

    # ---------- lifecycle ----------

    async def _ensure_running(self) -> None:
        if self._closed:
            raise EngineError("engine host is closed")
        if self._spawn_lock is None:
            self._spawn_lock = asyncio.Lock()
        async with self._spawn_lock:
            if self._proc is not None and self._proc.returncode is None \
                    and self._writer is not None:
                return
            delay = self.restart_backoff_s
            while True:
                if self._consecutive_failures > self.max_restarts:
                    raise EngineError(
                        f"engine {self.path} failed to start "
                        f"{self._consecutive_failures} consecutive times; "
                        "giving up"
                    )
                try:
                    await self._spawn_once()
                    self._consecutive_failures = 0
                    return
                except EngineError:
                    raise
                except Exception as e:
                    self._consecutive_failures += 1
                    logger.warning(
                        "engine spawn attempt failed (%d/%d): %s",
                        self._consecutive_failures, self.max_restarts, e,
                    )
                    if self._consecutive_failures > self.max_restarts:
                        raise EngineError(
                            f"engine {self.path} failed to start: {e}"
                        ) from e
                    await asyncio.sleep(delay)
                    delay *= 2

    async def _spawn_once(self) -> None:
        sock_dir = tempfile.mkdtemp(prefix="dyn-engine-")
        sock_path = os.path.join(sock_dir, "ipc.sock")
        connected: asyncio.Future = asyncio.get_running_loop().create_future()

        async def on_connect(reader, writer):
            if not connected.done():
                connected.set_result((reader, writer))
            else:  # only the hosted child may dial in
                writer.close()

        server = await asyncio.start_unix_server(on_connect, sock_path)
        env = dict(os.environ if self.child_env is None else self.child_env)
        env["DYN_ENGINE_SOCKET"] = sock_path
        # the child runs `-m dynamo_tpu...`: make the package importable
        # regardless of the parent's cwd
        pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        pp = env.get("PYTHONPATH")
        env["PYTHONPATH"] = pkg_parent + (os.pathsep + pp if pp else "")
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "dynamo_tpu.llm.engines.subprocess_host",
            self.path, env=env,
        )
        try:
            reader, writer = await asyncio.wait_for(
                connected, timeout=self.init_timeout_s
            )
            from ...runtime.transports.dynstore import read_frame, write_frame

            write_frame(writer, {"t": "init", "engine_args": self.engine_args})
            await writer.drain()
            frame = await asyncio.wait_for(
                read_frame(reader), timeout=self.init_timeout_s
            )
            if frame is None:
                raise RuntimeError("engine exited during init")
            if frame.get("t") == "init_error":
                # a deterministic user-code failure: do not burn restarts
                raise EngineError(
                    f"engine init failed: {frame.get('error')}"
                )
            if frame.get("t") != "ready":
                raise RuntimeError(f"unexpected init reply {frame.get('t')!r}")
        except (asyncio.TimeoutError, RuntimeError):
            with contextlib.suppress(ProcessLookupError):
                proc.kill()
            raise
        finally:
            server.close()
            # the socket only exists for the initial dial-in; a
            # crash-looping engine must not accumulate tmp dirs
            with contextlib.suppress(OSError):
                os.unlink(sock_path)
            with contextlib.suppress(OSError):
                os.rmdir(sock_dir)
        self._proc = proc
        self._writer = writer
        self._pong = 0
        self.spawn_count += 1
        if self.spawn_count > 1:
            # a RE-spawn: the previous child died for _last_down_kind
            reason = self._last_down_kind or "unknown"
            self._restarts.inc(reason=reason)
            from ...telemetry.flight import flight_recorder

            flight_recorder().record(
                "engine.respawn", path=self.path, pid=proc.pid,
                spawn=self.spawn_count, reason=reason,
            )
        self._reader_task = asyncio.create_task(self._read_loop(reader))
        self._hb_task = asyncio.create_task(self._heartbeat_loop(writer))
        logger.info(
            "engine subprocess for %s up (pid %d)", self.path, proc.pid
        )

    async def _read_loop(self, reader: asyncio.StreamReader) -> None:
        from ...runtime.transports.dynstore import read_frame

        try:
            while True:
                frame = await read_frame(reader)
                if frame is None:
                    break
                t = frame.get("t")
                if t == "pong":
                    self._pong = frame.get("n", 0)
                    if "m" in frame:
                        self._last_metrics = frame["m"]
                    continue
                if t == "kv":
                    self._on_kv_frame(frame)
                    continue
                q = self._streams.get(frame.get("id"))
                if q is not None:
                    q.put_nowait(frame)
        except (ConnectionResetError, BrokenPipeError, OSError):
            pass
        finally:
            await self._on_child_down("engine process disconnected")

    def _on_kv_frame(self, frame: dict) -> None:
        """Replay a child KV event into the worker-side sink — the KV
        router's radix index stays current even though the allocator
        lives in the engine child."""
        if self.events is None:
            return
        try:
            hashes = frame.get("hashes") or []
            if frame.get("ev") == "stored":
                self._kv_live_hashes.update(hashes)
                self.events.on_stored(hashes, frame.get("parent"))
            elif frame.get("ev") == "removed":
                self._kv_live_hashes.difference_update(hashes)
                self.events.on_removed(hashes)
        except Exception:
            logger.exception("KV event replay failed")

    async def _heartbeat_loop(self, writer: asyncio.StreamWriter) -> None:
        from ...runtime.transports.dynstore import write_frame

        n = 0
        try:
            while True:
                await asyncio.sleep(self.heartbeat_interval_s)
                n += 1
                write_frame(writer, {"t": "ping", "n": n})
                await writer.drain()
                if n - self._pong > self.heartbeat_misses:
                    logger.error(
                        "engine %s missed %d heartbeats; killing (a wedged "
                        "child — e.g. a hung compile — never exits on its own)",
                        self.path, n - self._pong,
                    )
                    await self._on_child_down(
                        f"engine unresponsive: missed "
                        f"{n - self._pong} heartbeats",
                        kind="heartbeat",
                    )
                    return
        except (ConnectionResetError, BrokenPipeError, OSError):
            await self._on_child_down("engine process disconnected")
        except asyncio.CancelledError:
            raise

    async def _on_child_down(self, reason: str,
                             kind: str = "disconnect") -> None:
        """Fail all in-flight streams and reap the child. Idempotent —
        and the hand-off is claimed SYNCHRONOUSLY before the first await:
        the heartbeat path and the read-loop EOF path race to call this,
        and the loser must find nothing left to fail (else the requester
        sees the generic 'disconnected' instead of the real reason)."""
        proc, self._proc = self._proc, None
        writer, self._writer = self._writer, None
        streams, self._streams = self._streams, {}
        hb, self._hb_task = self._hb_task, None
        winner = proc is not None or writer is not None or bool(streams)
        # the dead child's cached blocks died with its allocator: purge
        # them from the worker-side radix index before anything else
        # (synchronous, like the stream failures below)
        dead_hashes, self._kv_live_hashes = self._kv_live_hashes, set()
        if dead_hashes and self.events is not None:
            try:
                self.events.on_removed(sorted(dead_hashes))
            except Exception:
                logger.exception("KV purge after child death failed")
        if proc is not None and proc.returncode is not None:
            reason = f"{reason} (exit code {proc.returncode})"
            kind = "exit"
        if winner and not self._closed:
            self._last_down_kind = kind
            for fn in list(self._down_listeners):
                try:
                    fn(kind)
                except Exception:
                    logger.exception("engine down listener failed")
        # fail the streams before any await: past the first suspension
        # point this task can itself be cancelled by the competing path
        # (the read loop cancels the heartbeat task, and vice versa), and
        # a cancelled loser must not take the error frames with it
        for q in streams.values():
            q.put_nowait({"t": "error", "error": reason, "died": True})
        if hb is not None and hb is not asyncio.current_task():
            hb.cancel()
        if writer is not None:
            with contextlib.suppress(Exception):
                writer.close()
        if proc is not None:
            with contextlib.suppress(ProcessLookupError):
                proc.kill()
            with contextlib.suppress(Exception):
                await proc.wait()

    def add_down_listener(self, fn) -> None:
        """Subscribe to child deaths (sync callback with the down kind;
        not invoked for close()). The recovery controller uses this to
        run its respawn ladder proactively instead of waiting for the
        next request to pay the spawn."""
        self._down_listeners.append(fn)

    async def respawn(self, reason: str = "manual", card=None) -> None:
        """Kill the current child (failing its streams) and bring a
        fresh one up NOW — the supervised-child half of a recovery
        respawn or a rolling engine restart.

        ``card`` (a registry ModelCard or its wire dict) swaps the
        model the child serves: the flag-driven "@jax" child re-reads
        model_path/model_name on spawn, so a respawn with a different
        card IS the multi-model cold start (registry/pools.py) —
        hundreds of logical models per chip, one at a time."""
        if card is not None:
            flags = self.engine_args.get("flags")
            if not isinstance(flags, dict):
                from ...runtime.engine import EngineError

                raise EngineError(
                    "this engine host cannot swap model cards (no "
                    "flag-driven child; serve out=jax --isolate-engine)"
                )
            wire = card.to_wire() if hasattr(card, "to_wire") else dict(card)
            if not wire.get("model_path"):
                from ...runtime.engine import EngineError

                raise EngineError(
                    f"model card {wire.get('name')!r} carries no "
                    "model_path — cannot cold-start from it"
                )
            flags["model_path"] = wire["model_path"]
            flags["model_name"] = wire.get("name") or flags.get("model_name")
            if wire.get("kv_block_size"):
                flags["kv_block_size"] = int(wire["kv_block_size"])
            reason = f"{reason} (card={wire.get('name')})"
        await self._on_child_down(f"manual respawn: {reason}",
                                  kind="manual")
        await self._ensure_running()

    async def close(self) -> None:
        self._closed = True
        writer = self._writer
        if writer is not None:
            from ...runtime.transports.dynstore import write_frame

            with contextlib.suppress(Exception):
                write_frame(writer, {"t": "shutdown"})
                await writer.drain()
            proc = self._proc
            if proc is not None:
                with contextlib.suppress(asyncio.TimeoutError):
                    await asyncio.wait_for(proc.wait(), timeout=2.0)
        await self._on_child_down("engine host closed")
        if self._reader_task is not None:
            self._reader_task.cancel()

    # ---------- serving ----------

    async def generate(self, request: Context[Any]) -> AsyncIterator[Any]:
        await self._ensure_running()
        from ...runtime.transports.dynstore import write_frame

        rid = f"{request.id}-{uuid.uuid4().hex[:8]}"
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        writer = self._writer
        started = False
        ctx = request.context

        async def watch_cancel():
            await ctx.wait_stopped()
            t = "kill" if ctx.is_killed else "stop"
            w = self._writer
            if w is not None:
                with contextlib.suppress(Exception):
                    write_frame(w, {"t": t, "id": rid})
                    await w.drain()

        cancel_task = asyncio.create_task(watch_cancel())
        try:
            write_frame(writer, {"t": "req", "id": rid,
                                 "payload": _to_wire(request.payload)})
            await writer.drain()
            while True:
                frame = await q.get()
                t = frame.get("t")
                if t == "data":
                    started = True
                    yield frame.get("payload")
                elif t == "end":
                    return
                elif t == "error":
                    msg = frame.get("error", "engine error")
                    if frame.get("died") and started:
                        # the stream was already flowing: the network
                        # layer turns this into a mid-stream err frame
                        raise EngineStreamDied(msg)
                    raise EngineError(msg)
                else:
                    logger.warning("unexpected engine frame %r", t)
        finally:
            cancel_task.cancel()
            self._streams.pop(rid, None)


# ---------------------------------------------------------------------------
# child entrypoint
# ---------------------------------------------------------------------------


async def _build_child_engine(engine_path: str, engine_args: dict,
                              event_post) -> AsyncEngine:
    """Instantiate the hosted engine inside the child.

    ``engine_path`` is a python-file path (pystr:/pytok: contract) or
    the ``@jax`` sentinel — the native JAX serving engine, THE engine
    whose Mosaic/XLA compiles are the wedge hazard this host exists to
    quarantine. For ``@jax``, ``engine_args['flags']`` carries the
    parent CLI's flag namespace as a plain dict; KV events flow back to
    the parent as ``{"t": "kv"}`` frames via ``event_post``."""
    if engine_path == "@jax":
        from types import SimpleNamespace

        from ...cli.run import load_mdc
        from ...engine.block_allocator import KvEventSink
        from ...engine.serving import JaxServingEngine

        flags = SimpleNamespace(**(engine_args.get("flags") or {}))
        mdc = load_mdc(flags)
        sink = KvEventSink(
            on_stored=lambda hashes, parent: event_post(
                {"t": "kv", "ev": "stored",
                 "hashes": [int(h) for h in hashes],
                 "parent": None if parent is None else int(parent)}),
            on_removed=lambda hashes: event_post(
                {"t": "kv", "ev": "removed",
                 "hashes": [int(h) for h in hashes]}),
        )
        return await JaxServingEngine.create(mdc, flags, events=sink)
    from .python_file import PythonFileEngine

    return await PythonFileEngine.load(engine_path, engine_args)


async def _child_main(engine_path: str) -> int:
    sock = os.environ["DYN_ENGINE_SOCKET"]
    reader, writer = await asyncio.open_unix_connection(sock)
    from ...runtime.transports.dynstore import read_frame, write_frame

    init = await read_frame(reader)
    if init is None or init.get("t") != "init":
        return 2

    tasks: Dict[str, asyncio.Task] = {}
    send_lock = asyncio.Lock()

    async def send(frame: dict) -> None:
        async with send_lock:  # frames from concurrent streams interleave
            write_frame(writer, frame)
            await writer.drain()

    # KV events are posted synchronously from scheduler hooks; a FIFO
    # queue + one pump preserves stored/removed ordering (reordering a
    # block's stored after its removed would corrupt the radix index)
    event_q: asyncio.Queue = asyncio.Queue()

    async def _event_pump() -> None:
        while True:
            await send(await event_q.get())

    try:
        engine = await _build_child_engine(
            engine_path, init.get("engine_args") or {}, event_q.put_nowait
        )
    # dynlint: allow(silent-except) - error IS surfaced: the init_error frame below
    except BaseException as e:  # report, don't just die: init errors are
        write_frame(writer, {          # deterministic, not restartable
            "t": "init_error", "error": f"{type(e).__name__}: {e}",
        })
        await writer.drain()
        return 3
    pump_task = asyncio.create_task(_event_pump())  # noqa: F841
    write_frame(writer, {"t": "ready"})
    await writer.drain()

    async def run_stream(rid: str, payload: Any) -> None:
        try:
            async for chunk in engine.generate(Context(payload)):
                await send({"t": "data", "id": rid, "payload": chunk})
            await send({"t": "end", "id": rid})
        except asyncio.CancelledError:
            await send({"t": "end", "id": rid})
            raise
        # dynlint: allow(silent-except) - error IS surfaced: relayed as a wire frame
        except BaseException as e:
            await send({
                "t": "error", "id": rid,
                "error": f"{type(e).__name__}: {e}",
            })
        finally:
            tasks.pop(rid, None)

    while True:
        frame = await read_frame(reader)
        if frame is None:
            break
        t = frame.get("t")
        if t == "ping":
            # pongs double as the metrics channel: the parent's
            # stats_handler is synchronous, so it reads the cache the
            # latest pong refreshed (≤ one heartbeat interval stale)
            pong = {"t": "pong", "n": frame.get("n", 0)}
            if hasattr(engine, "metrics"):
                try:
                    pong["m"] = engine.metrics()
                # dynlint: allow(silent-except) - best-effort metrics must never kill the pong
                except Exception:
                    pass
            await send(pong)
        elif t == "req":
            from ...utils import faults

            if faults.fire("child_exit"):
                # chaos site: the child dies hard mid-serve — the parent
                # must fail the stream and respawn (utils/faults.py)
                os._exit(17)
            rid = frame["id"]
            tasks[rid] = asyncio.create_task(
                run_stream(rid, frame.get("payload"))
            )
        elif t in ("stop", "kill"):
            task = tasks.get(frame.get("id"))
            if task is not None:
                task.cancel()
        elif t == "shutdown":
            break
    for task in list(tasks.values()):
        task.cancel()
    return 0


def main() -> None:
    if len(sys.argv) != 2:
        print("usage: python -m dynamo_tpu.llm.engines.subprocess_host "
              "<engine_file.py>", file=sys.stderr)
        raise SystemExit(2)
    raise SystemExit(asyncio.run(_child_main(sys.argv[1])))


if __name__ == "__main__":
    main()
