"""Bring-your-own Python engine: ``out=pystr:<file.py>`` / ``out=pytok:<file.py>``.

Reference analog: lib/engines/python (reference: lib/engines/python/src/
lib.rs:43-382 — imports a user file via runpy and streams from its
``generate`` async generator; pystr = full OpenAI level, pytok = token
level behind the preprocessor/backend pipeline).

User file contract:

    async def generate(request: dict):        # REQUIRED async generator
        yield {...}                           # response chunks (dicts)

    async def initialize(engine_args: dict):  # optional, awaited once

pystr requests are OpenAI request dicts and chunks are OpenAI chunk
dicts; pytok requests are PreprocessedRequest wire dicts and chunks are
EngineOutput wire dicts (dynamo_tpu/protocols/common.py).
"""

from __future__ import annotations

import importlib.util
import inspect
import os
from typing import Any, AsyncIterator, Optional

from ...runtime.engine import AsyncEngine, Context


class PythonFileEngine(AsyncEngine):
    def __init__(self, path: str, generate_fn):
        self.path = path
        self._generate = generate_fn

    @classmethod
    async def load(
        cls, path: str, engine_args: Optional[dict] = None
    ) -> "PythonFileEngine":
        if not os.path.exists(path):
            raise FileNotFoundError(f"python engine file not found: {path}")
        spec = importlib.util.spec_from_file_location(
            f"dynamo_pyengine_{abs(hash(path))}", path
        )
        module = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(module)

        gen = getattr(module, "generate", None)
        if gen is None or not inspect.isasyncgenfunction(gen):
            raise TypeError(
                f"{path} must define `async def generate(request)` as an "
                "async generator"
            )
        init = getattr(module, "initialize", None)
        if init is not None:
            await init(engine_args or {})
        return cls(path, gen)

    async def generate(self, request: Context[Any]) -> AsyncIterator[Any]:
        payload = request.payload
        if hasattr(payload, "model_dump"):
            payload = payload.model_dump(exclude_none=True)
        elif hasattr(payload, "to_wire"):
            payload = payload.to_wire()
        async for chunk in self._generate(payload):
            if request.context.is_stopped:
                return
            yield chunk
