"""Echo engines: the GPU/TPU-free test engines every pipeline test uses.

Reference analog: lib/llm/src/engines.rs:78-178 — EchoEngineCore (token
level, configurable per-token delay via DYN_TOKEN_ECHO_DELAY_MS) and
EchoEngineFull (OpenAI level). These let the whole serving stack — HTTP,
preprocessor, backend, routing, disaggregation — run on any machine.
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, AsyncIterator

from ...protocols.common import EngineOutput, FinishReason, PreprocessedRequest
from ...runtime.engine import AsyncEngine, Context

DELAY_ENV = "DYN_TOKEN_ECHO_DELAY_MS"


def _delay_s() -> float:
    return float(os.environ.get(DELAY_ENV, "1")) / 1000.0


class EchoEngineCore(AsyncEngine):
    """Token-level echo: emits the prompt's token ids back one at a time.

    Respects max_tokens and cooperative cancellation, so scheduler/stream
    logic can be tested deterministically.
    """

    async def generate(self, request: Context[Any]) -> AsyncIterator[dict]:
        payload = request.payload
        req = (
            payload
            if isinstance(payload, PreprocessedRequest)
            else PreprocessedRequest.from_wire(payload)
        )
        delay = _delay_s()
        max_tokens = req.stop_conditions.max_tokens or len(req.token_ids)
        emitted = 0
        for tid in req.token_ids:
            if request.context.is_stopped:
                yield EngineOutput(
                    token_ids=[], finish_reason=FinishReason.CANCELLED
                ).to_wire()
                return
            if emitted >= max_tokens:
                break
            await asyncio.sleep(delay)
            emitted += 1
            yield EngineOutput(token_ids=[tid]).to_wire()
        yield EngineOutput(token_ids=[], finish_reason=FinishReason.LENGTH).to_wire()


class EchoEngineFull(AsyncEngine):
    """OpenAI-level echo: streams the last user message back as chunks."""

    async def generate(self, request: Context[Any]) -> AsyncIterator[dict]:
        from ...protocols.openai import (
            ChatChoiceDelta,
            ChatCompletionChunk,
            ChatCompletionRequest,
            ChatStreamChoice,
            new_request_id,
        )

        payload = request.payload
        req = (
            payload
            if isinstance(payload, ChatCompletionRequest)
            else ChatCompletionRequest.model_validate(payload)
        )
        rid = new_request_id()
        text = req.messages[-1].text_content() if req.messages else ""
        delay = _delay_s()
        yield ChatCompletionChunk(
            id=rid,
            model=req.model,
            choices=[ChatStreamChoice(delta=ChatChoiceDelta(role="assistant"))],
        ).model_dump(exclude_none=True)
        for word in text.split():
            if request.context.is_stopped:
                break
            await asyncio.sleep(delay)
            yield ChatCompletionChunk(
                id=rid,
                model=req.model,
                choices=[ChatStreamChoice(delta=ChatChoiceDelta(content=word + " "))],
            ).model_dump(exclude_none=True)
        yield ChatCompletionChunk(
            id=rid,
            model=req.model,
            choices=[ChatStreamChoice(delta=ChatChoiceDelta(), finish_reason="stop")],
        ).model_dump(exclude_none=True)
