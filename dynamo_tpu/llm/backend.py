"""Backend stage: streaming detokenization + stop-condition enforcement.

Sits between the engine (token ids out) and the preprocessor's response
path (text deltas in). Reference analog: lib/llm/src/backend.rs:87-385 —
incremental DecodeStream plus the "jail" that buffers partial matches of
stop sequences so a stop string is never partially surfaced to the client.
"""

from __future__ import annotations

from typing import AsyncIterator, List, Optional, Tuple

from ..protocols.common import (
    BackendOutput,
    EngineOutput,
    FinishReason,
    PreprocessedRequest,
)
from ..runtime.engine import AsyncEngine, Context
from ..runtime.pipeline import Operator
from .tokenizer import HFTokenizer


class Decoder:
    """Per-request detokenizer with stop-string jail.

    ``step`` returns ``(text_to_emit, finish_reason)``. Text that might be
    the beginning of a stop string is jailed (held back) until the match
    either completes (→ truncate + STOP) or breaks (→ released).
    """

    def __init__(
        self,
        tokenizer: Optional[HFTokenizer],
        stop_strings: Optional[List[str]] = None,
        hidden_stop_ids: Optional[List[int]] = None,
        eos_token_ids: Optional[List[int]] = None,
        ignore_eos: bool = False,
        skip_special_tokens: bool = True,
    ):
        self.stream = (
            tokenizer.decode_stream(skip_special_tokens) if tokenizer else None
        )
        self.stop_strings = [s for s in (stop_strings or []) if s]
        self.hidden_stop_ids = set(hidden_stop_ids or [])
        self.eos_token_ids = set(eos_token_ids or [])
        self.ignore_eos = ignore_eos
        self.jail = ""
        self.generated = 0

    def _longest_held_suffix(self, text: str) -> int:
        """Length of the longest suffix of ``text`` that could still grow
        into a stop string."""
        best = 0
        for stop in self.stop_strings:
            # try suffixes up to len(stop)-1 (a full match is handled earlier)
            max_len = min(len(stop) - 1, len(text))
            for k in range(max_len, 0, -1):
                if stop.startswith(text[-k:]):
                    best = max(best, k)
                    break
        return best

    def step(self, token_id: int) -> Tuple[Optional[str], Optional[FinishReason]]:
        self.generated += 1
        if token_id in self.hidden_stop_ids:
            # token-level stop: jailed text is legitimate output, release it
            # (only a completed stop-STRING match justifies discarding it)
            return self.flush(), FinishReason.STOP
        if not self.ignore_eos and token_id in self.eos_token_ids:
            return self.flush(), FinishReason.EOS

        if self.stream is None:
            return None, None
        delta = self.stream.step(token_id)
        if delta is None:
            return None, None

        text = self.jail + delta
        # full stop-string match → truncate at the earliest match
        cut = -1
        for stop in self.stop_strings:
            idx = text.find(stop)
            if idx != -1 and (cut == -1 or idx < cut):
                cut = idx
        if cut != -1:
            self.jail = ""
            emitted = text[:cut]
            return (emitted or None), FinishReason.STOP

        hold = self._longest_held_suffix(text)
        if hold:
            self.jail = text[-hold:]
            emit = text[:-hold]
        else:
            self.jail = ""
            emit = text
        return (emit or None), None

    def flush(self) -> Optional[str]:
        """Release jailed text (finish for a reason other than a stop match)."""
        out, self.jail = self.jail, ""
        return out or None


class Backend(Operator):
    """Pipeline operator: requests pass through; responses get detokenized."""

    def __init__(self, tokenizer: Optional[HFTokenizer]):
        self.tokenizer = tokenizer

    @classmethod
    def from_mdc(cls, mdc) -> "Backend":
        tok = HFTokenizer.from_model_path(mdc.model_path) if mdc.model_path else None
        return cls(tok)

    async def generate(
        self, request: Context[PreprocessedRequest], next_engine: AsyncEngine
    ) -> AsyncIterator[BackendOutput]:
        req = request.payload
        decoder = Decoder(
            self.tokenizer,
            stop_strings=req.stop_conditions.stop,
            hidden_stop_ids=req.stop_conditions.stop_token_ids_hidden,
            eos_token_ids=req.eos_token_ids,
            ignore_eos=req.stop_conditions.ignore_eos,
            skip_special_tokens=req.output_options.skip_special_tokens,
        )
        max_tokens = req.stop_conditions.max_tokens

        finished = False
        # deterministic finalization: this loop BREAKS at the finish
        # chunk, and an abandoned inner async generator is finalized
        # only lazily (GC / asyncgen hooks). The network client's
        # cleanup, which folds the worker's span export into the request
        # trace (runtime/client.py), must run BEFORE upstream hops
        # export THEIR spans — aclosing() runs the inner finally-chain
        # synchronously at the break.
        from contextlib import aclosing

        # re-bind across live migrations: a `migrated` control frame
        # (recovery/migration.py) makes the wrapper attach directly to
        # the peer so the draining source worker can exit instead of
        # relaying this stream to its end; byte-identity is the
        # migration plane's contract either way
        from ..recovery.migration import follow_migrated_stream

        engine_stream = follow_migrated_stream(
            next_engine.generate(request), ctx=request.context)
        async with aclosing(engine_stream):
            async for out in engine_stream:
                if isinstance(out, dict):  # off the wire
                    out = EngineOutput.from_wire(out)
                texts: List[str] = []
                emitted_ids: List[int] = []
                finish: Optional[FinishReason] = out.finish_reason
                for tid in out.token_ids:
                    text, tok_finish = decoder.step(tid)
                    emitted_ids.append(tid)
                    if text is not None:
                        texts.append(text)
                    if tok_finish is not None:
                        finish = tok_finish
                        break
                    if max_tokens is not None and decoder.generated >= max_tokens:
                        finish = finish or FinishReason.LENGTH
                        break
                if finish is not None and finish not in (FinishReason.STOP,):
                    tail = decoder.flush()
                    if tail:
                        texts.append(tail)
                yield BackendOutput(
                    token_ids=emitted_ids,
                    text="".join(texts) if texts else None,
                    finish_reason=finish,
                    logprobs=out.logprobs,
                    prompt_logprobs=out.prompt_logprobs,
                    cum_tokens=decoder.generated,
                )
                if finish is not None:
                    finished = True
                    break
        if not finished:
            # engine stream ended without a finish reason (e.g. cancelled)
            tail = decoder.flush()
            yield BackendOutput(
                token_ids=[],
                text=tail,
                finish_reason=FinishReason.CANCELLED,
                cum_tokens=decoder.generated,
            )
