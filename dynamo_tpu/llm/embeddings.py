"""/v1/embeddings — the prefill-only workload (ROADMAP item 4a).

An embeddings request is a prefill with no decode slot: tokenize, run
the batched cacheless prefill trunk (models/llama.embed_forward via
ModelRunner.embed_prompts — rows pad to the same prefill row/bucket
ladders the chat path uses), L2-normalize the last valid position's
final-norm hidden state, and return OpenAI-shaped rows with usage
counts. No KV blocks, no scheduler slot, no stream.

Deployment note: in the disaggregated shape this traffic belongs on the
prefill-worker pool (prefill-only by construction, and the planner
already autoscales that pool) — run ``in=http out=jax`` frontends
colocated with the pool's workers and route /v1/embeddings there; the
decode pool's frontends can leave the embedder unset and answer 501.
"""

from __future__ import annotations

import asyncio
import hashlib
from typing import List, Optional, Sequence, Tuple

import numpy as np


class EmbeddingError(ValueError):
    """Client-side problem with an embeddings request (HTTP 400)."""


def normalize_inputs(raw) -> List[object]:
    """OpenAI ``input`` shapes → list of items (str or token-id list).

    Accepted: a string, a list of strings, a list of token ids, a list
    of token-id lists. Anything else raises EmbeddingError.
    """
    if isinstance(raw, str):
        return [raw]
    if isinstance(raw, list):
        if not raw:
            raise EmbeddingError("input must not be empty")
        if all(isinstance(x, str) for x in raw):
            return list(raw)
        if all(isinstance(x, int) for x in raw):
            return [list(raw)]
        if all(isinstance(x, list)
               and x and all(isinstance(t, int) for t in x) for x in raw):
            return [list(x) for x in raw]
    raise EmbeddingError(
        "input must be a string, a list of strings, a list of token ids, "
        "or a list of token-id lists"
    )


class Embedder:
    """Tokenize + batch + embed through a token-level engine.

    ``engine`` must expose ``embed(prompts) -> np.ndarray [n, D]``
    (JaxServingEngine.embed). Tokenization and the device round trip
    both run off the event loop.
    """

    def __init__(self, tokenizer, engine, max_model_len: int,
                 vocab_size: Optional[int] = None):
        self.tokenizer = tokenizer
        self.engine = engine
        self.max_model_len = int(max_model_len)
        self.vocab_size = vocab_size

    def _tokenize(self, items: Sequence[object]) -> List[List[int]]:
        prompts: List[List[int]] = []
        for item in items:
            if isinstance(item, str):
                if self.tokenizer is None:
                    raise EmbeddingError(
                        "string input needs a tokenizer; this engine was "
                        "built without a model path — send token ids"
                    )
                ids = list(self.tokenizer.encode(item))
            else:
                ids = [int(t) for t in item]
            if not ids:
                raise EmbeddingError("input item tokenized to zero tokens")
            if len(ids) > self.max_model_len:
                raise EmbeddingError(
                    f"input of {len(ids)} tokens exceeds the model's "
                    f"context length {self.max_model_len}"
                )
            if self.vocab_size is not None:
                bad = next((t for t in ids
                            if not 0 <= t < self.vocab_size), None)
                if bad is not None:
                    raise EmbeddingError(
                        f"token id {bad} outside vocab [0, "
                        f"{self.vocab_size})"
                    )
            prompts.append(ids)
        return prompts

    async def embed(self, raw_input) -> Tuple[List[List[float]], int]:
        """→ (L2-normalized vectors, total prompt tokens)."""
        items = normalize_inputs(raw_input)
        loop = asyncio.get_running_loop()
        prompts = await loop.run_in_executor(None, self._tokenize, items)
        vecs = await self.engine.embed(prompts)
        vecs = np.asarray(vecs, np.float32)
        norms = np.linalg.norm(vecs, axis=1, keepdims=True)
        vecs = vecs / np.maximum(norms, 1e-12)
        return ([[float(x) for x in v] for v in vecs],
                sum(len(p) for p in prompts))


class EchoEmbedder:
    """Deterministic test/demo embedder: a hash-seeded unit vector per
    input (the echo engines' analog for the embeddings workload)."""

    def __init__(self, dim: int = 16, tokenizer=None,
                 max_model_len: int = 8192):
        self.dim = dim
        self.tokenizer = tokenizer
        self.max_model_len = max_model_len

    async def embed(self, raw_input) -> Tuple[List[List[float]], int]:
        items = normalize_inputs(raw_input)
        out: List[List[float]] = []
        ntok = 0
        for item in items:
            if isinstance(item, str):
                ntok += max(1, len(item.split()))
                seed_bytes = item.encode()
            else:
                ntok += len(item)
                seed_bytes = np.asarray(item, np.int64).tobytes()
            seed = int.from_bytes(
                hashlib.sha256(seed_bytes).digest()[:8], "little")
            v = np.random.default_rng(seed).standard_normal(self.dim)
            v = v / np.linalg.norm(v)
            out.append([float(x) for x in v])
        return out, ntok
