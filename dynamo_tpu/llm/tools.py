"""Tool-call extraction from generated text → OpenAI ``tool_calls``.

Reference analog: lib/llm/src/preprocessor/tools.rs ToolCallingMatcher —
which only JSON-parses a whole message as {name, parameters|arguments}
(and, notably, was never wired into the reference's delta layer; every
delta carried ``tool_calls: None``, left unimplemented at
chat_completions/delta.rs:131 — resolved here, including the forced
tool_choice forms "required" and named-function, which jail the stream
from token 0). Parsing covers the formats the popular
open-weight families actually emit, and llm/preprocessor.py chat_stream
emits the proper OpenAI STREAMED tool-call shape from it: per call, a
header delta ({index, id, type, function.name, arguments: ""}) followed
by {index, function.arguments} fragment deltas, closed by an empty
delta with finish_reason="tool_calls"; protocols/openai.py
aggregate_chat_stream folds the fragments back into whole entries for
non-streaming responses.

Formats:
- ``hermes``   — ``<tool_call>{...}</tool_call>`` blocks (Hermes, Qwen)
- ``mistral``  — ``[TOOL_CALLS] [{...}, ...]`` prefix
- ``json``     — the whole message is one JSON object or array of
                 objects with ``name`` + ``arguments``/``parameters``
                 (Llama-3.x JSON tool calling; the reference's behavior)
- ``auto``     — try hermes, then mistral, then json
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional

_HERMES_RE = re.compile(r"<tool_call>\s*(.*?)\s*</tool_call>", re.DOTALL)
_MISTRAL_PREFIX = "[TOOL_CALLS]"

FORMATS = ("auto", "hermes", "mistral", "json")


def _call_dict(name: str, arguments: Any) -> Dict[str, Any]:
    """One OpenAI tool_calls entry; arguments always a JSON string."""
    if not isinstance(arguments, str):
        arguments = json.dumps(arguments)
    return {
        "id": f"call-{uuid.uuid4().hex[:24]}",
        "type": "function",
        "function": {"name": name, "arguments": arguments},
    }


def _from_obj(obj: Any) -> Optional[Dict[str, Any]]:
    """{name, arguments|parameters} → tool_calls entry (else None)."""
    if not isinstance(obj, dict) or not isinstance(obj.get("name"), str):
        return None
    args = obj.get("arguments", obj.get("parameters"))
    if args is None or isinstance(args, (dict, str, list)):
        return _call_dict(obj["name"], args if args is not None else {})
    return None


def _parse_json_value(text: str) -> Optional[List[Dict[str, Any]]]:
    try:
        value = json.loads(text)
    except ValueError:
        return None
    objs = value if isinstance(value, list) else [value]
    calls = [_from_obj(o) for o in objs]
    if calls and all(c is not None for c in calls):
        return calls  # type: ignore[return-value]
    return None


def _extract_hermes(text: str):
    blocks = _HERMES_RE.findall(text)
    if not blocks:
        return text, None
    calls = []
    for block in blocks:
        parsed = _parse_json_value(block)
        if parsed is None:
            return text, None
        calls.extend(parsed)
    content = _HERMES_RE.sub("", text).strip()
    return content, (calls or None)


def _extract_mistral(text: str):
    stripped = text.strip()
    if not stripped.startswith(_MISTRAL_PREFIX):
        return text, None
    calls = _parse_json_value(stripped[len(_MISTRAL_PREFIX):].strip())
    return ("", calls) if calls else (text, None)


def _extract_json(text: str):
    calls = _parse_json_value(text.strip())
    return ("", calls) if calls else (text, None)


_EXTRACTORS = {
    "hermes": _extract_hermes,
    "mistral": _extract_mistral,
    "json": _extract_json,
}


def extract_tool_calls(text: str, fmt: str = "auto"):
    """(surrounding_content, calls-or-None) from a complete generation.

    Models legitimately emit prose around call blocks ("Let me check
    <tool_call>…</tool_call>") — that content is preserved for the
    response alongside ``tool_calls``."""
    if fmt == "auto":
        for name in ("hermes", "mistral", "json"):
            content, calls = _EXTRACTORS[name](text)
            if calls:
                return content, calls
        return text, None
    if fmt not in _EXTRACTORS:
        raise ValueError(f"unknown tool-call format {fmt!r}; use {FORMATS}")
    return _EXTRACTORS[fmt](text)


def parse_tool_calls(
    text: str, fmt: str = "auto"
) -> Optional[List[Dict[str, Any]]]:
    """Extract tool calls from a complete generation, or None if the text
    is not a tool call (callers then deliver it as normal content)."""
    return extract_tool_calls(text, fmt)[1]


def stream_markers(fmt: str = "auto"):
    """Substrings whose appearance in a stream signals a potential tool
    call: the backend's streaming jail withholds text only from a marker
    onward (the ``json`` format has no marker — a leading JSON value is
    its only signature, which the caller checks on the first chunk)."""
    if fmt == "hermes":
        return ("<tool_call>",)
    if fmt == "mistral":
        return (_MISTRAL_PREFIX,)
    if fmt == "json":
        return ()
    return ("<tool_call>", _MISTRAL_PREFIX)


def marker_prefix_len(tail: str, markers) -> int:
    """Longest suffix of ``tail`` that is a proper prefix of any marker —
    that many chars must be withheld in case the marker completes in the
    next chunk (same idea as the detokenizer's stop-string jail)."""
    best = 0
    for m in markers:
        for k in range(min(len(tail), len(m) - 1), 0, -1):
            if tail.endswith(m[:k]):
                best = max(best, k)
                break
    return best
