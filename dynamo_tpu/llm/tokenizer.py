"""Tokenizer wrapper + incremental detokenization.

Wraps HuggingFace ``tokenizers`` (reference analog:
lib/llm/src/tokenizers.rs — HuggingFaceTokenizer + DecodeStream). The
``DecodeStream`` here implements offset-based incremental decoding: decode
a sliding window of recent ids and emit only the stable new suffix, so
multi-byte characters that span tokens are never emitted half-finished.
"""

from __future__ import annotations

import os
from typing import List, Optional

from tokenizers import Tokenizer

REPLACEMENT_CHAR = "�"

# SentencePiece piece types (sentencepiece.proto; same semantics GGUF
# re-encodes in tokenizer.ggml.token_type — llm/gguf.py)
_SPM_NORMAL, _SPM_UNKNOWN, _SPM_CONTROL = 1, 2, 3
_SPM_USER_DEFINED, _SPM_UNUSED, _SPM_BYTE = 4, 5, 6


def add_spm_added_tokens(tok: Tokenizer, tokens, types) -> None:
    """Register CONTROL pieces as specials and USER_DEFINED pieces as
    whole-match tokens (shared by every SPM-semantics reconstruction:
    the two builders here and llm/gguf.py's gpt2 branch)."""
    from tokenizers import AddedToken

    specials = [
        AddedToken(tokens[i], special=True, normalized=False)
        for i, t in enumerate(types)
        if t == _SPM_CONTROL
    ]
    if specials:
        tok.add_special_tokens(specials)
    user_defined = [
        AddedToken(tokens[i], special=False, normalized=False)
        for i, t in enumerate(types)
        if t == _SPM_USER_DEFINED
    ]
    if user_defined:
        tok.add_tokens(user_defined)


def _set_spm_surface(tok: Tokenizer) -> None:
    """The ▁ whitespace convention: prepend/replace on the way in,
    replace/byte-fallback/fuse/strip on the way out."""
    from tokenizers import decoders, normalizers

    tok.normalizer = normalizers.Sequence(
        [normalizers.Prepend("▁"), normalizers.Replace(" ", "▁")]
    )
    tok.decoder = decoders.Sequence([
        decoders.Replace("▁", " "),
        decoders.ByteFallback(),
        decoders.Fuse(),
        decoders.Strip(" ", 1, 0),
    ])


def build_unigram_tokenizer(tokens, scores, types, unk_id=None) -> Tokenizer:
    """SentencePiece-semantics Unigram tokenizer from raw vocab data.

    Shared by the GGUF reconstruction (llm/gguf.py) and tokenizer.model
    loading: ▁ whitespace convention, byte fallback, CONTROL pieces
    special, USER_DEFINED pieces matched whole but visible in decode.
    """
    from tokenizers.models import Unigram

    if unk_id is None:
        unk_id = next(
            (i for i, t in enumerate(types) if t == _SPM_UNKNOWN), 0
        )
    vocab = list(zip(tokens, scores))
    tok = Tokenizer(Unigram(vocab, unk_id=int(unk_id), byte_fallback=True))
    _set_spm_surface(tok)
    add_spm_added_tokens(tok, tokens, types)
    return tok


def tokenizer_from_spm(path: str) -> Tokenizer:
    """Build a tokenizer from a SentencePiece ``tokenizer.model``.

    Parses the SPM protobuf through transformers' bundled schema (no
    sentencepiece package needed) and rebuilds the equivalent fast
    tokenizer (reference analog: lib/llm/src/tokenizers.rs SentencePiece
    support — the coverage gap called out in round 1).
    """
    from transformers.convert_slow_tokenizer import import_protobuf

    model_pb2 = import_protobuf()
    proto = model_pb2.ModelProto()
    with open(path, "rb") as f:
        proto.ParseFromString(f.read())
    tokens = [p.piece for p in proto.pieces]
    scores = [p.score for p in proto.pieces]
    types = [int(p.type) for p in proto.pieces]
    unk_id = proto.trainer_spec.unk_id if proto.HasField("trainer_spec") else None

    model_type = (
        int(proto.trainer_spec.model_type)
        if proto.HasField("trainer_spec") else 1
    )
    if model_type == 2:  # SPM BPE (original Llama/Mistral exports)
        return _build_spm_bpe_tokenizer(tokens, types, unk_id)
    if model_type != 1:
        raise ValueError(
            f"unsupported SentencePiece model_type {model_type} in {path} "
            "(supported: 1=unigram, 2=bpe)"
        )
    return build_unigram_tokenizer(tokens, scores, types, unk_id)


def _build_spm_bpe_tokenizer(tokens, types, unk_id=None) -> Tokenizer:
    """SPM-BPE (model_type=2) reconstruction.

    SPM-BPE merge priority is the merged piece's vocab rank: recover
    merges by splitting each piece at EVERY boundary where both halves
    exist (the public SentencePieceExtractor recipe keeps all valid
    splits — a piece can be reachable through several merge paths, and
    dropping one can make the piece unreachable when an earlier merge
    consumes its preferred split), ordered by the merged piece's id,
    then run standard BPE with byte fallback under the ▁ whitespace
    convention.
    """
    from tokenizers.models import BPE

    vocab = {t: i for i, t in enumerate(tokens)}
    merges = []
    for piece, piece_id in vocab.items():
        if len(piece) < 2 or types[piece_id] != _SPM_NORMAL:
            continue
        local = [
            (piece[:i], piece[i:])
            for i in range(1, len(piece))
            if piece[:i] in vocab and piece[i:] in vocab
        ]
        # within a piece, order splits by the rank at which their halves
        # became available (earliest-merged halves first)
        local.sort(key=lambda ab: max(vocab[ab[0]], vocab[ab[1]]))
        merges.extend(((piece_id, j), ab) for j, ab in enumerate(local))
    merges = [ab for _, ab in sorted(merges)]

    if unk_id is None:
        unk_id = next((i for i, t in enumerate(types) if t == _SPM_UNKNOWN), 0)
    tok = Tokenizer(BPE(
        vocab=vocab, merges=merges, unk_token=tokens[int(unk_id)],
        fuse_unk=True, byte_fallback=True,
    ))
    _set_spm_surface(tok)
    add_spm_added_tokens(tok, tokens, types)
    return tok


class HFTokenizer:
    """Thin wrapper over ``tokenizers.Tokenizer`` with the framework surface."""

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer

    @classmethod
    def from_file(cls, path: str) -> "HFTokenizer":
        return cls(Tokenizer.from_file(path))

    @classmethod
    def from_pretrained_dir(cls, model_dir: str) -> "HFTokenizer":
        path = os.path.join(model_dir, "tokenizer.json")
        if os.path.exists(path):
            return cls.from_file(path)
        spm = os.path.join(model_dir, "tokenizer.model")
        if os.path.exists(spm):
            # SentencePiece-only snapshots (original Llama/Mistral exports)
            return cls(tokenizer_from_spm(spm))
        raise FileNotFoundError(
            f"no tokenizer.json or tokenizer.model under {model_dir}"
        )

    @classmethod
    def from_model_path(cls, model_path: str) -> "HFTokenizer":
        """HF snapshot dir (tokenizer.json) OR a .gguf file (vocab
        reconstructed from the embedded GGUF metadata, llm/gguf.py)."""
        if model_path.endswith(".gguf"):
            from .gguf import read_gguf, tokenizer_from_gguf

            return cls(tokenizer_from_gguf(read_gguf(model_path)))
        return cls.from_pretrained_dir(model_path)

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: List[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tok.token_to_id(token)

    def id_to_token(self, token_id: int) -> Optional[str]:
        return self._tok.id_to_token(token_id)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer: feed ids one at a time, get text deltas.

    Keeps ``prefix_offset``/``read_offset`` into the id history; each step
    decodes ``ids[prefix:]`` and emits the stable suffix beyond the last
    emitted text. Returns None while the tail is an incomplete UTF-8
    sequence (e.g. the first half of a multi-token emoji).
    """

    def __init__(self, tokenizer: HFTokenizer, skip_special_tokens: bool = True):
        self.tokenizer = tokenizer
        self.skip_special_tokens = skip_special_tokens
        self.ids: List[int] = []
        self.prefix_offset = 0
        self.read_offset = 0

    def step(self, token_id: int) -> Optional[str]:
        self.ids.append(int(token_id))
        prefix_text = self.tokenizer.decode(
            self.ids[self.prefix_offset : self.read_offset], self.skip_special_tokens
        )
        new_text = self.tokenizer.decode(
            self.ids[self.prefix_offset :], self.skip_special_tokens
        )
        if new_text.endswith(REPLACEMENT_CHAR):
            # incomplete multi-byte sequence — wait for more tokens
            return None
        if len(new_text) <= len(prefix_text):
            return None
        delta = new_text[len(prefix_text) :]
        self.prefix_offset = self.read_offset
        self.read_offset = len(self.ids)
        return delta
