"""Tokenizer wrapper + incremental detokenization.

Wraps HuggingFace ``tokenizers`` (reference analog:
lib/llm/src/tokenizers.rs — HuggingFaceTokenizer + DecodeStream). The
``DecodeStream`` here implements offset-based incremental decoding: decode
a sliding window of recent ids and emit only the stable new suffix, so
multi-byte characters that span tokens are never emitted half-finished.
"""

from __future__ import annotations

import os
from typing import List, Optional

from tokenizers import Tokenizer

REPLACEMENT_CHAR = "�"


class HFTokenizer:
    """Thin wrapper over ``tokenizers.Tokenizer`` with the framework surface."""

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer

    @classmethod
    def from_file(cls, path: str) -> "HFTokenizer":
        return cls(Tokenizer.from_file(path))

    @classmethod
    def from_pretrained_dir(cls, model_dir: str) -> "HFTokenizer":
        path = os.path.join(model_dir, "tokenizer.json")
        if not os.path.exists(path):
            raise FileNotFoundError(f"no tokenizer.json under {model_dir}")
        return cls.from_file(path)

    @classmethod
    def from_model_path(cls, model_path: str) -> "HFTokenizer":
        """HF snapshot dir (tokenizer.json) OR a .gguf file (vocab
        reconstructed from the embedded GGUF metadata, llm/gguf.py)."""
        if model_path.endswith(".gguf"):
            from .gguf import read_gguf, tokenizer_from_gguf

            return cls(tokenizer_from_gguf(read_gguf(model_path)))
        return cls.from_pretrained_dir(model_path)

    def encode(self, text: str, add_special_tokens: bool = False) -> List[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: List[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    def token_to_id(self, token: str) -> Optional[int]:
        return self._tok.token_to_id(token)

    def id_to_token(self, token_id: int) -> Optional[str]:
        return self._tok.id_to_token(token_id)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    def decode_stream(self, skip_special_tokens: bool = True) -> "DecodeStream":
        return DecodeStream(self, skip_special_tokens)


class DecodeStream:
    """Incremental detokenizer: feed ids one at a time, get text deltas.

    Keeps ``prefix_offset``/``read_offset`` into the id history; each step
    decodes ``ids[prefix:]`` and emits the stable suffix beyond the last
    emitted text. Returns None while the tail is an incomplete UTF-8
    sequence (e.g. the first half of a multi-token emoji).
    """

    def __init__(self, tokenizer: HFTokenizer, skip_special_tokens: bool = True):
        self.tokenizer = tokenizer
        self.skip_special_tokens = skip_special_tokens
        self.ids: List[int] = []
        self.prefix_offset = 0
        self.read_offset = 0

    def step(self, token_id: int) -> Optional[str]:
        self.ids.append(int(token_id))
        prefix_text = self.tokenizer.decode(
            self.ids[self.prefix_offset : self.read_offset], self.skip_special_tokens
        )
        new_text = self.tokenizer.decode(
            self.ids[self.prefix_offset :], self.skip_special_tokens
        )
        if new_text.endswith(REPLACEMENT_CHAR):
            # incomplete multi-byte sequence — wait for more tokens
            return None
        if len(new_text) <= len(prefix_text):
            return None
        delta = new_text[len(prefix_text) :]
        self.prefix_offset = self.read_offset
        self.read_offset = len(self.ids)
        return delta
