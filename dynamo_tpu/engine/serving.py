"""JaxServingEngine: the AsyncEngine facade over runner + scheduler.

The token-level engine that slots into the pipeline where the reference
plugged vLLM/SGLang (reference: lib/llm/src/engines.rs ExecutionContext —
PreprocessedRequest in, streamed EngineOutput deltas out).
"""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, AsyncIterator, Optional

from ..protocols.common import EngineOutput, PreprocessedRequest
from ..runtime.engine import AsyncEngine, Context, EngineError
from .block_allocator import KvEventSink
from .config import EngineConfig, ModelConfig
from .model_runner import ModelRunner
from .scheduler import EngineRequest, Scheduler

logger = logging.getLogger(__name__)


def engine_config_from_mdc(mdc, flags=None, extra=None) -> EngineConfig:
    """The one place MDC + CLI flags become an EngineConfig.

    Shared by decode engines and prefill workers — block geometry MUST match
    across disaggregated workers or transferred KV lands in the wrong slots.

    ``extra`` is the ``--extra-engine-args`` JSON passthrough (reference:
    dynamo-run flags.rs:175): keys naming ModelConfig fields override the
    model config (e.g. ``attention_impl``), keys naming EngineConfig
    fields override the engine config; unknown keys are rejected loudly.
    """
    import dataclasses

    model_cfg = ModelConfig.from_hf_config(mdc.config) if mdc.config else ModelConfig()
    if getattr(flags, "quantization", None):
        model_cfg.quantization = flags.quantization
    if extra is None:
        extra = load_extra_engine_args(flags)
    extra = dict(extra or {})
    model_extra = {}
    engine_extra = {}
    model_fields = {f.name for f in dataclasses.fields(ModelConfig)}
    engine_fields = {f.name for f in dataclasses.fields(EngineConfig)}
    for key, value in extra.items():
        if key in model_fields:
            model_extra[key] = value
        elif key in engine_fields and key != "model":
            engine_extra[key] = value
        else:
            raise ValueError(
                f"--extra-engine-args key {key!r} matches no ModelConfig or "
                f"EngineConfig field"
            )
    if model_extra:
        # replace (not setattr) so __post_init__ re-validates/derives —
        # e.g. kv_lora_rank without the MLA head dims must fail loudly
        model_cfg = dataclasses.replace(model_cfg, **model_extra)
    return _apply_engine_extra(engine_extra, EngineConfig(
        model=model_cfg,
        max_batch_size=getattr(flags, "max_batch_size", 8),
        max_model_len=getattr(flags, "max_model_len", None)
        or min(mdc.context_length, model_cfg.max_position_embeddings),
        kv_block_size=mdc.kv_block_size,
        tp_size=getattr(flags, "tensor_parallel_size", 1),
        ep_size=getattr(flags, "expert_parallel_size", 1),
        dp_size=getattr(flags, "data_parallel_size", 1),
        pp_size=getattr(flags, "pipeline_parallel_size", 1),
        # sequence-parallel long-context prefill (docs/long_context.md)
        sp_size=getattr(flags, "sequence_parallel_size", 1) or 1,
        long_prefill_threshold_tokens=getattr(
            flags, "long_prefill_threshold_tokens", 0) or 0,
        host_kv_blocks=getattr(flags, "host_kv_blocks", 0) or 0,
        num_kv_blocks=getattr(flags, "num_kv_blocks", None) or 2048,
        multi_step_decode=getattr(flags, "multi_step_decode", 1) or 1,
        decode_pipeline_depth=getattr(flags, "decode_pipeline_depth", 1) or 1,
        device_finish=getattr(flags, "device_finish", "auto") or "auto",
        fused_epilogue=getattr(flags, "fused_epilogue", "auto") or "auto",
        # no `or 2` fallback: an explicit 0 must clamp to 1 (serial), not
        # silently flip back to double-buffered
        disagg_stream_depth=(
            2 if getattr(flags, "disagg_stream_depth", None) is None
            else flags.disagg_stream_depth
        ),
        spec_ngram_tokens=getattr(flags, "spec_ngram_tokens", 0) or 0,
        spec_ngram_match=getattr(flags, "spec_ngram_match", 3) or 3,
        # unrestricted chain (docs/performance.md): guided device
        # tables + device-approximate stop strings
        guided_device_table=not getattr(
            flags, "no_guided_device_table", False),
        guided_table_max_states=getattr(
            flags, "guided_table_max_states", 256) or 256,
        device_stop_strings=not getattr(
            flags, "no_device_stop_strings", False),
        # no `or` fallback: an explicit 0 must DISABLE the watchdog, not
        # silently restore the default deadline
        watchdog_stall_s=(
            30.0 if getattr(flags, "watchdog_stall_s", None) is None
            else flags.watchdog_stall_s
        ),
        spec_draft_model=getattr(flags, "spec_draft_model", None),
        spec_draft_tokens=getattr(flags, "spec_draft_tokens", 0) or 0,
        allow_random_weights=getattr(flags, "allow_random_weights", False),
        kv_cache_dtype=getattr(flags, "kv_cache_dtype", "auto") or "auto",
        # cluster KV fabric (kv/fabric.py): cross-worker prefix pull +
        # the content-addressed cold tier
        prefix_pull=getattr(flags, "prefix_pull", False),
        prefix_pull_min_blocks=getattr(
            flags, "prefix_pull_min_blocks", 2) or 2,
        prefix_pull_timeout_s=getattr(
            flags, "prefix_pull_timeout_s", 30.0) or 30.0,
        cold_tier_dir=getattr(flags, "cold_tier_dir", "") or "",
        cold_tier_blocks=getattr(flags, "cold_tier_blocks", 0) or 0,
    ))


def load_extra_engine_args(flags) -> dict:
    """--extra-engine-args <file.json> → dict (reference: dynamo-run's
    JSON passthrough, flags.rs:175). The ONE parse site — the CLI's
    python-file engine path reuses it."""
    path = getattr(flags, "extra_engine_args", None)
    if not path:
        return {}
    import json

    with open(path) as f:
        return json.load(f)


def _apply_engine_extra(extra: dict, cfg: EngineConfig) -> EngineConfig:
    """Apply --extra-engine-args EngineConfig overrides after construction.

    dataclasses.replace re-runs __post_init__, but the bucket derivation
    only fires when prefill_buckets is None — so a max_model_len override
    without an explicit bucket list must drop the already-derived buckets
    or the new length would keep the old (possibly too-short) ladder."""
    if not extra:
        return cfg
    import dataclasses

    if "max_model_len" in extra and "prefill_buckets" not in extra:
        extra = dict(extra, prefill_buckets=None)
    return dataclasses.replace(cfg, **extra)


def build_draft_config(target: EngineConfig) -> EngineConfig:
    """EngineConfig for the draft model of draft-speculative decoding.

    The draft's paged cache MIRRORS the target's block ids (same
    allocator decisions drive both), so block geometry must match
    exactly; the draft always runs unsharded (it is small by
    construction) with its K-step fused burst as the proposal program.
    """
    import dataclasses

    draft_model = ModelConfig.from_model_dir(target.spec_draft_model)
    if draft_model.vocab_size != target.model.vocab_size:
        # smaller: target ids are out of range for the draft. LARGER is
        # just as bad in the other direction — the draft can propose ids
        # the target's embedding gather clamps and the verify step never
        # accepts, silently wasting every speculation round.
        raise ValueError(
            f"draft vocab {draft_model.vocab_size} != target "
            f"{target.model.vocab_size}: the two must share a tokenizer "
            "(out-of-range ids are either invalid for the draft or "
            "never-accepted noise for the target)"
        )
    if draft_model.max_position_embeddings < target.max_model_len:
        raise ValueError(
            f"draft max_position_embeddings "
            f"{draft_model.max_position_embeddings} < target max_model_len "
            f"{target.max_model_len}: past its rope range the draft's "
            "proposals degrade to noise and every round pays for nothing"
        )
    return dataclasses.replace(
        target,
        model=draft_model,
        spec_draft_model=None, spec_draft_tokens=0,  # no recursion
        tp_size=1, dp_size=1, ep_size=1, pp_size=1,
        # K+1 burst steps for K proposals: the extra step writes the
        # K-th proposal's KV into the mirror cache, so a fully-accepted
        # round leaves no draft-KV hole behind the new context
        multi_step_decode=target.spec_draft_tokens + 1,
    )


class JaxServingEngine(AsyncEngine):
    def __init__(self, runner: ModelRunner, scheduler: Scheduler, config: EngineConfig):
        self.runner = runner
        self.scheduler = scheduler
        self.config = config
        # stall watchdog (telemetry/watchdog.py), attached by create();
        # held here so close() can cancel its task
        self.watchdog = None
        # guided JSON: grammars (and the vocab piece table they share)
        # are compiled once per distinct spec and reused across requests
        self._model_path: Optional[str] = None
        self._pieces = None
        self._json_grammars: dict = {}

    @classmethod
    async def create(
        cls,
        mdc,
        flags=None,
        engine_config: Optional[EngineConfig] = None,
        params=None,
        events: Optional[KvEventSink] = None,
        mesh=None,
        warmup: bool = True,
        disagg_factory=None,
    ) -> "JaxServingEngine":
        """Build from a ModelDeploymentCard (+CLI flags or explicit config).

        ``disagg_factory(runner) -> RemotePrefillCoordinator`` enables
        conditional remote prefill (disaggregated serving) on this engine.
        """
        if engine_config is None:
            engine_config = engine_config_from_mdc(mdc, flags)
        loop = asyncio.get_running_loop()
        runner_fut = loop.run_in_executor(
            None,
            lambda: ModelRunner(engine_config, params=params, mesh=mesh,
                                model_dir=mdc.model_path),
        )
        draft_runner = None
        if engine_config.spec_draft_model:
            # target and draft builds share nothing — load concurrently
            draft_config = build_draft_config(engine_config)
            draft_fut = loop.run_in_executor(
                None,
                lambda: ModelRunner(
                    draft_config, model_dir=engine_config.spec_draft_model
                ),
            )
            runner, draft_runner = await asyncio.gather(runner_fut, draft_fut)
        else:
            runner = await runner_fut
        disagg = None
        if disagg_factory is not None:
            if draft_runner is not None:
                raise ValueError(
                    "spec_draft_model is incompatible with disaggregated "
                    "remote prefill: remotely-computed KV never passes "
                    "through the draft model, so its mirror cache would "
                    "be stale for every remote-prefilled request"
                )
            disagg = await disagg_factory(runner)
        scheduler = Scheduler(runner, engine_config, events, disagg=disagg,
                              draft_runner=draft_runner)
        engine = cls(runner, scheduler, engine_config)
        engine._model_path = mdc.model_path  # guided-JSON piece table
        if warmup:
            futs = [loop.run_in_executor(None, runner.warmup)]
            if draft_runner is not None:
                futs.append(loop.run_in_executor(None, draft_runner.warmup))
            await asyncio.gather(*futs)
        scheduler.start()
        if engine_config.watchdog_stall_s > 0:
            from ..telemetry.watchdog import StallWatchdog

            # registered into the scheduler's registry so the trip
            # counter and loop-lag gauge render in the engine scrape;
            # registered as a dump source so GET /debug/flight and
            # SIGUSR2 include this engine's probe + request table
            engine.watchdog = StallWatchdog(
                probe=scheduler.watchdog_probe,
                requests=scheduler.request_table,
                registry=scheduler.registry,
                flight=scheduler.flight,
                interval_s=engine_config.watchdog_interval_s,
                stall_s=engine_config.watchdog_stall_s,
            ).start()
        return engine

    async def generate(self, request: Context[Any]) -> AsyncIterator[dict]:
        if self.scheduler.draining or self.scheduler._stopping:
            # a draining engine's admission is gated, and its extraction
            # pass has (or will have) already run — a request queued now
            # would sit in a seized scheduler forever. Fail fast with the
            # retryable subclass (HTTP edge → 503 + Retry-After).
            from ..runtime.engine import EngineDrainingError

            raise EngineDrainingError(
                "engine is draining (recovery or rolling update); "
                "retry against the worker pool"
            )
        payload = request.payload
        req = (
            payload
            if isinstance(payload, PreprocessedRequest)
            else PreprocessedRequest.from_wire(payload)
        )
        if not req.token_ids:
            raise EngineError("empty prompt")
        if len(req.token_ids) >= self.config.max_model_len:
            raise EngineError(
                f"prompt length {len(req.token_ids)} exceeds engine max_model_len "
                f"{self.config.max_model_len}"
            )
        # token-id prompts arrive unvalidated from /v1/completions; an
        # out-of-range id would fault deep inside the scheduler's penalty
        # state (numpy fancy indexing) and kill the engine loop for
        # everyone — reject HERE, per request
        vocab = self.config.model.vocab_size
        bad = next(
            (t for t in req.token_ids if not 0 <= int(t) < vocab), None
        )
        if bad is not None:
            raise EngineError(
                f"prompt token id {bad} outside vocab [0, {vocab})"
            )
        n = req.sampling_options.n
        if n is not None and n > 1:
            # engine-level n>1 fan-out: each choice becomes an
            # INDEPENDENT scheduler request (n=1, seed offset by choice
            # index — the preprocessor's _child_request convention), so
            # every choice is an ordinary device-checkable row the
            # persistent chain serves like any other; the choice-fold
            # happens here at drain, each delta tagged with its
            # EngineOutput.choice index.
            if n > 20:  # OpenAI's cap; also bounds the fan-out
                raise EngineError("n must be <= 20")
            async for out in self._generate_fanout(request, req, n):
                yield out
            return
        if (req.stop_conditions.max_tokens == 0
                and req.output_options.prompt_logprobs is None):
            # an empty completion: nothing to schedule, finish immediately
            # (AFTER the validation above — unsupported shapes must reject
            # consistently regardless of max_tokens). Prompt-SCORING
            # requests (prompt_logprobs + max_tokens=0, the OpenAI
            # echo+logprobs idiom) do schedule: the prefill must run for
            # its logits even though no token is generated.
            from ..protocols.common import EngineOutput, FinishReason

            yield EngineOutput(
                token_ids=[], finish_reason=FinishReason.LENGTH
            ).to_wire()
            return
        guided = None
        if req.sampling_options.guided_json:
            guided = await self._json_constraint(
                req.sampling_options.guided_json
            )
        er = EngineRequest(
            request_id=request.id or uuid.uuid4().hex,
            prompt=list(req.token_ids),
            req=req,
            ctx=request.context,
            out_queue=asyncio.Queue(),
            guided=guided,
        )
        self.scheduler.add_request(er)
        try:
            while True:
                out = await er.out_queue.get()
                if out is None:
                    return
                yield out.to_wire()
        finally:
            # consumer went away (stop/kill/break) — scheduler will reap it
            request.context.stop_generating()

    async def _generate_fanout(self, request: Context[Any],
                               req: PreprocessedRequest, n: int):
        """n>1 as n independent n=1 scheduler requests sharing the
        caller's cancellation context; deltas interleave in completion
        order, each stamped with its choice index, and the stream ends
        when every choice's sentinel arrived."""
        import dataclasses as _dc

        from ..runtime.engine import AsyncEngineContext

        base_seed = req.sampling_options.seed
        # per-choice child contexts (the preprocessor fan-out's
        # convention): cancellation isolation per choice, spans folded
        # back into the parent trace with #<choice> suffixes
        child_ctxs = [
            AsyncEngineContext(trace_id=request.context.trace_id)
            for _ in range(n)
        ]

        async def relay_stop() -> None:
            await request.context.wait_stopped()
            for c in child_ctxs:
                c.stop_generating()

        relay = asyncio.ensure_future(relay_stop())
        children = []
        base_id = request.id or uuid.uuid4().hex
        for i in range(n):
            child_req = _dc.replace(
                req,
                sampling_options=_dc.replace(
                    req.sampling_options, n=1,
                    seed=(base_seed + i) if base_seed is not None else None,
                ),
            )
            er = EngineRequest(
                request_id=f"{base_id}#{i}",
                prompt=list(req.token_ids),
                req=child_req,
                ctx=child_ctxs[i],
                out_queue=asyncio.Queue(),
                guided=(
                    await self._json_constraint(
                        req.sampling_options.guided_json)
                    if req.sampling_options.guided_json else None
                ),
            )
            children.append(er)
        merged: asyncio.Queue = asyncio.Queue()

        async def pump(i: int, er: EngineRequest):
            while True:
                out = await er.out_queue.get()
                await merged.put((i, out))
                if out is None:
                    return

        tasks = [asyncio.ensure_future(pump(i, er))
                 for i, er in enumerate(children)]
        for er in children:
            self.scheduler.add_request(er)
        open_choices = n
        try:
            while open_choices:
                i, out = await merged.get()
                if out is None:
                    open_choices -= 1
                    continue
                out.choice = i
                yield out.to_wire()
        finally:
            for t in tasks:
                t.cancel()
            relay.cancel()
            for c in child_ctxs:
                c.stop_generating()
            request.context.merge_stages_from(child_ctxs)

    async def _json_constraint(self, spec: dict):
        """Per-request cursor over the (cached) compiled grammar. The
        first request with a new spec pays the compile + the O(vocab)
        piece-table build in an executor thread; the scheduler loop
        never blocks on it."""
        import json as _json

        from ..runtime.engine import EngineError
        from .guided import JsonConstraint, JsonGrammar, build_piece_table

        key = _json.dumps(spec, sort_keys=True)
        entry = self._json_grammars.get(key)
        if isinstance(entry, asyncio.Future):
            # a concurrent first request is already building this spec:
            # await it instead of paying the O(vocab) sweep N times
            grammar = await asyncio.shield(entry)
        else:
            grammar = entry
        if grammar is None:
            if self._model_path is None:
                raise EngineError(
                    "guided json requires a tokenizer; this engine was "
                    "built without a model path"
                )
            loop = asyncio.get_running_loop()

            def build():
                if self._pieces is None:
                    from ..llm.tokenizer import HFTokenizer

                    tok = HFTokenizer.from_model_path(self._model_path)
                    self._pieces = build_piece_table(
                        tok, self.config.model.vocab_size
                    )
                schema = (spec.get("schema")
                          if spec.get("type") == "json_schema" else None)
                g = JsonGrammar(self._pieces, schema)
                # the first O(vocab) mask sweep belongs HERE (executor
                # thread), not on the event loop — and it doubles as
                # the expressibility check
                ids, _at_end = JsonConstraint(g).allowed()
                if not ids:
                    # e.g. a tokenizer whose vocab has no brace/quote
                    # pieces: the grammar is unsatisfiable — reject the
                    # request instead of streaming junk-then-stop
                    raise EngineError(
                        "guided json: this model's tokenizer cannot "
                        "express the requested grammar (no legal first "
                        "token)"
                    )
                return g

            fut = loop.create_future()
            self._json_grammars[key] = fut  # followers await this build
            try:
                grammar = await loop.run_in_executor(None, build)
            except ValueError as e:
                err = EngineError(f"guided json: {e}")
                fut.set_exception(err)
                fut.exception()  # consumed (no un-retrieved warning)
                self._json_grammars.pop(key, None)
                raise err
            except BaseException as e:
                fut.set_exception(e)
                fut.exception()
                self._json_grammars.pop(key, None)
                raise
            fut.set_result(grammar)
            # bounded LRU over distinct specs: each grammar's per-state
            # mask cache can reach vocab-sized lists — adversarial
            # unique-schema traffic must not grow memory without limit
            evictable = [k for k, v in self._json_grammars.items()
                         if not isinstance(v, asyncio.Future)]
            while len(self._json_grammars) > 32 and evictable:
                self._json_grammars.pop(evictable.pop(0), None)
            self._json_grammars[key] = grammar  # resolve future → value
        else:
            self._json_grammars.pop(key)
            self._json_grammars[key] = grammar  # LRU touch
        return JsonConstraint(grammar)

    @property
    def embed_ready(self) -> bool:
        return getattr(self.runner, "embed_ready", False)

    async def embed(self, prompts):
        """Batched prefill-only embeddings (the /v1/embeddings engine
        half): [n] token-id lists → [n, D] float32. The cacheless embed
        program reads params only — no donated buffers — so the device
        round trip can ride an executor thread beside the scheduler
        loop's own dispatches."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self.runner.embed_prompts, prompts
        )

    def metrics(self) -> dict:
        return self.scheduler.metrics()

    @property
    def registry(self):
        """The engine's MetricsRegistry (scheduler + KV allocator +
        disagg instruments) — attach it to the frontend's ServiceMetrics
        so one /metrics scrape covers every layer."""
        return self.scheduler.registry

    async def close(self) -> None:
        # watchdog first: a slow drain during scheduler.stop() must not
        # read as a stall and dump a spurious artifact mid-shutdown
        if self.watchdog is not None:
            await self.watchdog.stop()
        await self.scheduler.stop()
