"""Paged KV block allocator with prefix-cache reuse and KV event hooks.

Semantics follow the reference's block-manager design (SURVEY.md §2.2,
reference: lib/llm/src/kv/{manager,reuse,reserved}.rs) re-designed
around the engine's flat block-id space:

- ``allocate_prompt`` stages exactly like the reference's
  ``KvStorageManager::prepare_prefill_sequence`` (kv/manager.rs:22-121):
  match INFLIGHT blocks first (refcount > 0 — another sequence is
  actively computing/holding the same prefix, reference kv/reserved.rs),
  then REUSABLE pooled blocks (refcount 0, state preserved), then take
  fresh/evicted blocks and restore any host-tier extension.
- The reuse pool is priority-ordered FIFO, not flat LRU (reference
  kv/reuse.rs AvailableBlocks): eviction pops the lowest priority class
  first and oldest-returned within a class, so important prefixes (e.g.
  system prompts) are retained longest. Priorities attach per sequence
  hash via ``set_priority`` — the reference's UpdateBlock control path.
- ``pin_blocks``/``unpin_blocks`` fence a block against reclaim while an
  out-of-band consumer (host-tier restore in flight, a KV transfer
  reading the slot) depends on its contents — the reference's fence/
  reset machinery (kv/reuse.rs fence, docstring "Synchronization").
  Freeing a pinned block defers the release until unpin.
- Completed blocks (prompt or generated) are registered by sequence hash
  and announced via the ``events`` callback — the same stream the KV-aware
  router indexes (kv_router/publisher.py).
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..tokens import compute_block_hashes


@dataclasses.dataclass
class KvEventSink:
    """Engine-side KV event hooks (no-op by default).

    The ``_cold`` pair announces cold-tier residency (kv/cold_tier.py
    spills/evictions) so the router can score a rehydratable prefix —
    discounted vs a warm hit (kv_router/scheduler.py cold_discount)."""

    on_stored: Callable[[List[int], Optional[int]], None] = lambda hashes, parent: None
    on_removed: Callable[[List[int]], None] = lambda hashes: None
    on_stored_cold: Callable[[List[int], Optional[int]], None] = (
        lambda hashes, parent: None
    )
    on_removed_cold: Callable[[List[int]], None] = lambda hashes: None


class _ReusePool:
    """Priority-ordered FIFO of refcount-0 cached blocks.

    Eviction order is (priority asc, return-tick asc): the lowest
    priority class is drained first, oldest first within a class —
    the reference's PriorityKey ordering (kv/reuse.rs:246-270).
    Implemented as a lazy-deletion heap; membership is the dict.
    """

    def __init__(self) -> None:
        self._entry: Dict[int, Tuple[int, int]] = {}  # bid → (prio, tick)
        self._heap: List[Tuple[int, int, int]] = []   # (prio, tick, bid)
        self._tick = itertools.count()

    def add(self, bid: int, priority: int = 0) -> None:
        tick = next(self._tick)
        self._entry[bid] = (priority, tick)
        heapq.heappush(self._heap, (priority, tick, bid))

    def discard(self, bid: int) -> None:
        self._entry.pop(bid, None)  # heap entry invalidated lazily

    def reprioritize(self, bid: int, priority: int) -> None:
        if bid in self._entry:
            # keeps its FIFO position within the NEW class via a new tick
            self.add(bid, priority)

    def pop(self, skip: Optional[Set[int]] = None) -> Optional[int]:
        """Evict the (priority, FIFO)-first block, skipping ``skip``."""
        deferred: List[Tuple[int, int, int]] = []
        out: Optional[int] = None
        while self._heap:
            prio, tick, bid = heapq.heappop(self._heap)
            if self._entry.get(bid) != (prio, tick):
                continue  # stale entry (discarded or reprioritized)
            if skip and bid in skip:
                deferred.append((prio, tick, bid))
                continue
            del self._entry[bid]
            out = bid
            break
        for item in deferred:  # pinned blocks keep their order
            heapq.heappush(self._heap, item)
        return out

    def __contains__(self, bid: int) -> bool:
        return bid in self._entry

    def __len__(self) -> int:
        return len(self._entry)


class BlockAllocator:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        events: Optional[KvEventSink] = None,
        tier2=None,  # Optional[KvHostTier] — host-RAM offload tier
        registry=None,  # Optional[telemetry.MetricsRegistry]
        flight=None,  # Optional[telemetry.FlightRecorder]
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self.events = events or KvEventSink()
        if flight is None:
            from ..telemetry.flight import flight_recorder

            flight = flight_recorder()
        self.flight = flight
        self.tier2 = tier2
        # evictions collected during one allocation; offloaded in a single
        # batched gather (one device round-trip) by flush_offload
        self._pending_offload: List[Tuple[int, int]] = []
        self.free: List[int] = list(range(num_blocks - 1, -1, -1))  # pop() → block 0 first
        # sequence_hash → block id (cached, complete blocks)
        self.by_hash: Dict[int, int] = {}
        self.block_hash: Dict[int, int] = {}   # block id → sequence hash
        self.refcount: Dict[int, int] = {}
        # refcount-0 cached blocks, priority-FIFO order — evictable
        self.reusable = _ReusePool()
        # sequence_hash → retention priority (default 0; higher = kept longer)
        self.hash_priority: Dict[int, int] = {}
        # fenced blocks: excluded from eviction/free until unpinned.
        # COUNTED — two consumers can fence the same block (e.g. two
        # concurrent transfers reading it); the fence holds until the
        # last unpin
        self.pinned: Dict[int, int] = {}
        self._deferred_free: List[int] = []
        # match staging telemetry (reference manager.rs staging order)
        self.matched_inflight_total = 0
        self.matched_reusable_total = 0
        if registry is None:
            from ..telemetry.registry import MetricsRegistry

            registry = MetricsRegistry()  # private; owner renders nothing
        self._evictions = registry.counter(
            "dynamo_kv_evictions_total",
            "Cached blocks evicted from the reuse pool to satisfy demand",
        )
        registry.callback_gauge(
            "dynamo_kv_active_blocks", "KV blocks in use",
            # dynrace: domain(executor)
            lambda: self.used,
        )
        registry.callback_gauge(
            "dynamo_kv_total_blocks", "KV cache capacity in blocks",
            # dynrace: domain(executor)
            lambda: self.num_blocks,
        )
        registry.callback_gauge(
            "dynamo_kv_block_usage_ratio", "used / total KV blocks",
            # dynrace: domain(executor)
            lambda: self.usage(),
        )

    # ---------- accounting ----------

    @property
    def available(self) -> int:
        pinned_reusable = sum(1 for b in self.pinned if b in self.reusable)
        return len(self.free) + len(self.reusable) - pinned_reusable

    @property
    def used(self) -> int:
        return self.num_blocks - self.available

    # ---------- priorities / fences ----------

    def set_priority(self, sequence_hashes: List[int], priority: int) -> None:
        """Retention priority for blocks by content hash (reference:
        kv/reuse.rs UpdateBlock). Applies to blocks already pooled and to
        any future pooling of these hashes; priority 0 blocks evict first."""
        for h in sequence_hashes:
            if priority == 0:
                self.hash_priority.pop(h, None)
            else:
                self.hash_priority[h] = priority
            bid = self.by_hash.get(h)
            if bid is not None and bid in self.reusable:
                self.reusable.reprioritize(bid, priority)

    def pin_blocks(self, block_ids: List[int]) -> None:
        """Fence blocks against reclaim: a pinned block is never evicted
        from the reuse pool, and a concurrent free defers until the LAST
        unpin — the guard for restores/transfers reading the slot
        out-of-band."""
        for bid in block_ids:
            self.pinned[bid] = self.pinned.get(bid, 0) + 1

    def unpin_blocks(self, block_ids: List[int]) -> None:
        for bid in block_ids:
            n = self.pinned.get(bid, 0) - 1
            if n > 0:
                self.pinned[bid] = n
            else:
                self.pinned.pop(bid, None)
        if self._deferred_free:
            # a block re-acquired while pinned (probe_prefix matched it and
            # _ref'd) cancels its pending free: releasing it now would make
            # a LIVE block evictable (silent KV corruption on reuse)
            self._deferred_free = [
                b for b in self._deferred_free if self.refcount.get(b, 0) == 0
            ]
            ready = [b for b in self._deferred_free if b not in self.pinned]
            self._deferred_free = [
                b for b in self._deferred_free if b in self.pinned
            ]
            if ready:
                self._release(ready)

    def fence(self) -> None:
        """Synchronization point (reference kv/reuse.rs fence): all
        offloads queued/staged so far are committed to the host tier."""
        self.flush_offload()
        if self.tier2 is not None:
            self.tier2.drain()

    # ---------- core ops ----------

    def _take_block(self) -> int:
        if self.free:
            return self.free.pop()
        bid = self.reusable.pop(skip=self.pinned)
        if bid is not None:
            self._evictions.inc()
            self.flight.record(
                "kv.eviction", block=bid,
                offloaded=self.tier2 is not None,
            )
            h = self.block_hash.pop(bid, None)
            if h is not None:
                self.by_hash.pop(h, None)
                if self.tier2 is not None:
                    # KV is still intact in the slot — queue it for host
                    # offload; flushed (batched) before the slot is written
                    self._pending_offload.append((h, bid))
                self.events.on_removed([h])
            return bid
        self.flight.record(
            "kv.oom", used=self.used, total=self.num_blocks,
            pinned=len(self.pinned),
        )
        raise MemoryError("KV cache exhausted")

    def flush_offload(self) -> None:
        """Offload all queued evictions in one batched device gather.

        Must run before the evicted slots are overwritten; callers that
        allocate with ``flush=False`` own that ordering.
        """
        if self._pending_offload:
            pending, self._pending_offload = self._pending_offload, []
            self.tier2.offload_batch(pending)

    def match_prefix(self, token_ids: List[int]) -> Tuple[List[int], List[int]]:
        """Longest HBM-cached prefix of complete blocks.
        Returns (block_ids, their sequence hashes)."""
        hashes, blocks, _host = self.probe_prefix(token_ids)
        return blocks, hashes[: len(blocks)]

    def probe_prefix(self, token_ids: List[int]):
        """One hashing pass over both tiers.

        Returns (hashes, hbm_blocks, host_hashes): the HBM-resident prefix
        blocks, then the host-tier run extending it. Feed the result into
        ``allocate_prompt(probe=...)`` so hot callers hash the prompt once.
        ``cached_tokens(probe)`` gives the restorable-token count for
        scheduling decisions (e.g. the disagg local-vs-remote verdict).
        """
        if not self.enable_prefix_caching:
            return [], [], []
        hashes = compute_block_hashes(token_ids, self.block_size)
        blocks: List[int] = []
        for h in hashes:
            bid = self.by_hash.get(h)
            if bid is None:
                break
            blocks.append(bid)
        host_hashes: List[int] = []
        if self.tier2 is not None:
            host_hashes = self.tier2.match_extension(hashes, len(blocks))
        return hashes, blocks, host_hashes

    def cached_tokens(self, probe) -> int:
        _hashes, blocks, host_hashes = probe
        return (len(blocks) + len(host_hashes)) * self.block_size

    def allocate_prompt(
        self, token_ids: List[int], probe=None
    ) -> Tuple[List[int], int]:
        """Allocate blocks for a prompt; reuse cached prefix blocks from HBM
        and restore host-tier blocks into fresh slots.

        ``probe`` may carry a just-computed ``probe_prefix`` result (valid
        only if no allocator mutation happened in between).
        Returns (block_ids covering ceil(len/bs) blocks, num_cached_tokens).
        Raises MemoryError if the demand cannot be met (caller queues).
        """
        n_needed = max(1, -(-len(token_ids) // self.block_size))
        hashes, cached_blocks, host_hashes = (
            probe if probe is not None else self.probe_prefix(token_ids)
        )
        cached_blocks = list(cached_blocks)
        host_hashes = list(host_hashes)
        # a full-prompt hit still needs the last block re-filled only if the
        # prompt ends mid-block; always recompute at least one token so the
        # engine has logits to sample from
        if (len(cached_blocks) + len(host_hashes)) * self.block_size >= len(token_ids):
            if host_hashes:
                host_hashes.pop()
            else:
                cached_blocks = cached_blocks[:-1]
        n_new = n_needed - len(cached_blocks)
        # pinning the matched prefix removes its refcount-0 blocks from the
        # evictable pool, so subtract them — otherwise _take_block could
        # exhaust mid-allocation after state was already mutated
        pinned = sum(
            1 for bid in cached_blocks
            if bid in self.reusable and bid not in self.pinned
        )
        if n_new > self.available - pinned:
            self.flight.record(
                "kv.oom", needed=n_new,
                available=self.available - pinned, total=self.num_blocks,
            )
            raise MemoryError(
                f"need {n_new} blocks, {self.available - pinned} available"
            )
        # staging telemetry: inflight (shared with a live sequence) vs
        # reusable-pool matches — the reference's two match stages
        self.matched_inflight_total += sum(
            1 for bid in cached_blocks if self.refcount.get(bid, 0) > 0
        )
        self.matched_reusable_total += sum(
            1 for bid in cached_blocks if self.refcount.get(bid, 0) == 0
        )
        for bid in cached_blocks:
            self._ref(bid)
        new_blocks = [self._take_block() for _ in range(n_new)]
        for bid in new_blocks:
            self.refcount[bid] = self.refcount.get(bid, 0) + 1
        # offload evicted blocks (one batched gather) BEFORE restore may
        # write new data into any of those same slots
        self.flush_offload()

        if host_hashes:
            # commit staged offloads first: drain applies capacity
            # eviction, and the keep-check below must see the post-drain
            # store (a staged hash can be the one capacity evicts)
            self.tier2.drain()
            # taking blocks above may itself have evicted host-tier entries
            # (capacity pressure) — keep only the still-resident prefix run
            keep = 0
            while keep < len(host_hashes) and self.tier2.has(host_hashes[keep]):
                keep += 1
            host_hashes = host_hashes[:keep]
        if host_hashes:
            restore_bids = new_blocks[: len(host_hashes)]
            # fence the restore targets for the duration of the restore
            # dispatch: nothing may reclaim a slot with a copy in flight
            self.pin_blocks(restore_bids)
            try:
                self.tier2.restore(host_hashes, restore_bids)
            finally:
                self.unpin_blocks(restore_bids)
            for i, h in enumerate(host_hashes):
                idx = len(cached_blocks) + i
                parent = hashes[idx - 1] if idx > 0 else None
                self.register_complete(restore_bids[i], h, parent)

        num_cached = (len(cached_blocks) + len(host_hashes)) * self.block_size
        return cached_blocks + new_blocks, num_cached

    def allocate_n(self, n: int) -> List[int]:
        """``n`` anonymous blocks, all-or-nothing (migration admits: a
        partial reservation would strand a half-scattered transfer).
        On MemoryError everything taken so far is released first."""
        got: List[int] = []
        try:
            for _ in range(n):
                got.append(self.allocate_block(flush=False))
        except MemoryError:
            self.free_blocks(got)
            raise
        self.flush_offload()
        return got

    def allocate_block(self, flush: bool = True) -> int:
        """One more block for a growing (decoding) sequence.

        ``flush=False`` defers the host-offload gather so a caller growing
        many sequences in one step pays one batched device round-trip; it
        must call ``flush_offload()`` before the evicted slots are written.
        """
        bid = self._take_block()
        if flush:
            self.flush_offload()
        self.refcount[bid] = self.refcount.get(bid, 0) + 1
        return bid

    def _ref(self, bid: int) -> None:
        self.refcount[bid] = self.refcount.get(bid, 0) + 1
        self.reusable.discard(bid)  # no longer evictable

    def register_complete(
        self, bid: int, sequence_hash: int, parent_hash: Optional[int]
    ) -> None:
        """A block is now full with known content — make it matchable."""
        if not self.enable_prefix_caching:
            return
        existing = self.by_hash.get(sequence_hash)
        if existing is not None and existing != bid:
            return  # identical content already cached under another block
        self.by_hash[sequence_hash] = bid
        self.block_hash[bid] = sequence_hash
        self.events.on_stored([sequence_hash], parent_hash)

    def rollback_tail(self, block_ids: List[int], keep: int) -> List[int]:
        """Release the over-allocated tail of a sequence's block list.

        The dispatch-ahead decode pipeline reserves block headroom for
        2x the burst depth before every dispatch; a finish (eos/stop/
        max-token/cancel) detected one burst late leaves the row holding
        blocks whose only contents are over-decoded positions the host
        never committed. Those tail blocks are by construction anonymous
        (registration only ever covers positions below the host
        ``context_len``), so releasing them returns them straight to the
        free list. Returns the retained prefix.
        """
        keep = max(0, keep)
        tail = block_ids[keep:]
        if tail:
            self.free_blocks(tail)
        return block_ids[:keep]

    def free_blocks(self, block_ids: List[int]) -> None:
        """Release a sequence's references. Hashed blocks become reusable
        (still matchable until evicted); anonymous blocks go to the free
        list. Pinned blocks defer until ``unpin_blocks``."""
        ready: List[int] = []
        for bid in block_ids:
            rc = self.refcount.get(bid, 0) - 1
            if rc > 0:
                self.refcount[bid] = rc
                continue
            self.refcount.pop(bid, None)
            if bid in self.pinned:
                if bid not in self._deferred_free:  # re-freed after re-ref
                    self._deferred_free.append(bid)
                continue
            ready.append(bid)
        self._release(ready)

    def _release(self, block_ids: List[int]) -> None:
        removed_hashes: List[int] = []
        for bid in block_ids:
            h = self.block_hash.get(bid)
            if h is not None and self.enable_prefix_caching:
                self.reusable.add(bid, self.hash_priority.get(h, 0))
            else:
                h = self.block_hash.pop(bid, None)
                if h is not None:
                    self.by_hash.pop(h, None)
                    removed_hashes.append(h)
                self.free.append(bid)
        if removed_hashes:
            self.events.on_removed(removed_hashes)

    def usage(self) -> float:
        return self.used / self.num_blocks if self.num_blocks else 0.0
