"""Paged KV block allocator with prefix-cache reuse and KV event hooks.

Semantics follow the reference's block-manager design (SURVEY.md §2.2,
reference: lib/llm/src/kv/{manager,reuse}.rs — match-then-allocate with a
reuse pool of refcount-0 hashed blocks, LRU eviction) re-designed around
the engine's flat block-id space:

- ``allocate_prompt`` first matches the prompt's chained block hashes
  against cached blocks (prefix-cache hit → those tokens skip prefill),
  then takes free blocks, then evicts LRU reusable blocks.
- Completed blocks (prompt or generated) are registered by sequence hash
  and announced via the ``events`` callback — the same stream the KV-aware
  router indexes (kv_router/publisher.py).
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ..tokens import compute_block_hashes


@dataclasses.dataclass
class KvEventSink:
    """Engine-side KV event hooks (no-op by default)."""

    on_stored: Callable[[List[int], Optional[int]], None] = lambda hashes, parent: None
    on_removed: Callable[[List[int]], None] = lambda hashes: None


class BlockAllocator:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        events: Optional[KvEventSink] = None,
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self.events = events or KvEventSink()
        self.free: List[int] = list(range(num_blocks - 1, -1, -1))  # pop() → block 0 first
        # sequence_hash → block id (cached, complete blocks)
        self.by_hash: Dict[int, int] = {}
        self.block_hash: Dict[int, int] = {}   # block id → sequence hash
        self.refcount: Dict[int, int] = {}
        # refcount-0 cached blocks, LRU order (oldest first) — evictable
        self.reusable: "OrderedDict[int, None]" = OrderedDict()

    # ---------- accounting ----------

    @property
    def available(self) -> int:
        return len(self.free) + len(self.reusable)

    @property
    def used(self) -> int:
        return self.num_blocks - self.available

    # ---------- core ops ----------

    def _take_block(self) -> int:
        if self.free:
            return self.free.pop()
        if self.reusable:
            bid, _ = self.reusable.popitem(last=False)  # LRU
            h = self.block_hash.pop(bid, None)
            if h is not None:
                self.by_hash.pop(h, None)
                self.events.on_removed([h])
            return bid
        raise MemoryError("KV cache exhausted")

    def match_prefix(self, token_ids: List[int]) -> Tuple[List[int], List[int]]:
        """Longest cached prefix of complete blocks.
        Returns (block_ids, their sequence hashes)."""
        if not self.enable_prefix_caching:
            return [], []
        hashes = compute_block_hashes(token_ids, self.block_size)
        blocks: List[int] = []
        matched: List[int] = []
        for h in hashes:
            bid = self.by_hash.get(h)
            if bid is None:
                break
            blocks.append(bid)
            matched.append(h)
        return blocks, matched

    def allocate_prompt(
        self, token_ids: List[int], cached_blocks: Optional[List[int]] = None
    ) -> Tuple[List[int], int]:
        """Allocate blocks for a prompt; reuse cached prefix blocks.

        ``cached_blocks`` may carry a just-computed ``match_prefix`` result so
        hot callers don't hash the prompt twice (valid only if no allocator
        mutation happened in between).
        Returns (block_ids covering ceil(len/bs) blocks, num_cached_tokens).
        Raises MemoryError if the demand cannot be met (caller queues).
        """
        n_needed = max(1, -(-len(token_ids) // self.block_size))
        if cached_blocks is None:
            cached_blocks, _ = self.match_prefix(token_ids)
        else:
            cached_blocks = list(cached_blocks)
        # a full-prompt hit still needs the last block re-filled only if the
        # prompt ends mid-block; always recompute at least one token so the
        # engine has logits to sample from
        if len(cached_blocks) * self.block_size >= len(token_ids):
            cached_blocks = cached_blocks[:-1]
        n_new = n_needed - len(cached_blocks)
        if n_new > self.available:
            raise MemoryError(
                f"need {n_new} blocks, {self.available} available"
            )
        for bid in cached_blocks:
            self._ref(bid)
        new_blocks = [self._take_block() for _ in range(n_new)]
        for bid in new_blocks:
            self.refcount[bid] = self.refcount.get(bid, 0) + 1
        return cached_blocks + new_blocks, len(cached_blocks) * self.block_size

    def allocate_block(self) -> int:
        """One more block for a growing (decoding) sequence."""
        bid = self._take_block()
        self.refcount[bid] = self.refcount.get(bid, 0) + 1
        return bid

    def _ref(self, bid: int) -> None:
        self.refcount[bid] = self.refcount.get(bid, 0) + 1
        self.reusable.pop(bid, None)  # no longer evictable

    def register_complete(
        self, bid: int, sequence_hash: int, parent_hash: Optional[int]
    ) -> None:
        """A block is now full with known content — make it matchable."""
        if not self.enable_prefix_caching:
            return
        existing = self.by_hash.get(sequence_hash)
        if existing is not None and existing != bid:
            return  # identical content already cached under another block
        self.by_hash[sequence_hash] = bid
        self.block_hash[bid] = sequence_hash
        self.events.on_stored([sequence_hash], parent_hash)

    def free_blocks(self, block_ids: List[int]) -> None:
        """Release a sequence's references. Hashed blocks become reusable
        (still matchable until evicted); anonymous blocks go to the free list."""
        removed_hashes: List[int] = []
        for bid in block_ids:
            rc = self.refcount.get(bid, 0) - 1
            if rc > 0:
                self.refcount[bid] = rc
                continue
            self.refcount.pop(bid, None)
            if bid in self.block_hash and self.enable_prefix_caching:
                self.reusable[bid] = None
                self.reusable.move_to_end(bid)
            else:
                h = self.block_hash.pop(bid, None)
                if h is not None:
                    self.by_hash.pop(h, None)
                    removed_hashes.append(h)
                self.free.append(bid)
        if removed_hashes:
            self.events.on_removed(removed_hashes)

    def usage(self) -> float:
        return self.used / self.num_blocks if self.num_blocks else 0.0
