"""Paged KV block allocator with prefix-cache reuse and KV event hooks.

Semantics follow the reference's block-manager design (SURVEY.md §2.2,
reference: lib/llm/src/kv/{manager,reuse}.rs — match-then-allocate with a
reuse pool of refcount-0 hashed blocks, LRU eviction) re-designed around
the engine's flat block-id space:

- ``allocate_prompt`` first matches the prompt's chained block hashes
  against cached blocks (prefix-cache hit → those tokens skip prefill),
  then takes free blocks, then evicts LRU reusable blocks.
- Completed blocks (prompt or generated) are registered by sequence hash
  and announced via the ``events`` callback — the same stream the KV-aware
  router indexes (kv_router/publisher.py).
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ..tokens import compute_block_hashes


@dataclasses.dataclass
class KvEventSink:
    """Engine-side KV event hooks (no-op by default)."""

    on_stored: Callable[[List[int], Optional[int]], None] = lambda hashes, parent: None
    on_removed: Callable[[List[int]], None] = lambda hashes: None


class BlockAllocator:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        enable_prefix_caching: bool = True,
        events: Optional[KvEventSink] = None,
        tier2=None,  # Optional[KvHostTier] — host-RAM offload tier
    ):
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self.events = events or KvEventSink()
        self.tier2 = tier2
        # evictions collected during one allocation; offloaded in a single
        # batched gather (one device round-trip) by flush_offload
        self._pending_offload: List[Tuple[int, int]] = []
        self.free: List[int] = list(range(num_blocks - 1, -1, -1))  # pop() → block 0 first
        # sequence_hash → block id (cached, complete blocks)
        self.by_hash: Dict[int, int] = {}
        self.block_hash: Dict[int, int] = {}   # block id → sequence hash
        self.refcount: Dict[int, int] = {}
        # refcount-0 cached blocks, LRU order (oldest first) — evictable
        self.reusable: "OrderedDict[int, None]" = OrderedDict()

    # ---------- accounting ----------

    @property
    def available(self) -> int:
        return len(self.free) + len(self.reusable)

    @property
    def used(self) -> int:
        return self.num_blocks - self.available

    # ---------- core ops ----------

    def _take_block(self) -> int:
        if self.free:
            return self.free.pop()
        if self.reusable:
            bid, _ = self.reusable.popitem(last=False)  # LRU
            h = self.block_hash.pop(bid, None)
            if h is not None:
                self.by_hash.pop(h, None)
                if self.tier2 is not None:
                    # KV is still intact in the slot — queue it for host
                    # offload; flushed (batched) before the slot is written
                    self._pending_offload.append((h, bid))
                self.events.on_removed([h])
            return bid
        raise MemoryError("KV cache exhausted")

    def flush_offload(self) -> None:
        """Offload all queued evictions in one batched device gather.

        Must run before the evicted slots are overwritten; callers that
        allocate with ``flush=False`` own that ordering.
        """
        if self._pending_offload:
            pending, self._pending_offload = self._pending_offload, []
            self.tier2.offload_batch(pending)

    def match_prefix(self, token_ids: List[int]) -> Tuple[List[int], List[int]]:
        """Longest HBM-cached prefix of complete blocks.
        Returns (block_ids, their sequence hashes)."""
        hashes, blocks, _host = self.probe_prefix(token_ids)
        return blocks, hashes[: len(blocks)]

    def probe_prefix(self, token_ids: List[int]):
        """One hashing pass over both tiers.

        Returns (hashes, hbm_blocks, host_hashes): the HBM-resident prefix
        blocks, then the host-tier run extending it. Feed the result into
        ``allocate_prompt(probe=...)`` so hot callers hash the prompt once.
        ``cached_tokens(probe)`` gives the restorable-token count for
        scheduling decisions (e.g. the disagg local-vs-remote verdict).
        """
        if not self.enable_prefix_caching:
            return [], [], []
        hashes = compute_block_hashes(token_ids, self.block_size)
        blocks: List[int] = []
        for h in hashes:
            bid = self.by_hash.get(h)
            if bid is None:
                break
            blocks.append(bid)
        host_hashes: List[int] = []
        if self.tier2 is not None:
            host_hashes = self.tier2.match_extension(hashes, len(blocks))
        return hashes, blocks, host_hashes

    def cached_tokens(self, probe) -> int:
        _hashes, blocks, host_hashes = probe
        return (len(blocks) + len(host_hashes)) * self.block_size

    def allocate_prompt(
        self, token_ids: List[int], probe=None
    ) -> Tuple[List[int], int]:
        """Allocate blocks for a prompt; reuse cached prefix blocks from HBM
        and restore host-tier blocks into fresh slots.

        ``probe`` may carry a just-computed ``probe_prefix`` result (valid
        only if no allocator mutation happened in between).
        Returns (block_ids covering ceil(len/bs) blocks, num_cached_tokens).
        Raises MemoryError if the demand cannot be met (caller queues).
        """
        n_needed = max(1, -(-len(token_ids) // self.block_size))
        hashes, cached_blocks, host_hashes = (
            probe if probe is not None else self.probe_prefix(token_ids)
        )
        cached_blocks = list(cached_blocks)
        host_hashes = list(host_hashes)
        # a full-prompt hit still needs the last block re-filled only if the
        # prompt ends mid-block; always recompute at least one token so the
        # engine has logits to sample from
        if (len(cached_blocks) + len(host_hashes)) * self.block_size >= len(token_ids):
            if host_hashes:
                host_hashes.pop()
            else:
                cached_blocks = cached_blocks[:-1]
        n_new = n_needed - len(cached_blocks)
        # pinning the matched prefix removes its refcount-0 blocks from the
        # evictable pool, so subtract them — otherwise _take_block could
        # exhaust mid-allocation after state was already mutated
        pinned = sum(1 for bid in cached_blocks if bid in self.reusable)
        if n_new > self.available - pinned:
            raise MemoryError(
                f"need {n_new} blocks, {self.available - pinned} available"
            )
        for bid in cached_blocks:
            self._ref(bid)
        new_blocks = [self._take_block() for _ in range(n_new)]
        for bid in new_blocks:
            self.refcount[bid] = self.refcount.get(bid, 0) + 1
        # offload evicted blocks (one batched gather) BEFORE restore may
        # write new data into any of those same slots
        self.flush_offload()

        if host_hashes:
            # taking blocks above may itself have evicted host-tier entries
            # (capacity pressure) — keep only the still-resident prefix run
            keep = 0
            while keep < len(host_hashes) and self.tier2.has(host_hashes[keep]):
                keep += 1
            host_hashes = host_hashes[:keep]
        if host_hashes:
            restore_bids = new_blocks[: len(host_hashes)]
            self.tier2.restore(host_hashes, restore_bids)
            for i, h in enumerate(host_hashes):
                idx = len(cached_blocks) + i
                parent = hashes[idx - 1] if idx > 0 else None
                self.register_complete(restore_bids[i], h, parent)

        num_cached = (len(cached_blocks) + len(host_hashes)) * self.block_size
        return cached_blocks + new_blocks, num_cached

    def allocate_block(self, flush: bool = True) -> int:
        """One more block for a growing (decoding) sequence.

        ``flush=False`` defers the host-offload gather so a caller growing
        many sequences in one step pays one batched device round-trip; it
        must call ``flush_offload()`` before the evicted slots are written.
        """
        bid = self._take_block()
        if flush:
            self.flush_offload()
        self.refcount[bid] = self.refcount.get(bid, 0) + 1
        return bid

    def _ref(self, bid: int) -> None:
        self.refcount[bid] = self.refcount.get(bid, 0) + 1
        self.reusable.pop(bid, None)  # no longer evictable

    def register_complete(
        self, bid: int, sequence_hash: int, parent_hash: Optional[int]
    ) -> None:
        """A block is now full with known content — make it matchable."""
        if not self.enable_prefix_caching:
            return
        existing = self.by_hash.get(sequence_hash)
        if existing is not None and existing != bid:
            return  # identical content already cached under another block
        self.by_hash[sequence_hash] = bid
        self.block_hash[bid] = sequence_hash
        self.events.on_stored([sequence_hash], parent_hash)

    def free_blocks(self, block_ids: List[int]) -> None:
        """Release a sequence's references. Hashed blocks become reusable
        (still matchable until evicted); anonymous blocks go to the free list."""
        removed_hashes: List[int] = []
        for bid in block_ids:
            rc = self.refcount.get(bid, 0) - 1
            if rc > 0:
                self.refcount[bid] = rc
                continue
            self.refcount.pop(bid, None)
            if bid in self.block_hash and self.enable_prefix_caching:
                self.reusable[bid] = None
                self.reusable.move_to_end(bid)
            else:
                h = self.block_hash.pop(bid, None)
                if h is not None:
                    self.by_hash.pop(h, None)
                    removed_hashes.append(h)
                self.free.append(bid)
        if removed_hashes:
            self.events.on_removed(removed_hashes)

    def usage(self) -> float:
        return self.used / self.num_blocks if self.num_blocks else 0.0
