"""Batched in-jit sampling: greedy / temperature / top-k / top-p / min-p plus
presence, frequency and repetition penalties — all per slot.

All parameters are per-request arrays so one compiled program serves every
sampling configuration in the batch (no recompiles when requests differ).
temperature == 0 means greedy. Every request samples from its own PRNG key
(seeded requests are bit-reproducible and isolated from their batchmates —
reference surface: lib/llm/src/protocols/common.rs:248-316 SamplingOptions).

Penalty state lives on device as two [num_slots, vocab] buffers owned by the
ModelRunner: ``counts`` (how often each token was *generated*) and ``seen``
(tokens present in the prompt). Penalty semantics follow the de-facto
standard the reference's engines implement (vLLM):

- repetition_penalty r: for tokens in prompt or output, positive logits are
  divided by r, negative multiplied (r == 1 disables).
- presence_penalty: subtracted once from every token that has been generated.
- frequency_penalty: subtracted per occurrence of a generated token.
- min_p: after temperature scaling, tokens with prob < min_p * max_prob drop.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..protocols.common import SamplingOptions


@dataclasses.dataclass
class SamplingParams:
    """Per-slot device arrays; batch dimension leads."""

    temperature: jax.Array          # [B] f32; 0 → greedy
    top_k: jax.Array                # [B] i32; 0 → disabled
    top_p: jax.Array                # [B] f32; 1.0 → disabled
    min_p: jax.Array                # [B] f32; 0.0 → disabled
    presence_penalty: jax.Array     # [B] f32; 0.0 → disabled
    frequency_penalty: jax.Array    # [B] f32; 0.0 → disabled
    repetition_penalty: jax.Array   # [B] f32; 1.0 → disabled
    keys: jax.Array                 # [B, 2] u32 per-request base PRNG keys
    counters: jax.Array             # [B] i32 fold-in step counters

    @classmethod
    def zeros(cls, batch: int) -> "SamplingParams":
        return cls(
            temperature=jnp.zeros(batch, jnp.float32),
            top_k=jnp.zeros(batch, jnp.int32),
            top_p=jnp.ones(batch, jnp.float32),
            min_p=jnp.zeros(batch, jnp.float32),
            presence_penalty=jnp.zeros(batch, jnp.float32),
            frequency_penalty=jnp.zeros(batch, jnp.float32),
            repetition_penalty=jnp.ones(batch, jnp.float32),
            keys=jnp.zeros((batch, 2), jnp.uint32),
            counters=jnp.arange(batch, dtype=jnp.int32),
        )


jax.tree_util.register_dataclass(
    SamplingParams,
    data_fields=[f.name for f in dataclasses.fields(SamplingParams)],
    meta_fields=[],
)


def host_row(opts: SamplingOptions):
    """One request's SamplingOptions → the per-slot host scalars
    (temperature, top_k, top_p, min_p, presence, frequency, repetition)."""
    temp = opts.temperature if opts.temperature is not None else 1.0
    return (
        float(temp),
        int(opts.top_k) if opts.top_k and opts.top_k > 0 else 0,
        float(opts.top_p) if opts.top_p is not None else 1.0,
        float(opts.min_p) if opts.min_p else 0.0,
        float(opts.presence_penalty) if opts.presence_penalty else 0.0,
        float(opts.frequency_penalty) if opts.frequency_penalty else 0.0,
        float(opts.repetition_penalty) if opts.repetition_penalty else 1.0,
    )


def seed_to_key(seed: int) -> np.ndarray:
    """A per-request base key from an explicit user seed (uint32[2])."""
    seed = int(seed)
    return np.asarray(
        [(seed >> 32) & 0xFFFFFFFF, seed & 0xFFFFFFFF], np.uint32
    )


def _row_keys(params: SamplingParams) -> jax.Array:
    """Fold each row's step counter into its base key (typed key array)."""
    def fold(kdata, c):
        return jax.random.fold_in(
            jax.random.wrap_key_data(kdata, impl="threefry2x32"), c
        )
    return jax.vmap(fold)(params.keys, params.counters)


def sample(
    logits: jax.Array,  # [B, V] f32
    params: SamplingParams,
    counts: Optional[jax.Array] = None,   # [B, V] i32 generated-token counts
    seen: Optional[jax.Array] = None,     # [B, V] bool prompt-token presence
    bias: Optional[jax.Array] = None,     # [B, V] f32 OpenAI logit_bias rows
) -> jax.Array:
    """Returns sampled token ids [B]."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)
    if bias is not None:
        logits = logits + bias

    # ---- penalties (on raw logits, before temperature) ----
    if counts is not None:
        generated = counts > 0
        ever = generated if seen is None else (generated | seen)
        rp = params.repetition_penalty[:, None]
        logits = jnp.where(
            ever, jnp.where(logits > 0, logits / rp, logits * rp), logits
        )
        logits = logits - params.frequency_penalty[:, None] * counts.astype(jnp.float32)
        logits = logits - params.presence_penalty[:, None] * generated.astype(jnp.float32)

    greedy = jnp.argmax(logits, axis=-1)

    # temperature scaling (guard against 0 for the sampled branch)
    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k: mask everything below the k-th largest (k=0 → no-op)
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]  # descending
    k_idx = jnp.clip(params.top_k - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_logits, k_idx[:, None], axis=1)
    topk_mask = (params.top_k[:, None] > 0) & (scaled < kth)
    scaled = jnp.where(topk_mask, -jnp.inf, scaled)

    # min-p: drop tokens whose prob is below min_p * max_prob. Computed on
    # the already-top-k-masked logits, like the engines the reference wraps.
    probs_all = jax.nn.softmax(scaled, axis=-1)
    minp_mask = probs_all < params.min_p[:, None] * probs_all.max(axis=-1, keepdims=True)
    scaled = jnp.where(minp_mask, -jnp.inf, scaled)

    # top-p (nucleus): mask the tail whose cumulative prob exceeds p
    sort_idx = jnp.argsort(scaled, axis=-1)[:, ::-1]
    sorted_scaled = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = cum - probs < params.top_p[:, None]  # always keep the top token
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(b)[:, None], sort_idx
    ].set(keep_sorted)
    scaled = jnp.where(keep, scaled, -jnp.inf)

    row_keys = _row_keys(params)
    sampled = jax.vmap(lambda k, l: jax.random.categorical(k, l))(row_keys, scaled)
    return jnp.where(params.temperature <= 0.0, greedy, sampled).astype(jnp.int32)


# ---- device-resident finish detection (the persistent decode loop) ----
#
# The fused decode burst can evaluate EOS / hidden-stop / max-token /
# model-len checks inside its scan and freeze finished rows instead of
# ending the burst (model_runner._build_burst's device-finish variant).
# The per-row stop-token set rides as a fixed-width id matrix; requests
# whose set overflows the width stay on the host sync path (the
# scheduler's admission-time "device-checkable" classification) and are
# COUNTED there (dynamo_engine_sync_fallback_total{reason}) instead of
# silently downgrading.

# ids per row: eos ids + hidden stop ids, -1 padded. Widened 8 → 16
# (two rows' worth of the original matrix packed into one): requests
# with 9-16 stop/eos ids used to fall out of the chain silently.
STOP_ID_WIDTH = 16


def stop_id_row(eos_ids, hidden_ids, ignore_eos: bool) -> Optional[np.ndarray]:
    """One request's device stop-token row: the merged eos (unless
    suppressed) + hidden-stop id set, -1 padded to ``STOP_ID_WIDTH``.
    Returns None when the set overflows the width — the request is not
    device-checkable and must keep host-side finish checks."""
    ids = set() if ignore_eos else {int(t) for t in (eos_ids or [])}
    ids |= {int(t) for t in (hidden_ids or [])}
    if len(ids) > STOP_ID_WIDTH:
        return None
    row = np.full(STOP_ID_WIDTH, -1, np.int32)
    row[: len(ids)] = sorted(ids)
    return row


def device_finish_mask(
    tokens: jax.Array,     # [B] i32 the step's sampled tokens
    gen: jax.Array,        # [B] i32 generated count INCLUDING this token
    pos: jax.Array,        # [B] i32 position the step's forward ran at
    stop_ids: jax.Array,   # [B, STOP_ID_WIDTH] i32, -1 padded
    min_new: jax.Array,    # [B] i32 min_tokens (suppresses eos/stop below)
    max_new: jax.Array,    # [B] i32 effective max_tokens
    max_model_len: int,
) -> jax.Array:
    """Per-row finish verdict for one scan step — the exact device
    mirror of ``Scheduler._check_finish``: at host-check time the
    committed context is ``pos + 1`` (the pending token's KV was just
    written), so the model-len bound reads ``pos + 2 >= max_model_len``.
    Token ids are non-negative, so the -1 padding never matches."""
    hit = (tokens[:, None] == stop_ids).any(axis=1)
    stop = (gen >= min_new) & hit
    length = (gen >= max_new) | (pos + 2 >= max_model_len)
    return stop | length


# ---- device-approximate stop strings (suffix ring + rolling hash) ----
#
# Stop STRINGS are a text-level condition the engine cannot evaluate
# exactly (it holds no tokenizer), so chained rows use an APPROXIMATION:
# the preprocessor ships each stop string's canonical tokenization
# (StopConditions.stop_token_seqs), the burst program carries a ring of
# the last SUFFIX_RING_W emitted tokens per row, and each step compares
# rolling polynomial hashes of the ring's suffixes against the
# precomputed per-sequence target hashes. A match FREEZES the row as a
# stop *candidate*; the host confirms on drain with an exact token-
# suffix compare (Scheduler._check_finish runs the same check on every
# emitted token, so a true candidate already carries its STOP verdict)
# and a hash collision resumes the row byte-identically. Non-canonical
# tokenizations of a stop string are still caught by the backend
# detokenizer jail, exactly as on the sync path.

SUFFIX_RING_W = 32   # trailing tokens carried per row (also feeds ngram)
STOP_SEQ_WIDTH = 4   # stop sequences per row the device can watch
STOP_SEQ_MAX_LEN = 8 # tokens per watched sequence

_HASH_P = np.uint32(1000003)


def stop_seq_hash(seq) -> int:
    """Polynomial hash of one token sequence (uint32, wrapping) — the
    host mirror of the in-program rolling suffix hash."""
    h = np.uint32(0)
    with np.errstate(over="ignore"):
        for t in seq:
            h = np.uint32(h * _HASH_P + np.uint32(int(t) + 1))
    return int(h)


def stop_seq_rows(seqs):
    """Pack one request's stop token sequences into the device rows:
    ``(hashes [STOP_SEQ_WIDTH] uint32, lens [STOP_SEQ_WIDTH] int32)``.
    Returns None when the set overflows the width/length bounds — the
    request is not device-checkable (counted, never silent)."""
    seqs = [tuple(int(t) for t in s) for s in (seqs or []) if s]
    if not seqs or len(seqs) > STOP_SEQ_WIDTH:
        return None
    if any(len(s) > STOP_SEQ_MAX_LEN for s in seqs):
        return None
    hashes = np.zeros(STOP_SEQ_WIDTH, np.uint32)
    lens = np.zeros(STOP_SEQ_WIDTH, np.int32)
    for i, s in enumerate(seqs):
        hashes[i] = stop_seq_hash(s)
        lens[i] = len(s)
    return hashes, lens


def ring_init(tokens, width: int = SUFFIX_RING_W) -> np.ndarray:
    """Host-side ring fill: the last ``width`` tokens of the emitted
    history (prompt + generated, ending with the pending token), -1
    padded on the left. The chain-fill input for the burst carry."""
    row = np.full(width, -1, np.int32)
    tail = list(tokens)[-width:]
    if tail:
        row[-len(tail):] = tail
    return row


def ring_push(ring: jax.Array, tokens: jax.Array,
              live: jax.Array) -> jax.Array:
    """Shift each LIVE row's ring left and append its new token."""
    shifted = jnp.concatenate(
        [ring[:, 1:], tokens[:, None].astype(ring.dtype)], axis=1
    )
    return jnp.where(live[:, None], shifted, ring)


def suffix_hashes(ring: jax.Array) -> jax.Array:
    """[B, STOP_SEQ_MAX_LEN + 1] rolling hashes of the ring's trailing
    suffixes: column L is the hash of the last L tokens (column 0 = 0).
    Unrolled over the (small, static) max length — pure vector ops."""
    b, w = ring.shape
    toks = (ring.astype(jnp.uint32) + jnp.uint32(1))
    cols = [jnp.zeros((b,), jnp.uint32)]
    p_pow = jnp.uint32(1)
    for ell in range(1, STOP_SEQ_MAX_LEN + 1):
        cols.append(cols[-1] + toks[:, w - ell] * p_pow)
        p_pow = p_pow * _HASH_P
    return jnp.stack(cols, axis=1)


def stop_candidate_mask(
    ring: jax.Array,       # [B, W] trailing tokens INCLUDING this step's
    gen: jax.Array,        # [B] generated count including this token
    min_new: jax.Array,    # [B] min_tokens (suppresses stops below)
    stop_hash: jax.Array,  # [B, STOP_SEQ_WIDTH] uint32 target hashes
    stop_len: jax.Array,   # [B, STOP_SEQ_WIDTH] i32 lengths (0 = unused)
) -> jax.Array:
    """Per-row stop-STRING candidate verdict for one step: any watched
    sequence whose length-L suffix hash matches, gated so the whole
    suffix is generated output (gen >= L) and min_tokens is satisfied."""
    hs = suffix_hashes(ring)                              # [B, L+1]
    sel = jnp.take_along_axis(
        hs, jnp.clip(stop_len, 0, STOP_SEQ_MAX_LEN), axis=1
    )                                                     # [B, NS]
    cand = (
        (stop_len > 0)
        & (gen[:, None] >= stop_len)
        & (gen[:, None] >= min_new[:, None])
        & (sel == stop_hash)
    )
    return cand.any(axis=1)


# alternatives returned with every step — covers OpenAI's top_logprobs
# (≤ 20); a fixed width keeps the step program's shapes static
TOP_LOGPROBS_K = 20


def top_k_width(vocab_size: int) -> int:
    """The step program's top-logprobs width: lax.top_k(k) requires
    k <= vocab (tiny test vocabs would otherwise fail outright)."""
    return min(TOP_LOGPROBS_K, vocab_size)


def top_logprobs_for(logits: jax.Array, logp: Optional[jax.Array] = None) -> tuple:
    """(values [B, K], ids [B, K]) of the K most likely tokens per row.

    Pass ``logp`` to reuse an already-computed log_softmax (the step
    program shares it with the chosen-token logprob)."""
    if logp is None:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    vals, ids = jax.lax.top_k(logp, top_k_width(logits.shape[-1]))
    return vals, ids.astype(jnp.int32)
