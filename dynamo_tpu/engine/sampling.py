"""Batched in-jit sampling: greedy / temperature / top-k / top-p per slot.

All parameters are per-request arrays so one compiled program serves every
sampling configuration in the batch (no recompiles when requests differ).
temperature == 0 means greedy.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

from ..protocols.common import SamplingOptions


@dataclasses.dataclass
class SamplingParams:
    """Per-slot device arrays; batch dimension leads."""

    temperature: jax.Array  # [B] f32; 0 → greedy
    top_k: jax.Array        # [B] i32; 0 → disabled
    top_p: jax.Array        # [B] f32; 1.0 → disabled

    @classmethod
    def zeros(cls, batch: int) -> "SamplingParams":
        return cls(
            temperature=jnp.zeros(batch, jnp.float32),
            top_k=jnp.zeros(batch, jnp.int32),
            top_p=jnp.ones(batch, jnp.float32),
        )


def host_row(opts: SamplingOptions):
    """One request's SamplingOptions → (temperature, top_k, top_p) scalars."""
    temp = opts.temperature if opts.temperature is not None else 1.0
    return (
        float(temp),
        int(opts.top_k) if opts.top_k and opts.top_k > 0 else 0,
        float(opts.top_p) if opts.top_p is not None else 1.0,
    )


def sample(
    logits: jax.Array,  # [B, V] f32
    params: SamplingParams,
    key: jax.Array,
) -> jax.Array:
    """Returns sampled token ids [B]."""
    b, v = logits.shape
    logits = logits.astype(jnp.float32)

    greedy = jnp.argmax(logits, axis=-1)

    # temperature scaling (guard against 0 for the sampled branch)
    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits / temp

    # top-k: mask everything below the k-th largest (k=0 → no-op)
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]  # descending
    k_idx = jnp.clip(params.top_k - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_logits, k_idx[:, None], axis=1)
    topk_mask = (params.top_k[:, None] > 0) & (scaled < kth)
    scaled = jnp.where(topk_mask, -jnp.inf, scaled)

    # top-p (nucleus): mask the tail whose cumulative prob exceeds p
    sort_idx = jnp.argsort(scaled, axis=-1)[:, ::-1]
    sorted_scaled = jnp.take_along_axis(scaled, sort_idx, axis=-1)
    probs = jax.nn.softmax(sorted_scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = cum - probs < params.top_p[:, None]  # always keep the top token
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(b)[:, None], sort_idx
    ].set(keep_sorted)
    scaled = jnp.where(keep, scaled, -jnp.inf)

    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(params.temperature <= 0.0, greedy, sampled).astype(jnp.int32)


def logprobs_for(
    logits: jax.Array,   # [B, V]
    token_ids: jax.Array,  # [B]
) -> jax.Array:
    """Log-probability of the chosen tokens (for OutputOptions.logprobs)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.take_along_axis(logp, token_ids[:, None], axis=-1)[:, 0]
