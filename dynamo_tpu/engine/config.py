"""Engine configuration: model architecture + serving shapes + mesh layout.

Everything that determines compiled-program shapes lives here, because under
jit every distinct shape is a recompile: decode batch is fixed at
``max_batch_size`` (inactive slots masked), prefill lengths are bucketed,
block tables are fixed-width.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import List, Optional, Tuple


@dataclasses.dataclass
class ModelConfig:
    """Llama-family architecture description (HF config.json compatible)."""

    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632
    num_layers: int = 16
    num_heads: int = 16
    num_kv_heads: int = 8
    head_dim: Optional[int] = None
    rope_theta: float = 10000.0
    # HF rope_scaling dict (rope_type/type + params): "linear", "llama3"
    # and "yarn" (incl. DeepSeek's mscale variant) are applied exactly
    # (models/llama.rope_frequencies); other types load with a loud
    # warning (unscaled frequencies)
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 4096
    tie_word_embeddings: bool = False
    # qkv projection biases (Qwen2-family); o_proj stays bias-free
    attention_bias: bool = False
    # MoE (Mixtral-class); num_experts == 0 means dense
    num_experts: int = 0
    num_experts_per_tok: int = 2
    moe_capacity_factor: float = 2.0  # headroom over perfectly-balanced routing
    moe_intermediate_size: int = 0    # per-expert width; 0 → intermediate_size
    n_shared_experts: int = 0         # DeepSeek always-on shared expert count
    first_k_dense_replace: int = 0    # DeepSeek: first k layers use dense MLP
    # routing semantics (DeepSeek): gate score fn, top-k weight normalization,
    # and the scaling applied to the routed (non-shared) output
    moe_scoring_func: str = "softmax"  # "softmax" | "sigmoid"
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    # DeepSeek group-limited routing: experts partition into n_group
    # groups, top-k selection is restricted to the topk_group
    # best-scoring groups (V2 "group_limited_greedy" scores a group by
    # its max expert, V3 "noaux_tc" by its top-2 sum of biased scores).
    # n_group == 1 disables the restriction (Mixtral/Qwen/V2-Lite).
    n_group: int = 1
    topk_group: int = 1
    # attention implementation: "auto" (pallas on TPU, xla elsewhere),
    # "xla", or "pallas"
    attention_impl: str = "auto"
    # serving-time weight quantization: None (checkpoint dtype) or "int8"
    # (per-out-channel weight-only; halves the decode weight stream —
    # models/quant.py QUANT_KEYS: llama-family trunks, MoE expert
    # stacks incl. GPT-OSS fused gate/up, DeepSeek shared experts and
    # MLA low-rank projections).
    quantization: Optional[str] = None
    # Gemma-2 family (models/gemma2.py): sandwich norms, GeGLU, logit
    # softcapping, alternating sliding-window attention. model_family
    # "gemma2" routes models.resolve; the numeric fields are 0/off for
    # every other family.
    model_family: str = ""
    attn_logit_softcap: float = 0.0
    final_logit_softcap: float = 0.0
    query_pre_attn_scalar: int = 0
    sliding_window: int = 0
    # MLA (DeepSeek-class); kv_lora_rank > 0 enables MLA attention
    kv_lora_rank: int = 0
    q_lora_rank: int = 0
    qk_rope_head_dim: int = 0
    qk_nope_head_dim: int = 0
    v_head_dim: int = 0

    def __post_init__(self):
        if self.head_dim is None:
            self.head_dim = self.hidden_size // self.num_heads
        if self.kv_lora_rank > 0:
            missing = [
                name for name in
                ("qk_nope_head_dim", "qk_rope_head_dim", "v_head_dim")
                if getattr(self, name) <= 0
            ]
            if missing:
                raise ValueError(
                    f"kv_lora_rank={self.kv_lora_rank} selects MLA attention, "
                    f"which also requires {', '.join(missing)} > 0"
                )

    @classmethod
    def from_hf_config(cls, config: dict) -> "ModelConfig":
        arch = str(config.get("architectures", "")).lower()
        rope_scaling = config.get("rope_scaling") or None
        if rope_scaling and rope_scaling.get(
                "rope_type", rope_scaling.get("type")) in ("longrope", "su"):
            # longrope's profile choice and attention factor need the
            # original/extended windows, which live OUTSIDE the HF
            # rope_scaling dict — carry them in (models/llama.py)
            rope_scaling = dict(rope_scaling)
            rope_scaling.setdefault(
                "original_max_position_embeddings",
                config.get("original_max_position_embeddings")
                or config.get("max_position_embeddings", 4096),
            )
            rope_scaling.setdefault(
                "max_position_embeddings",
                config.get("max_position_embeddings", 4096),
            )
        if config.get("num_experts") and (
            config.get("mlp_only_layers")
            or config.get("decoder_sparse_step", 1) != 1
        ):
            # Qwen-MoE variants that interleave dense MLP layers; the
            # MoE trunk here is uniformly sparse
            raise NotImplementedError(
                "MoE checkpoints with mlp_only_layers/decoder_sparse_step "
                "(mixed dense+sparse trunks) are not supported"
            )
        lt = config.get("layer_types")
        if "gptoss" in arch and lt:
            want = [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(len(lt))
            ]
            if list(lt) != want:
                # the family module hardcodes the even-sliding alternation
                # (models/gptoss.py window = li % 2 == 0)
                raise NotImplementedError(
                    "gpt-oss layer_types must alternate "
                    "sliding/full starting sliding at layer 0"
                )
        if config.get("shared_expert_intermediate_size"):
            # Qwen2-MoE's sigmoid-gated shared expert — reject at config
            # parse, BEFORE any multi-GB checkpoint stream starts (the
            # loader keeps a tensor-level backstop)
            raise NotImplementedError(
                "Qwen2-MoE checkpoints (gated shared expert) are not "
                "supported; Qwen3-MoE and Mixtral load"
            )
        n_group = config.get("n_group", 1) or 1
        topk_group = config.get("topk_group", 1) or 1
        if config.get("topk_method") == "greedy":
            # DeepSeek-V2-Lite ships n_group in its config but routes
            # plain greedy — the restriction is off
            n_group = topk_group = 1
        n_experts = (config.get("num_local_experts", 0)
                     or config.get("n_routed_experts", 0)
                     or config.get("num_experts", 0) or 0)
        if n_group > 1:
            # the group-limited restriction only composes when the
            # expert set tiles evenly into groups and the selection can
            # still fill top_k from the permitted groups
            if n_experts % n_group:
                raise ValueError(
                    f"n_group={n_group} does not divide "
                    f"n_routed_experts={n_experts}"
                )
            if not (1 <= topk_group <= n_group):
                raise ValueError(
                    f"topk_group={topk_group} outside [1, n_group={n_group}]"
                )
            if topk_group * (n_experts // n_group) < config.get(
                    "num_experts_per_tok", 2):
                raise ValueError(
                    "permitted groups hold fewer experts than "
                    "num_experts_per_tok"
                )
        return cls(
            vocab_size=config.get("vocab_size", 32000),
            hidden_size=config.get("hidden_size", 2048),
            intermediate_size=config.get("intermediate_size", 5632),
            num_layers=config.get("num_hidden_layers", 16),
            num_heads=config.get("num_attention_heads", 16),
            num_kv_heads=config.get(
                "num_key_value_heads", config.get("num_attention_heads", 16)
            ),
            head_dim=config.get("head_dim"),
            rope_theta=config.get("rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            # Qwen2-family checkpoints carry qkv biases but their HF config
            # has no attention_bias key — infer from the architecture name
            attention_bias=config.get("attention_bias", "qwen2" in arch),
            rms_norm_eps=config.get("rms_norm_eps", 1e-5),
            max_position_embeddings=config.get("max_position_embeddings", 4096),
            tie_word_embeddings=config.get("tie_word_embeddings", False),
            num_experts=config.get("num_local_experts", 0)
            or config.get("n_routed_experts", 0)
            or config.get("num_experts", 0)  # Qwen-MoE config key
            or 0,
            num_experts_per_tok=config.get("num_experts_per_tok", 2),
            moe_intermediate_size=config.get("moe_intermediate_size", 0) or 0,
            n_shared_experts=config.get("n_shared_experts", 0) or 0,
            first_k_dense_replace=config.get("first_k_dense_replace", 0) or 0,
            moe_scoring_func=config.get("scoring_func", "softmax"),
            norm_topk_prob=config.get("norm_topk_prob", True),
            routed_scaling_factor=config.get("routed_scaling_factor", 1.0) or 1.0,
            n_group=n_group,
            topk_group=topk_group,
            # Gemma-2 / GPT-OSS (config.json keys; sliding_window exists
            # in other families' configs too, so gate on the architecture)
            model_family=(
                "gemma2" if "gemma2" in arch
                else "gptoss" if "gptoss" in arch
                else ""
            ),
            attn_logit_softcap=config.get("attn_logit_softcapping") or 0.0,
            final_logit_softcap=config.get("final_logit_softcapping") or 0.0,
            query_pre_attn_scalar=config.get("query_pre_attn_scalar", 0) or 0,
            # honored whenever the checkpoint's HF modeling honors it:
            # gemma2 alternates it per layer; mistral/phi3-style configs
            # apply it to every layer; qwen2 ships the key but disables
            # it via use_sliding_window
            sliding_window=(
                (config.get("sliding_window", 0) or 0)
                if ("gemma2" in arch
                    or config.get("use_sliding_window", True))
                else 0
            ),
            # MLA (DeepSeek config.json keys)
            kv_lora_rank=config.get("kv_lora_rank", 0) or 0,
            q_lora_rank=config.get("q_lora_rank", 0) or 0,
            qk_rope_head_dim=config.get("qk_rope_head_dim", 0) or 0,
            qk_nope_head_dim=config.get("qk_nope_head_dim", 0) or 0,
            v_head_dim=config.get("v_head_dim", 0) or 0,
        )

    @classmethod
    def from_model_dir(cls, model_dir: str) -> "ModelConfig":
        """HF snapshot dir (config.json) or a .gguf file."""
        if model_dir.endswith(".gguf"):
            from ..llm.gguf import model_config_from_gguf, read_gguf

            return model_config_from_gguf(read_gguf(model_dir))
        with open(os.path.join(model_dir, "config.json")) as f:
            return cls.from_hf_config(json.load(f))


def default_prefill_buckets(max_len: int) -> List[int]:
    """Powers of two up to max_len — each bucket is one compiled program."""
    buckets = []
    b = 64
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


@dataclasses.dataclass
class EngineConfig:
    model: ModelConfig
    max_batch_size: int = 8          # concurrent decode slots
    max_model_len: int = 4096        # max tokens per sequence (prompt+gen)
    kv_block_size: int = 16
    num_kv_blocks: int = 2048        # HBM budget for the paged cache
    prefill_buckets: Optional[List[int]] = None
    dtype: str = "bfloat16"
    # paged-KV-cache storage dtype: "auto" stores at the engine dtype;
    # "fp8" stores float8_e4m3fn — halves the decode KV stream and
    # doubles cache capacity for ~6% elementwise KV error (the standard
    # serving lever the reference's engines expose as kv_cache_dtype).
    # Unscaled e4m3: post-rope K and V are O(1), well inside its ±448
    # range. GQA families only (the MLA latent is too quantization-
    # sensitive; ModelRunner rejects the combination).
    kv_cache_dtype: str = "auto"
    # mesh axes: pipeline stages x data-parallel replicas x expert-parallel
    # x tensor-parallel. pp > 1 stages the dense trunk over a collective
    # GPipe schedule (parallel/pipeline.py) — reference analog:
    # pipeline_parallel_size = num_nodes (lib/engines/vllm0_7/src/
    # vllm_inc.py:37-38 over Ray); here it is one SPMD program over the
    # mesh's pp axis.
    pp_size: int = 1
    dp_size: int = 1
    ep_size: int = 1
    tp_size: int = 1
    # sequence parallelism for long-context prefill (parallel/sequence.py,
    # docs/long_context.md): sp_size > 1 adds an ``sp`` mesh axis and
    # compiles a sequence-parallel prefill program (``prefill_sp``) that
    # shards ONE oversized prompt's tokens across the axis — ring
    # attention over the chunk + the committed paged prefix — so a 128k
    # prompt prefills across the slice instead of monopolizing one chip.
    # Decode programs ignore the axis (their specs never name it), so an
    # sp engine decodes exactly as before. Llama-family GQA dense trunks
    # only (the ring kernel has no MLA/MoE/sliding-window variant yet).
    sp_size: int = 1
    # the admission class: prompts whose uncached suffix is at least this
    # long route to the sequence-parallel prefill program (local mesh) or,
    # in disagg mode, bias toward the prefill-worker pool whose workers
    # run the same SP chunk ladder. 0 with sp_size > 1 defaults to
    # max_prefill_tokens_per_step (one dense chunk budget); 0 with
    # sp_size == 1 disables the class entirely.
    long_prefill_threshold_tokens: int = 0
    seed: int = 0
    # serve random-init weights when model_dir has no checkpoint (tests,
    # topology dry runs); off by default so a misnamed checkpoint dir
    # fails loudly instead of serving plausible-looking garbage
    allow_random_weights: bool = False
    # scheduler knobs
    max_prefill_tokens_per_step: int = 8192
    # concurrent prompts batched into ONE prefill step (rows padded to a
    # power-of-two ladder; one compiled program per (rows, bucket)).
    # Serial prefill (the round-2 design) queued TTFT linearly under
    # prompt bursts; batching amortizes the weight stream and per-step
    # overhead across rows. 1 restores strictly-serial behavior.
    max_prefill_batch: int = 4
    # decode steps fused into ONE device dispatch (lax.scan inside the
    # compiled program). Each dispatch pays fixed host+launch overhead
    # (scheduler bookkeeping, transfer latency, program launch); at small
    # per-step compute that overhead dominates, and fusing K steps
    # amortizes it K-fold — the TPU-native analog of the multi-step
    # scheduling the reference's engines use. Tokens stream in bursts of
    # K (ITL becomes bursty), so it only engages when no prefill work is
    # waiting, and 1 (default) keeps strict per-token dispatch. Sampling
    # is bit-identical either way (same per-row PRNG fold-in counters).
    multi_step_decode: int = 1
    # dispatch-ahead decode: with depth 2, burst k+1 is dispatched before
    # burst k's sampled tokens are synced to the host (JAX dispatch is
    # async; the carry tokens are already device-resident), so the host's
    # detokenize/stream/finish-check work for burst k overlaps burst
    # k+1's device compute instead of leaving the TPU idle. Finishes
    # (eos/stop/max-token/cancel) are detected one burst late and the
    # over-decoded rows retro-invalidated (tokens truncated, KV blocks
    # rolled back); block headroom for 2*K positions is reserved before
    # every dispatch so the in-flight burst can never OOM. Guided
    # decoding, speculative decoding, and prefill work force the
    # synchronous path per pass. 0/1 = today's strictly-synchronous
    # behavior, 2 = double-buffered (the only pipelined depth).
    decode_pipeline_depth: int = 1
    # device-resident finish detection (the persistent decode loop):
    # "auto" | "on" | "off". When enabled, the fused decode burst carries
    # a per-row ``done`` mask and evaluates EOS / hidden-stop /
    # max-tokens / model-len checks INSIDE the scan — finished rows
    # freeze (no further sampling or KV writes, padded emission) instead
    # of ending the burst, so the scheduler dispatches bursts
    # back-to-back off the device-resident carry and drains completed
    # rows asynchronously, compacting batch membership only at natural
    # barriers (admission, preemption, KV-OOM, drain). The carry also
    # holds speculative state (trailing-token ring), bounded guided
    # grammar state (guided_device_table below), and the stop-string
    # suffix-hash ring (device_stop_strings below), so spec / guided /
    # stop-string / n>1 traffic chains too; the remaining sync-path
    # fallbacks are counted per pass in
    # dynamo_engine_sync_fallback_total{reason}. "auto" engages with
    # decode_pipeline_depth >= 2; "on" requires it.
    device_finish: str = "auto"
    # the fused Pallas sampling epilogue (ops/pallas_epilogue.py): run
    # the whole per-step decode tail — penalties, top-k/top-p/min-p
    # sampling, count commit, and (in the chained burst) the
    # device-finish verdict + stop-suffix rolling hash — as ONE kernel
    # dispatch instead of a string of small [B, V] XLA ops. Sampling is
    # bit-identical to the unfused ladder by construction. "auto"
    # follows the attention route: it engages exactly when the Pallas
    # serving kernels do (warmup probe passes), so the probe/warmup XLA
    # fallback drops it automatically. "on" forces it (CPU tests use
    # DYN_PALLAS_INTERPRET=1); "off" keeps the XLA tail.
    fused_epilogue: str = "auto"
    # guided decoding inside the chain: compile TrieConstraint /
    # in-bound JsonGrammar cursors to a dense device transition table
    # (state x token -> next state) so the per-token mask is computed
    # on device and the grammar cursor advances in the burst carry.
    # Grammars whose reachable state set exceeds the bound keep the
    # host sync path explicitly (fallback reason "guided_table_bound").
    guided_device_table: bool = True
    guided_table_max_states: int = 256
    # stop STRINGS inside the chain: device-approximate detection via a
    # rolling suffix-hash over the burst carry's trailing-token ring
    # against the stop strings' canonical tokenizations
    # (StopConditions.stop_token_seqs); candidate rows freeze on device,
    # the host confirms exactly on drain, and hash-collision false
    # positives resume byte-identically. Off -> stop-string rows keep
    # the per-burst sync pipeline.
    device_stop_strings: bool = True
    # n-gram (prompt-lookup) speculative decoding: propose up to K tokens
    # per decode step by matching the context's trailing n-gram against
    # its own history, then VERIFY all K+1 positions in one forward.
    # Decode is bandwidth-bound (weights stream once per step regardless
    # of S), so accepted tokens are nearly free — the reference's engines
    # ship the same technique (vLLM ngram speculative decoding). Greedy,
    # penalty-free requests only; mixed batches fall back per step.
    spec_ngram_tokens: int = 0   # K proposal tokens (0 = off)
    spec_ngram_match: int = 3    # trailing n-gram length to look up
    # draft-MODEL speculative decoding: a small model proposes K tokens
    # per round (its fused K-step burst = ONE extra dispatch) and the
    # target verifies all K+1 positions in one forward — the
    # draft/verify speculation reference-class engines ship. The draft
    # keeps a mirror paged cache on the SAME block ids as the target
    # (same allocator decisions), so prefix-cache hits, resume, and
    # block registration carry valid draft context for free. Greedy,
    # penalty-free requests only (stream is provably identical either
    # way). Mutually exclusive with ngram speculation; incompatible
    # with the host KV tier (restored blocks would hold stale draft KV).
    spec_draft_model: Optional[str] = None  # HF dir of the draft model
    spec_draft_tokens: int = 0              # K proposals per round (2..16)
    # streamed remote prefill (the disagg prefill worker): the worker
    # always chunks its prefill with the shared bucket ladder +
    # max_prefill_tokens_per_step and streams each chunk's completed KV
    # blocks while the next chunk computes, so remote TTFT approaches
    # max(compute, transfer) instead of compute + transfer. This knob is
    # the transfer plane's frame depth: 2 (default) double-buffers — the
    # next frame's gather/host-pack proceeds while the previous frame's
    # bytes are on the wire — and 1 ships frames strictly serially.
    # Streams are byte-identical at every depth; host memory is bounded
    # at <= depth chunk-sized frames either way.
    disagg_stream_depth: int = 2
    enable_prefix_caching: bool = True
    # host-RAM KV offload tier: evicted HBM blocks are copied out and can be
    # restored on later prefix hits instead of recomputed. 0 disables.
    host_kv_blocks: int = 0
    # cluster KV fabric (kv/fabric.py, docs/kv_fabric.md): cross-worker
    # prefix PULL — when the fabric's ownership view says a peer holds a
    # longer prefix of an incoming prompt than every local tier, the
    # scheduler pulls those committed KV blocks over the transfer plane
    # instead of recomputing them (pull failure/timeout falls back to
    # local recompute, byte-identically). The peer view itself (KV event
    # feed + pull-server descriptors) is wired by the CLI/discovery
    # layer; this flag builds the engine-side machinery.
    prefix_pull: bool = False
    # minimum remote/cold extension (in blocks past the local hit) worth
    # a pull — below this the transfer round trip loses to recompute
    prefix_pull_min_blocks: int = 2
    # per-pull deadline: a dead/stalled source must never hold a request
    # past this before the local-recompute fallback takes over
    prefix_pull_timeout_s: float = 30.0
    # content-addressed cold tier (kv/cold_tier.py): host-tier-evicted
    # blocks spill to checksummed files keyed by sequence hash in this
    # directory, so cold-but-hot-again prefixes (system prompts, RAG
    # documents) survive RAM eviction and ANY worker sharing the
    # directory — including a freshly respawned one — can rehydrate
    # them. Requires host_kv_blocks > 0 (the spill source is host-tier
    # eviction). Both knobs must be set together.
    cold_tier_dir: str = ""
    cold_tier_blocks: int = 0
    # stall watchdog (telemetry/watchdog.py): trip when work is pending
    # but the scheduler loop's heartbeat (or its dispatch counter) has
    # been stale for this long — a wedged Mosaic compile or dead host
    # sync then dumps a flight artifact to DYN_FLIGHT_DIR instead of
    # freezing silently. 0 disables the watchdog. The deadline must
    # comfortably exceed one loop PASS (chunked prefill bounds a pass;
    # a cold late compile is the longest legitimate pass).
    watchdog_stall_s: float = 30.0
    watchdog_interval_s: float = 1.0

    def __post_init__(self):
        if self.prefill_buckets is None:
            self.prefill_buckets = default_prefill_buckets(self.max_model_len)
        self.prefill_buckets = sorted(self.prefill_buckets)
        if self.dp_size > 1:
            if self.max_batch_size % self.dp_size:
                raise ValueError(
                    f"max_batch_size {self.max_batch_size} not divisible "
                    f"by dp_size {self.dp_size} (decode rows shard over dp)"
                )
            # batch rows shard over dp in every compiled program (jit
            # in_shardings P("dp")), so the padded prefill row ladder must
            # stay dp-divisible too — scale it; short batches ride as
            # inert pad rows
            self.PREFILL_ROW_BUCKETS = tuple(
                r * self.dp_size for r in type(self).PREFILL_ROW_BUCKETS
            )
        # clamp into the compiled row ladder: values past the top bucket
        # would admit more rows than the step arrays hold (IndexError in
        # the scheduler), and <= 0 would silently admit nothing
        self.max_prefill_batch = max(
            1, min(self.max_prefill_batch, self.PREFILL_ROW_BUCKETS[-1])
        )
        # a burst must fit comfortably inside one sequence's block budget;
        # 64 already amortizes dispatch overhead past the point of returns
        self.multi_step_decode = max(1, min(self.multi_step_decode, 64))
        # depth > 2 buys nothing: with one burst in flight the host is
        # already fully overlapped, and reconciliation lag grows with
        # every extra stage — clamp instead of failing
        self.decode_pipeline_depth = max(0, min(self.decode_pipeline_depth, 2))
        if self.device_finish not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown device_finish {self.device_finish!r} "
                "(auto | on | off)"
            )
        if self.fused_epilogue not in ("auto", "on", "off"):
            raise ValueError(
                f"unknown fused_epilogue {self.fused_epilogue!r} "
                "(auto | on | off)"
            )
        if self.device_finish == "on" and self.decode_pipeline_depth < 2:
            # the chained dispatch only exists under the dispatch-ahead
            # pipeline; an explicit "on" that silently never engaged
            # would be worse than failing here
            raise ValueError(
                "device_finish='on' requires decode_pipeline_depth >= 2 "
                "(the persistent loop rides the dispatch-ahead pipeline)"
            )
        # (speculation + device_finish used to be mutually exclusive —
        # the chain now runs propose-verify rounds off the same device
        # carry, so spec engines chain too)
        self.guided_table_max_states = max(2, self.guided_table_max_states)
        # one frame in flight is the serial floor; beyond two buys nothing
        # (the wire is busy continuously at 2) and unbounds host buffers
        self.disagg_stream_depth = max(1, min(self.disagg_stream_depth, 2))
        # watchdog: negative means off (same as 0); the sampling interval
        # floors at 50 ms so a mistyped value can't busy-spin the loop
        self.watchdog_stall_s = max(0.0, self.watchdog_stall_s)
        self.watchdog_interval_s = max(0.05, self.watchdog_interval_s)
        self.spec_ngram_tokens = max(0, min(self.spec_ngram_tokens, 16))
        self.spec_ngram_match = max(1, self.spec_ngram_match)
        self.sp_size = max(1, self.sp_size)
        self.long_prefill_threshold_tokens = max(
            0, self.long_prefill_threshold_tokens)
        if self.sp_size > 1:
            if self.prefill_buckets[0] % self.sp_size:
                # every SP chunk pads to a bucket sharded over the axis;
                # the smallest bucket bounds the divisibility requirement
                raise ValueError(
                    f"sp_size {self.sp_size} must divide the smallest "
                    f"prefill bucket {self.prefill_buckets[0]}"
                )
            if self.pp_size > 1:
                raise ValueError(
                    "sp_size > 1 does not compose with pipeline "
                    "parallelism (the SP program assumes an unstaged "
                    "cache)"
                )
            if self.long_prefill_threshold_tokens == 0:
                # default: anything past one dense chunk budget is
                # "long" — it would already take multiple ladder passes
                self.long_prefill_threshold_tokens = (
                    self.max_prefill_tokens_per_step
                    or self.prefill_buckets[-1]
                )
        self.prefix_pull_min_blocks = max(1, self.prefix_pull_min_blocks)
        self.prefix_pull_timeout_s = max(0.1, self.prefix_pull_timeout_s)
        if bool(self.cold_tier_dir) != (self.cold_tier_blocks > 0):
            raise ValueError(
                "cold_tier_dir and cold_tier_blocks must be set together "
                f"(got dir={self.cold_tier_dir!r}, "
                f"blocks={self.cold_tier_blocks})"
            )
        if self.cold_tier_blocks > 0 and self.host_kv_blocks <= 0:
            raise ValueError(
                "the cold tier spills from the host tier: "
                "cold_tier_blocks > 0 requires host_kv_blocks > 0"
            )
        if self.spec_draft_tokens and not self.spec_draft_model:
            raise ValueError(
                "spec_draft_tokens set without spec_draft_model — "
                "speculation would silently stay off"
            )
        if self.spec_draft_model:
            if not 2 <= self.spec_draft_tokens <= 16:
                raise ValueError(
                    "spec_draft_model needs spec_draft_tokens in 2..16 "
                    f"(got {self.spec_draft_tokens}; a 1-token draft "
                    "round never amortizes the extra dispatch)"
                )
            if self.spec_ngram_tokens:
                raise ValueError(
                    "spec_draft_model and spec_ngram_tokens are mutually "
                    "exclusive proposal sources"
                )
            if self.host_kv_blocks:
                raise ValueError(
                    "spec_draft_model is incompatible with the host KV "
                    "tier: restored blocks would carry stale draft KV "
                    "(the draft cache mirrors device block ids only)"
                )

    @property
    def blocks_per_seq(self) -> int:
        return math.ceil(self.max_model_len / self.kv_block_size)

    @property
    def device_finish_enabled(self) -> bool:
        """Resolved device-resident finish detection: explicit on/off,
        auto follows the dispatch-ahead pipeline."""
        if self.device_finish == "on":
            return True
        return (self.device_finish == "auto"
                and self.decode_pipeline_depth >= 2)

    def bucket_for(self, length: int) -> int:
        for b in self.prefill_buckets:
            if length <= b:
                return b
        raise ValueError(f"prompt length {length} exceeds max bucket {self.prefill_buckets[-1]}")

    PREFILL_ROW_BUCKETS = (1, 2, 4, 8)

    def prefill_row_buckets(self) -> List[int]:
        """Row-count ladder for batched prefill: the prefill batch pads to
        the next power of two (one compiled program per (rows, bucket));
        warmup sweeps this ladder."""
        cap = self.prefill_row_bucket(self.max_prefill_batch)
        return [r for r in self.PREFILL_ROW_BUCKETS if r <= cap]

    def prefill_row_bucket(self, n: int) -> int:
        for r in self.PREFILL_ROW_BUCKETS:
            if n <= r:
                return r
        return self.PREFILL_ROW_BUCKETS[-1]

    def sp_prefill_bucket(self) -> int:
        """The ONE chunk length the sequence-parallel prefill program
        compiles at: the largest prefill bucket whose PER-DEVICE token
        share (bucket / sp) stays within the per-step budget — the same
        ITL bound the dense ladder honors, scaled by the axis. A fixed
        bucket (short/final chunks pad into it) keeps ``prefill_sp`` at
        exactly one compiled shape."""
        budget = self.max_prefill_tokens_per_step
        if not budget:
            return self.prefill_buckets[-1]
        allowed = [
            b for b in self.prefill_buckets
            if b <= self.sp_size * budget and b % self.sp_size == 0
        ]
        return allowed[-1] if allowed else self.prefill_buckets[0]

    def kv_width_buckets(self) -> List[int]:
        """The decode block-table width ladder: powers of two from 8 up to
        the full per-seq width (always included). One compiled decode
        program exists per bucket; ModelRunner.warmup sweeps the ladder."""
        widths = []
        w = 8
        while w < self.blocks_per_seq:
            widths.append(w)
            w *= 2
        widths.append(self.blocks_per_seq)
        return widths

    def kv_width_bucket(self, nblocks: int) -> int:
        """Block-table width for a decode step covering ``nblocks`` live
        blocks. Attention cost on the gather/page-walk side scales with
        table width, so short contexts must not pay max_model_len's
        width."""
        for w in self.kv_width_buckets():
            if nblocks <= w:
                return w
        return self.blocks_per_seq
