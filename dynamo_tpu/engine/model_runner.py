"""Jitted prefill/decode step programs + mesh sharding.

Two compiled programs per prefill bucket plus one decode program, all with
static shapes (SURVEY.md §7 hard-part #1: dynamic batch membership without
recompiles). The KV cache is donated through every call so XLA updates it
in place in HBM.

Sharding (TPU-first): mesh axes ("dp", "tp"). Attention heads, KV heads,
MLP intermediate, and the vocab dim of lm_head shard over "tp" (Megatron
layout — XLA inserts the all-reduces after wo / w_down); the batch dim of
activations shards over "dp". Single-device collapses to a trivial mesh.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import models
from ..models import llama
from .config import EngineConfig
from .sampling import SamplingParams, logprobs_for, sample

logger = logging.getLogger(__name__)


def build_mesh(dp: int, tp: int, devices=None, ep: int = 1) -> Mesh:
    """(dp, ep, tp) mesh; tp innermost so its collectives ride fastest ICI.
    ep=1 keeps the axis present (specs may name it) but trivial."""
    devices = devices if devices is not None else jax.devices()
    n = dp * ep * tp
    if n > len(devices):
        raise ValueError(f"mesh {dp}x{ep}x{tp} needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, ep, tp)
    return Mesh(arr, ("dp", "ep", "tp"))


def param_specs(params) -> Dict:
    """Llama param specs (kept for back-compat; models now own their specs)."""
    return llama.param_specs(params)


CACHE_SPEC = P(None, None, None, "tp", None)  # [L, N, bs, KVH, D] — KV heads over tp


class ModelRunner:
    """Owns params + cache on device and the compiled step programs."""

    def __init__(
        self,
        config: EngineConfig,
        params=None,
        mesh: Optional[Mesh] = None,
        model_dir: Optional[str] = None,
    ):
        self.config = config
        cfg = config.model
        self.arch = models.resolve(cfg)
        self.dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
        self.mesh = mesh or build_mesh(
            config.dp_size, config.tp_size, ep=config.ep_size
        )

        if cfg.kv_lora_rank == 0 and cfg.num_kv_heads % config.tp_size != 0:
            # (MLA caches a per-token latent, no KV head dim to shard)
            raise ValueError(
                f"num_kv_heads {cfg.num_kv_heads} not divisible by tp {config.tp_size}"
            )
        if cfg.num_heads % config.tp_size != 0:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by tp {config.tp_size}"
            )
        if cfg.num_experts and cfg.num_experts % config.ep_size != 0:
            raise ValueError(
                f"num_experts {cfg.num_experts} not divisible by ep {config.ep_size}"
            )

        if params is None:
            if model_dir is not None:
                if self.arch is llama:
                    from ..models.loader import has_checkpoint, load_llama_params

                    if has_checkpoint(model_dir):
                        params = load_llama_params(model_dir, cfg, self.dtype)
                    else:
                        logger.warning("no checkpoint in %s — random init", model_dir)
                else:
                    logger.warning(
                        "no weight loader for %s yet — IGNORING checkpoint %s, "
                        "serving random init", self.arch.__name__, model_dir,
                    )
            if params is None:
                params = self.arch.init_params(
                    cfg, jax.random.PRNGKey(config.seed), self.dtype
                )

        pspecs = self.arch.param_specs(params)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)), params, pspecs
        )
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, P),
        )

        cache = self.arch.init_kv_cache(
            cfg, config.num_kv_blocks, config.kv_block_size, self.dtype
        )
        cache_spec = getattr(self.arch, "CACHE_SPEC", CACHE_SPEC)
        self.cache_sharding = NamedSharding(self.mesh, cache_spec)
        self.kv_cache = tuple(jax.device_put(c, self.cache_sharding) for c in cache)

        self._step_compiled = {}
        self._build_step()
        self._build_block_ops()

    # ---------- the unified step program ----------

    def _build_step(self):
        cfg = self.config.model
        mesh = self.mesh
        arch = self.arch
        batch_spec = NamedSharding(mesh, P("dp"))
        batch2_spec = NamedSharding(mesh, P("dp", None))
        repl = NamedSharding(mesh, P())

        def step(params, k_cache, v_cache, tokens, positions, block_tables,
                 slot_mapping, context_lens, last_idx, temperature, top_k, top_p, key):
            logits, (k_cache, v_cache) = arch.forward(
                params, cfg, tokens, positions, (k_cache, v_cache),
                block_tables, slot_mapping, context_lens,
                mesh=mesh,
            )
            b = tokens.shape[0]
            last_logits = logits[jnp.arange(b), last_idx]  # [B, V]
            samp = SamplingParams(temperature=temperature, top_k=top_k, top_p=top_p)
            next_tokens = sample(last_logits, samp, key)
            lps = logprobs_for(last_logits, next_tokens)
            return next_tokens, lps, k_cache, v_cache

        self._step = jax.jit(
            step,
            donate_argnums=(1, 2),
            in_shardings=(
                self.param_shardings,        # params
                self.cache_sharding,         # k
                self.cache_sharding,         # v
                batch2_spec,                 # tokens [B, S]
                batch2_spec,                 # positions
                batch2_spec,                 # block_tables
                batch2_spec,                 # slot_mapping
                batch_spec,                  # context_lens
                batch_spec,                  # last_idx
                batch_spec, batch_spec, batch_spec,  # sampling params
                repl,                        # key
            ),
            out_shardings=(batch_spec, batch_spec, self.cache_sharding, self.cache_sharding),
        )

    def step(
        self,
        tokens: np.ndarray,        # [B, S]
        positions: np.ndarray,     # [B, S]
        block_tables: np.ndarray,  # [B, W]
        slot_mapping: np.ndarray,  # [B, S]
        context_lens: np.ndarray,  # [B]
        last_idx: np.ndarray,      # [B] index of the position to sample from
        temperature: np.ndarray,
        top_k: np.ndarray,
        top_p: np.ndarray,
        key: jax.Array,
    ) -> Tuple[jax.Array, jax.Array]:
        """Run one compiled step; returns (next_tokens, logprobs) device arrays."""
        next_tokens, lps, k, v = self._step(
            self.params, self.kv_cache[0], self.kv_cache[1],
            jnp.asarray(tokens, jnp.int32), jnp.asarray(positions, jnp.int32),
            jnp.asarray(block_tables, jnp.int32), jnp.asarray(slot_mapping, jnp.int32),
            jnp.asarray(context_lens, jnp.int32), jnp.asarray(last_idx, jnp.int32),
            jnp.asarray(temperature, jnp.float32), jnp.asarray(top_k, jnp.int32),
            jnp.asarray(top_p, jnp.float32), key,
        )
        self.kv_cache = (k, v)
        return next_tokens, lps

    # ---------- paged-block gather / scatter ----------
    #
    # The KV data-movement primitive behind disaggregated prefill→decode
    # transfer and host-memory offload — the TPU-native role of the
    # reference's CUDA block-copy kernel + NIXL RDMA path (reference:
    # lib/llm/src/kernels/block_copy.cu:40-758, lib/llm/src/kv/layer.rs
    # CopyStream). XLA compiles the gather/scatter over the [L, N, bs, H, D]
    # cache; block counts are bucketed so each bucket compiles once.

    BLOCK_OP_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

    def _build_block_ops(self):
        repl = NamedSharding(self.mesh, P())

        def gather(k_cache, v_cache, ids):
            return k_cache[:, ids], v_cache[:, ids]

        self._gather_jit = jax.jit(
            gather,
            in_shardings=(self.cache_sharding, self.cache_sharding, repl),
            out_shardings=(repl, repl),
        )

        def scatter(k_cache, v_cache, ids, k_blocks, v_blocks):
            return (
                k_cache.at[:, ids].set(k_blocks.astype(k_cache.dtype)),
                v_cache.at[:, ids].set(v_blocks.astype(v_cache.dtype)),
            )

        self._scatter_jit = jax.jit(
            scatter,
            donate_argnums=(0, 1),
            in_shardings=(self.cache_sharding, self.cache_sharding, repl, repl, repl),
            out_shardings=(self.cache_sharding, self.cache_sharding),
        )

    def _bucket_ids(self, n: int) -> int:
        for b in self.BLOCK_OP_BUCKETS:
            if n <= b:
                return b
        return self.BLOCK_OP_BUCKETS[-1]

    def gather_blocks(self, block_ids) -> Tuple[np.ndarray, np.ndarray]:
        """Read KV blocks out of HBM → host arrays [L, n, bs, KVH, D] ×2."""
        ids = list(block_ids)
        k_parts, v_parts = [], []
        i = 0
        while i < len(ids):
            chunk = ids[i : i + self.BLOCK_OP_BUCKETS[-1]]
            bucket = self._bucket_ids(len(chunk))
            padded = chunk + [chunk[-1]] * (bucket - len(chunk))
            k, v = self._gather_jit(
                self.kv_cache[0], self.kv_cache[1], jnp.asarray(padded, jnp.int32)
            )
            k_parts.append(np.asarray(jax.device_get(k))[:, : len(chunk)])
            v_parts.append(np.asarray(jax.device_get(v))[:, : len(chunk)])
            i += len(chunk)
        if len(k_parts) == 1:
            return k_parts[0], v_parts[0]
        return np.concatenate(k_parts, axis=1), np.concatenate(v_parts, axis=1)

    def scatter_blocks(self, block_ids, k_blocks, v_blocks) -> None:
        """Write KV block data [L, n, bs, KVH, D] into HBM cache slots.

        Accepts numpy OR already-device-resident jax arrays (callers that
        must not block the event loop stage with ``jax.device_put`` first).
        """
        ids = list(block_ids)
        assert k_blocks.shape[1] == len(ids), (k_blocks.shape, len(ids))
        kb_all = jnp.asarray(k_blocks)
        vb_all = jnp.asarray(v_blocks)
        i = 0
        while i < len(ids):
            chunk = ids[i : i + self.BLOCK_OP_BUCKETS[-1]]
            bucket = self._bucket_ids(len(chunk))
            pad = bucket - len(chunk)
            padded_ids = chunk + [chunk[-1]] * pad
            kb = kb_all[:, i : i + len(chunk)]
            vb = vb_all[:, i : i + len(chunk)]
            if pad:
                # duplicate the last block's data for the repeated pad ids —
                # identical values land on the same slot, so order is benign
                kb = jnp.concatenate([kb, jnp.repeat(kb[:, -1:], pad, axis=1)], axis=1)
                vb = jnp.concatenate([vb, jnp.repeat(vb[:, -1:], pad, axis=1)], axis=1)
            k, v = self._scatter_jit(
                self.kv_cache[0], self.kv_cache[1],
                jnp.asarray(padded_ids, jnp.int32), kb, vb,
            )
            self.kv_cache = (k, v)
            i += len(chunk)

    def warmup(self, decode_batch: Optional[int] = None) -> None:
        """Compile the decode-shape program up front."""
        b = decode_batch or self.config.max_batch_size
        w = self.config.blocks_per_seq
        zeros2 = np.zeros((b, 1), np.int32)
        self.step(
            zeros2, zeros2, np.zeros((b, w), np.int32), np.full((b, 1), -1, np.int32),
            np.ones(b, np.int32), np.zeros(b, np.int32),
            np.zeros(b, np.float32), np.zeros(b, np.int32), np.ones(b, np.float32),
            jax.random.PRNGKey(0),
        )
