"""Jitted prefill/decode step programs + mesh sharding.

Two compiled programs per prefill bucket plus one decode program, all with
static shapes (SURVEY.md §7 hard-part #1: dynamic batch membership without
recompiles). The KV cache is donated through every call so XLA updates it
in place in HBM.

Sharding (TPU-first): mesh axes ("dp", "tp"). Attention heads, KV heads,
MLP intermediate, and the vocab dim of lm_head shard over "tp" (Megatron
layout — XLA inserts the all-reduces after wo / w_down); the batch dim of
activations shards over "dp". Single-device collapses to a trivial mesh.
"""

from __future__ import annotations

import functools
import logging
import os
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import models
from ..models import llama, quant
from ..ops.attention import _pad_minor
from ..telemetry.flight import CompileTracker
from .config import EngineConfig
from .sampling import SamplingParams, sample, top_logprobs_for

logger = logging.getLogger(__name__)


def build_mesh(dp: int, tp: int, devices=None, ep: int = 1, pp: int = 1,
               sp: int = 1) -> Mesh:
    """(pp, dp, sp, ep, tp) mesh; tp innermost so its collectives ride
    the fastest ICI, pp outermost so stage hops cross the slowest links
    (stages communicate once per microbatch tick, tp all-reduces twice
    per layer), sp between dp and ep — the ring rotation's per-hop
    payload is one K/V shard, heavier than an ep dispatch but far
    lighter than tp's twice-per-layer all-reduces. ep=1/pp=1/sp=1 keep
    those axes present (specs may name them) but trivial.

    Device pick: LOCAL devices when they suffice — in a multi-process
    world (disagg workers sharing a jax.distributed group for the ICI
    transfer plane) each engine runs its own independent program and must
    not claim the peer's devices. A mesh larger than the local count is
    the single-engine multi-host case and takes the global list.
    """
    n = pp * dp * sp * ep * tp
    if devices is None:
        local = jax.local_devices()
        devices = local if n <= len(local) else jax.devices()
    if n > len(devices):
        raise ValueError(
            f"mesh {pp}x{dp}x{sp}x{ep}x{tp} needs {n} devices, "
            f"have {len(devices)}"
        )
    arr = np.asarray(devices[:n]).reshape(pp, dp, sp, ep, tp)
    return Mesh(arr, ("pp", "dp", "sp", "ep", "tp"))


def param_specs(params) -> Dict:
    """Llama param specs (kept for back-compat; models now own their specs)."""
    return llama.param_specs(params)


CACHE_SPEC = P(None, None, None, "tp", None)  # [L, N, bs, KVH, D] — KV heads over tp


def _sample_and_logprobs(cfg, last_logits, samp, counts, seen, bias,
                         sample_slots, commit, want_top, extra_bias=None,
                         fused=False, unique_slots=True, finish=None,
                         max_model_len=0):
    """The per-token tail shared by the single step and every scan
    iteration of the fused burst: penalty-aware sampling, the sampled
    token's logprob, gated top-K alternatives, and the committed-count
    update. One implementation ⇒ the burst's bit-identical-stream
    guarantee can't drift from the single-step program.

    ``extra_bias`` is an additive [B, V] term computed in-program (the
    chained burst's device-guided mask); the sync path expresses the
    same mask through the persistent ``bias`` buffer instead, so adding
    it here keeps the two paths' logits — and logprobs — bit-equal.

    ``fused=True`` routes the whole tail through the single-dispatch
    Pallas epilogue (ops/pallas_epilogue.py) — bit-identical by
    construction, gated by the ``epilogue`` compile probe. With
    ``finish`` (the chained burst's per-row carry tuple) the kernel also
    returns the step's (hard, cand, ring_new) finish verdicts, appended
    to the return. ``unique_slots=False`` marks call sites whose pad
    rows may share a live row's sample slot (the batched prefill step):
    the count commit then stays a scatter-add outside the kernel."""
    from .sampling import top_k_width

    b = last_logits.shape[0]
    if fused:
        from ..ops.pallas_epilogue import fused_sampling_epilogue
        from .sampling import _row_keys

        v = last_logits.shape[1]
        row_keys = _row_keys(samp)
        gum = jax.vmap(
            lambda kk: jax.random.gumbel(kk, (v,), jnp.float32)
        )(row_keys)
        scalars = (samp.temperature, samp.top_k, samp.top_p, samp.min_p,
                   samp.presence_penalty, samp.frequency_penalty,
                   samp.repetition_penalty)
        outs = fused_sampling_epilogue(
            last_logits, gum, scalars, counts, seen, bias, sample_slots,
            commit, extra_bias=extra_bias, finish=finish,
            max_model_len=max_model_len, alias_counts=unique_slots,
            interpret=bool(os.environ.get("DYN_PALLAS_INTERPRET")),
        )
        next_tokens, lps, counts = outs[:3]
        kw = top_k_width(cfg.vocab_size)

        def _top(_):
            row_bias = bias[sample_slots]
            if extra_bias is not None:
                row_bias = row_bias + extra_bias
            logp = jax.nn.log_softmax(
                (last_logits + row_bias).astype(jnp.float32), axis=-1
            )
            return top_logprobs_for(last_logits, logp)

        top_vals, top_ids = jax.lax.cond(
            want_top,
            _top,
            lambda _: (jnp.zeros((b, kw), jnp.float32),
                       jnp.zeros((b, kw), jnp.int32)),
            0,
        )
        return (next_tokens, lps, top_vals, top_ids, counts) + tuple(
            outs[3:]
        )
    assert finish is None, "finish fusion requires fused=True"
    row_counts = counts[sample_slots]
    row_seen = seen[sample_slots]
    row_bias = bias[sample_slots]
    if extra_bias is not None:
        row_bias = row_bias + extra_bias
    next_tokens = sample(last_logits, samp, row_counts, row_seen,
                         bias=row_bias)
    logp = jax.nn.log_softmax(
        (last_logits + row_bias).astype(jnp.float32), axis=-1
    )
    lps = jnp.take_along_axis(logp, next_tokens[:, None], axis=-1)[:, 0]
    # top-K alternatives only when some active request asked (OpenAI
    # top_logprobs): the [B, V] top_k sort is fixed hot-path cost
    # otherwise. lax.cond keeps one compiled program either way.
    kw = top_k_width(cfg.vocab_size)
    top_vals, top_ids = jax.lax.cond(
        want_top,
        lambda lp_: top_logprobs_for(last_logits, lp_),
        lambda lp_: (jnp.zeros((b, kw), jnp.float32),
                     jnp.zeros((b, kw), jnp.int32)),
        logp,
    )
    # count the sampled token as generated for its slot — but only for
    # rows whose sample the scheduler will keep (``commit``)
    counts = counts.at[sample_slots, next_tokens].add(
        commit.astype(jnp.int32)
    )
    return next_tokens, lps, top_vals, top_ids, counts


def _ngram_props(ring: jax.Array, match: int, k: int) -> jax.Array:
    """In-program prompt-lookup proposal from the carry's trailing-token
    ring: find the latest earlier occurrence of the trailing ``match``-
    gram whose ``k``-token continuation is fully inside the ring and
    return it ([B, k], -1 where nothing matches). The device analog of
    scheduler.ngram_propose bounded to the ring window — proposals only
    affect acceptance length, never stream content (the verify emits the
    target's own greedy tokens), so the narrower window is free."""
    b, w = ring.shape
    tail = ring[:, w - match:]                       # [B, m]
    n_starts = w - match                             # excludes the tail itself
    s_idx = jnp.arange(n_starts)
    win_idx = s_idx[:, None] + jnp.arange(match)[None, :]   # [S0, m]
    wins = ring[:, win_idx]                          # [B, S0, m]
    hit = (wins == tail[:, None, :]).all(-1) & (wins >= 0).all(-1)
    full = (s_idx + match + k) <= w                  # continuation in-ring
    cand = hit & full[None, :]
    s_best = jnp.max(jnp.where(cand, s_idx[None, :], -1), axis=1)  # latest
    has = s_best >= 0
    cont_idx = jnp.clip(s_best, 0)[:, None] + match + jnp.arange(k)[None, :]
    props = jnp.take_along_axis(ring, cont_idx, axis=1)
    return jnp.where(has[:, None] & (props >= 0), props, -1)


class ModelRunner:
    """Owns params + cache on device and the compiled step programs."""

    def __init__(
        self,
        config: EngineConfig,
        params=None,
        mesh: Optional[Mesh] = None,
        model_dir: Optional[str] = None,
    ):
        self.config = config
        cfg = config.model
        self.arch = models.resolve(cfg)
        self.dtype = jnp.bfloat16 if config.dtype == "bfloat16" else jnp.float32
        if config.kv_cache_dtype not in ("auto", "fp8"):
            raise ValueError(
                f"unknown kv_cache_dtype {config.kv_cache_dtype!r} "
                "(auto | fp8)"
            )
        # fp8 KV covers MLA too: the "latent too sensitive" intuition
        # did not survive measurement — teacher-forced e4m3 round-trip
        # noise on the full latent+rope cache matches the GQA fp8 path
        # (rel logit err 0.043 vs 0.042, argmax flip 0.10 vs 0.10;
        # examples/llm/benchmarks/results/fp8_mla_accuracy.json), and
        # quantizing only the rope half halves the noise again if a
        # future accuracy budget wants it. Kernel side: the MLA decode
        # kernel upcasts after the DMA (its own Mosaic specialization,
        # probed as "mla_decode_fp8").
        self.kv_dtype = (
            jnp.float8_e4m3fn if config.kv_cache_dtype == "fp8"
            else self.dtype
        )
        self.mesh = mesh or build_mesh(
            config.dp_size, config.tp_size, ep=config.ep_size,
            pp=config.pp_size, sp=config.sp_size,
        )
        # mixed dense+MoE MLA trunk under pp: the dense prefix stays
        # replicated (params, cache, and compute) while the MoE trunk
        # stages — parallel/pipeline.py's has_prefix path
        self._pp_prefix_layers = (
            cfg.first_k_dense_replace
            if (config.pp_size > 1 and cfg.kv_lora_rank > 0
                and cfg.num_experts > 0)
            else 0
        )
        if config.pp_size > 1:
            from ..models import deepseek as _deepseek
            from ..models import gemma2 as _gemma2
            from ..models import gptoss as _gptoss
            from ..models import mixtral as _mixtral

            if self.arch not in (llama, _mixtral, _gemma2, _gptoss,
                                 _deepseek):
                raise NotImplementedError(
                    "pipeline parallelism stages llama-family dense, "
                    "mixtral MoE, gemma2, gptoss, and deepseek (MLA)"
                )
            if self.arch is _deepseek:
                if config.tp_size > 1:
                    raise NotImplementedError(
                        "MLA over pp composes with dp/ep, not tp: the "
                        "compressed latent cache has a single head, so "
                        "there is no head axis for the manual-tp stage "
                        "to shard (MLA tp runs on the GSPMD non-pp path)"
                    )
            if self.arch is _gptoss and config.tp_size > 1 and (
                cfg.intermediate_size % config.tp_size
            ):
                # the interleaved gate/up stacks shard the 2I columns in
                # contiguous chunks; whole gate/up pairs (and their
                # matching w_down rows) stay together only when the
                # expert width divides by tp
                raise ValueError(
                    f"gptoss intermediate_size {cfg.intermediate_size} "
                    f"not divisible by tp {config.tp_size}"
                )
            # only the STAGED trunk must tile into stages — a mixed MLA
            # trunk's dense prefix is replicated, not staged (real V3:
            # 61 layers = 3 dense + 58 staged, pp2-able)
            staged_layers = cfg.num_layers - self._pp_prefix_layers
            if staged_layers % config.pp_size:
                raise ValueError(
                    f"{staged_layers} staged layers not divisible by "
                    f"pp {config.pp_size}"
                )

        if cfg.kv_lora_rank == 0 and cfg.num_kv_heads % config.tp_size != 0:
            # (MLA caches a per-token latent, no KV head dim to shard)
            raise ValueError(
                f"num_kv_heads {cfg.num_kv_heads} not divisible by tp {config.tp_size}"
            )
        if cfg.num_heads % config.tp_size != 0:
            raise ValueError(
                f"num_heads {cfg.num_heads} not divisible by tp {config.tp_size}"
            )
        if cfg.num_experts and cfg.num_experts % config.ep_size != 0:
            raise ValueError(
                f"num_experts {cfg.num_experts} not divisible by ep {config.ep_size}"
            )
        # config-only quantization checks, BEFORE any checkpoint I/O: a
        # 70B load must not stream for minutes just to hit a config error
        if cfg.quantization and cfg.quantization != "int8":
            raise ValueError(
                f"unknown quantization {cfg.quantization!r} (only int8)"
            )

        if params is None:
            if model_dir is not None:
                from ..models.loader import has_checkpoint, load_checkpoint_params

                if has_checkpoint(model_dir):
                    # raises for architectures without a loader — never
                    # silently serve random weights against a checkpoint
                    params = load_checkpoint_params(
                        model_dir, cfg, self.arch, self.dtype
                    )
                elif config.allow_random_weights:
                    logger.warning("no checkpoint in %s — random init", model_dir)
                else:
                    raise FileNotFoundError(
                        f"no *.safetensors under {model_dir}; the engine will "
                        "not silently serve random weights — provide a "
                        "safetensors checkpoint or set allow_random_weights"
                    )
            if params is None:
                params = self.arch.init_params(
                    cfg, jax.random.PRNGKey(config.seed), self.dtype
                )

        if cfg.quantization:
            params = quant.quantize_params(params)

        if config.pp_size > 1:
            # stage the stacked layers/cache for the collective GPipe
            # schedule: [L, ...] → [P, L/P, ...] sharded on the stage axis
            from ..parallel import pipeline as pp_mod

            params = pp_mod.stage_params(params, config.pp_size)
            # pp_mod.param_specs mirrors QuantizedWeight leaves itself (the
            # same tree feeds pipeline_forward's shard_map in_specs); the
            # family's own specs carry ep for MoE expert stacks
            pspecs = pp_mod.param_specs(
                params, tp=config.tp_size > 1, arch=self.arch
            )
            cache_spec = (
                pp_mod.CACHE_SPEC_TP if config.tp_size > 1
                else pp_mod.CACHE_SPEC
            )
            if self._pp_prefix_layers:
                # replicated prefix slab + staged trunk slab per side
                cache_spec = {"pre": P(), "stg": cache_spec}
        else:
            pspecs = self.arch.param_specs(params)
            if cfg.quantization:
                pspecs = quant.mirror_specs(params, pspecs)
            cache_spec = getattr(self.arch, "CACHE_SPEC", CACHE_SPEC)
        self.params = jax.tree.map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)), params, pspecs
        )
        self.param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), pspecs,
            is_leaf=lambda x: isinstance(x, P),
        )

        self.cache_sharding = jax.tree.map(
            lambda sp: NamedSharding(self.mesh, sp), cache_spec,
            is_leaf=lambda x: isinstance(x, P),
        )
        self.state_sharding = NamedSharding(self.mesh, P("dp", None))
        self._reinit_device_state()

        # XLA compile observability: every compiled-program dispatch site
        # below runs through compiles.track(program, shape-bucket key) —
        # the first dispatch of a new key is the compile, and a compile
        # after mark_serving_started() is a "late" compile (the
        # recompile-storm signal; see telemetry/flight.py). The scheduler
        # / prefill worker attach compiles.registry into the engine's
        # scrape and flip the serving flag when they start.
        self.compiles = CompileTracker()
        # attention-route observability: the dispatch seams in
        # ops/attention.py / parallel/sequence.py record which kernel
        # served each trace; the tracked dispatch supplies the program
        # label, and the singleton counter renders in this runner's
        # compile registry (attached to the engine scrape)
        from ..ops import attention as _attn_ops

        self.compiles.dispatch_cm = _attn_ops.route_program
        if (_attn_ops.ATTENTION_ROUTE_COUNTER.name
                not in self.compiles.registry.names()):
            self.compiles.registry.register(
                _attn_ops.ATTENTION_ROUTE_COUNTER)

        # live device-time + roofline accounting (telemetry/device_time.py):
        # the byte model mirrors bench.py's — per decode step the device
        # streams every param leaf once plus each live row's KV context.
        # kv_bytes_per_token is EXACT for any cache layout (GQA, MLA
        # latent, fp8, pp-staged): total cache bytes over total token
        # capacity. The scheduler feeds observations at its existing
        # reconciliation seams and attaches device_time.registry.
        from ..telemetry.device_time import DeviceTimeTracker

        def _leaf_bytes(tree) -> float:
            return float(sum(
                x.size * x.dtype.itemsize for x in jax.tree.leaves(tree)
                if hasattr(x, "size") and hasattr(x, "dtype")
            ))

        self.param_bytes = _leaf_bytes(self.params)
        self.kv_bytes_per_token = _leaf_bytes(self.kv_cache) / max(
            1, config.num_kv_blocks * config.kv_block_size
        )
        self.device_time = DeviceTimeTracker(
            param_bytes=self.param_bytes,
            kv_bytes_per_token=self.kv_bytes_per_token,
        )

        self._build_step()
        self._build_burst()
        self._build_spec_burst()
        self._build_sp_prefill()
        self._build_block_ops()
        self._build_sample_row()
        # batched cacheless embedding programs, compiled per (rows,
        # bucket) on first use (the /v1/embeddings workload)
        self._embed_progs: Dict[Tuple[int, int], Any] = {}

    # ---------- the unified step program ----------

    def _make_forward(self):
        """(trunk, head) closures both compiled programs trace: the trunk
        returns pre-final-norm hidden states, the head applies final norm
        + lm head (+ per-family logit tail) to any [..., D] slice. The
        split lets the step run the head on ONLY the sampled positions —
        the full-S [B, S, V] head is the dominant prefill matmul and pure
        waste for every position nobody reads."""
        cfg = self.config.model
        mesh = self.mesh
        arch = self.arch
        if self.config.pp_size > 1:
            from ..parallel.pipeline import pipeline_forward

            def forward(params, cache, tokens, positions, bt, slots, ctx):
                return pipeline_forward(
                    params, cfg, tokens, positions, cache, bt, slots, ctx,
                    mesh, return_hidden=True, arch=arch,
                )
        else:
            def forward(params, cache, tokens, positions, bt, slots, ctx):
                return arch.forward(
                    params, cfg, tokens, positions, cache, bt, slots, ctx,
                    mesh=mesh, return_hidden=True,
                )

        def head(hidden, params):
            return arch.logits_from_hidden(hidden, params, cfg)

        return forward, head

    def _fused_epilogue_enabled(self) -> bool:
        """Resolve config.fused_epilogue at program-BUILD time: "auto"
        follows the attention route (Pallas serving kernels proven by
        the warmup probe ⇒ the epilogue kernel is proven by the same
        probe pass), so the existing probe/warmup fallback — which
        flips ``attention_impl`` to "xla" and rebuilds the programs —
        drops the fused tail with no extra rebuild plumbing."""
        mode = self.config.fused_epilogue
        if mode == "off":
            return False
        if mode == "on":
            return True
        from ..ops.attention import resolve_attention_impl

        return resolve_attention_impl(
            self.config.model.attention_impl) == "pallas"

    def _build_step(self):
        cfg = self.config.model
        mesh = self.mesh
        fused = self._fused_epilogue_enabled()
        batch_spec = NamedSharding(mesh, P("dp"))
        batch2_spec = NamedSharding(mesh, P("dp", None))
        repl = NamedSharding(mesh, P())
        forward, head = self._make_forward()

        def step(params, k_cache, v_cache, counts, seen, bias, tokens,
                 positions, block_tables, slot_mapping, context_lens,
                 last_idx, samp, sample_slots, commit, want_top,
                 targets, want_prompt, want_greedy):
            hidden, (k_cache, v_cache) = forward(
                params, (k_cache, v_cache), tokens, positions,
                block_tables, slot_mapping, context_lens,
            )
            b = tokens.shape[0]
            # the full-S [B, S, V] head exists ONLY inside this gated
            # branch — it serves two consumers that need every position:
            # prompt logprobs (OutputOptions.prompt_logprobs, reference:
            # lib/llm/src/protocols/common.rs:320-341) and the ngram
            # speculative verify's per-position argmax. Everything else
            # samples from the last_idx slice below, so ordinary prefill
            # never pays vocab-width compute for positions nobody reads.
            want_full = jnp.logical_or(want_prompt, want_greedy)

            def full_head(h):
                lg = head(h, params)                      # [B, S, V]
                # the f32 log_softmax + gather serves prompt_logprobs
                # only — a speculative verify (want_greedy) needs just
                # the argmax, so keep the two consumers' costs separate
                plp = jax.lax.cond(
                    want_prompt,
                    lambda l: jnp.take_along_axis(
                        jax.nn.log_softmax(l.astype(jnp.float32), axis=-1),
                        targets[..., None], axis=-1,
                    )[..., 0],
                    lambda l: jnp.zeros(l.shape[:2], jnp.float32),
                    lg,
                )
                ga = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return plp, ga

            prompt_lps, greedy_all = jax.lax.cond(
                want_full,
                full_head,
                lambda h: (jnp.zeros(h.shape[:2], jnp.float32),
                           jnp.zeros(h.shape[:2], jnp.int32)),
                hidden,
            )
            last_logits = head(
                hidden[jnp.arange(b), last_idx], params
            )  # [B, V]
            # pad rows of a partial batch default to sample slot 0 and
            # may alias a live row's slot — the fused kernel keeps its
            # commit outside (unique_slots=False)
            next_tokens, lps, top_vals, top_ids, counts = _sample_and_logprobs(
                cfg, last_logits, samp, counts, seen, bias, sample_slots,
                commit, want_top, fused=fused, unique_slots=False,
            )
            return (next_tokens, lps, top_vals, top_ids, prompt_lps,
                    greedy_all, k_cache, v_cache, counts, seen, bias)

        samp_spec = SamplingParams(
            temperature=batch_spec, top_k=batch_spec, top_p=batch_spec,
            min_p=batch_spec, presence_penalty=batch_spec,
            frequency_penalty=batch_spec, repetition_penalty=batch_spec,
            keys=batch2_spec, counters=batch_spec,
        )
        self._step = jax.jit(
            step,
            donate_argnums=(1, 2, 3, 4, 5),
            in_shardings=(
                self.param_shardings,        # params
                self.cache_sharding,         # k
                self.cache_sharding,         # v
                self.state_sharding,         # counts
                self.state_sharding,         # seen
                self.state_sharding,         # bias
                batch2_spec,                 # tokens [B, S]
                batch2_spec,                 # positions
                batch2_spec,                 # block_tables
                batch2_spec,                 # slot_mapping
                batch_spec,                  # context_lens
                batch_spec,                  # last_idx
                samp_spec,                   # SamplingParams pytree
                batch_spec,                  # sample_slots
                batch_spec,                  # commit
                repl,                        # want_top scalar
                batch2_spec,                 # targets [B, S]
                repl,                        # want_prompt scalar
                repl,                        # want_greedy scalar
            ),
            out_shardings=(batch_spec, batch_spec, batch2_spec, batch2_spec,
                           batch2_spec, batch2_spec,
                           self.cache_sharding, self.cache_sharding,
                           self.state_sharding, self.state_sharding,
                           self.state_sharding),
        )

    def _build_burst(self):
        """K fused decode steps per dispatch (config.multi_step_decode).

        A ``lax.scan`` chains K single-token decode steps inside ONE
        compiled program: each iteration feeds the sampled token back as
        the next input and derives its KV slot from the block table on
        device, so the host pays scheduler bookkeeping + launch latency
        once per K tokens instead of per token. Sampling math and PRNG
        fold-in (base key + ``counters + step``) are identical to the
        single-step program — the token stream is bit-equal for any K.
        The reference reaches the same amortization through its engines'
        multi-step scheduling; this is the one-SPMD-program version.
        """
        K = self.config.multi_step_decode
        self._burst = None
        self._burst_df = None
        if (K <= 1 and self.config.decode_pipeline_depth < 2
                and self.config.sp_size <= 1):
            # the dispatch-ahead pipeline always runs through the burst
            # program (its carry keeps sampled tokens device-resident),
            # so pipelining with multi_step_decode=1 compiles a K=1 scan
            # — and so does the SP engine's early decode handoff, which
            # chains the first burst off the final chunk's device token
            return
        cfg = self.config.model
        mesh = self.mesh
        bs = self.config.kv_block_size
        fused = self._fused_epilogue_enabled()
        batch_spec = NamedSharding(mesh, P("dp"))
        batch2_spec = NamedSharding(mesh, P("dp", None))
        repl = NamedSharding(mesh, P())
        steps_spec = NamedSharding(mesh, P(None, "dp"))
        steps3_spec = NamedSharding(mesh, P(None, "dp", None))
        forward, head = self._make_forward()

        import dataclasses as _dc

        def burst(params, k_cache, v_cache, counts, seen, bias, tokens0,
                  positions0, block_tables, samp, sample_slots, commit,
                  want_top):
            b = tokens0.shape[0]
            rows = jnp.arange(b)

            def one(carry, step_i):
                k_cache, v_cache, counts, toks, pos = carry
                # the slot for each row's pending token, straight from the
                # block table (the host precomputes this in the single-step
                # path); inactive rows write nowhere
                slot = block_tables[rows, pos // bs] * bs + pos % bs
                slot = jnp.where(commit, slot, -1)
                hidden, (k_cache, v_cache) = forward(
                    params, (k_cache, v_cache), toks[:, None], pos[:, None],
                    block_tables, slot[:, None], pos + 1,
                )
                samp_i = _dc.replace(samp, counters=samp.counters + step_i)
                nt, lp, tv, ti, counts = _sample_and_logprobs(
                    cfg, head(hidden[:, 0], params), samp_i, counts, seen,
                    bias, sample_slots, commit, want_top, fused=fused,
                )
                return (k_cache, v_cache, counts, nt, pos + 1), (nt, lp, tv, ti)

            init = (k_cache, v_cache, counts, tokens0, positions0)
            (k_cache, v_cache, counts, _, _), (toks, lps, tvs, tis) = (
                jax.lax.scan(one, init, jnp.arange(K))
            )
            return (toks, lps, tvs, tis, k_cache, v_cache, counts, seen,
                    bias)

        samp_spec = SamplingParams(
            temperature=batch_spec, top_k=batch_spec, top_p=batch_spec,
            min_p=batch_spec, presence_penalty=batch_spec,
            frequency_penalty=batch_spec, repetition_penalty=batch_spec,
            keys=batch2_spec, counters=batch_spec,
        )
        self._burst = jax.jit(
            burst,
            donate_argnums=(1, 2, 3, 4, 5),
            in_shardings=(
                self.param_shardings,
                self.cache_sharding, self.cache_sharding,
                self.state_sharding, self.state_sharding, self.state_sharding,
                batch_spec,                  # tokens0 [B]
                batch_spec,                  # positions0 [B]
                batch2_spec,                 # block_tables [B, W]
                samp_spec,
                batch_spec,                  # sample_slots
                batch_spec,                  # commit
                repl,                        # want_top
            ),
            out_shardings=(steps_spec, steps_spec, steps3_spec, steps3_spec,
                           self.cache_sharding, self.cache_sharding,
                           self.state_sharding, self.state_sharding,
                           self.state_sharding),
        )

        if not self.config.device_finish_enabled:
            return

        # ---- the device-finish (persistent-loop) variant ----
        #
        # Same K-step scan, plus a per-row ``done`` carry and on-device
        # finish state: EOS / hidden-stop membership ([B, STOP_ID_WIDTH]
        # id matrix), per-row generated-token counters against min/max
        # bounds, and the model-len horizon — evaluated each step by
        # sampling.device_finish_mask, the exact mirror of
        # Scheduler._check_finish. A row that finishes FREEZES: its KV
        # slot goes to -1 (no writes), its sampling-penalty counts stop
        # updating (``live`` gates _sample_and_logprobs' commit), its
        # position/token/counter carries stop advancing, and its output
        # lane emits -1 pads. The burst itself never ends early, so the
        # scheduler can chain dispatches off the returned device carry
        # without any host round-trip.
        #
        # The carry additionally holds the UNRESTRICTED-traffic state:
        # ``ring`` — the row's trailing SUFFIX_RING_W emitted tokens,
        # hashed each step against the stop strings' canonical-
        # tokenization hashes (sampling.stop_candidate_mask; a match
        # freezes the row as a *candidate* the host confirms exactly on
        # drain) — and ``gstate``, the guided-grammar cursor advanced
        # through a bounded device transition table (``gtable``:
        # state × token → next state, -1 reject, state 0 = DONE;
        # engine/guided.compile_device_table). Rows with gstate < 0 are
        # unguided and never consult the table.
        from .sampling import (
            device_finish_mask,
            ring_push,
            stop_candidate_mask,
        )

        max_len = self.config.max_model_len

        def burst_df(params, k_cache, v_cache, counts, seen, bias,
                     tokens0, positions0, gen0, done0, ring0, gstate0,
                     block_tables, samp, sample_slots, commit, want_top,
                     stop_ids, min_new, max_new, stop_hash, stop_hlen,
                     gtable):
            b = tokens0.shape[0]
            rows = jnp.arange(b)

            def one(carry, _step_i):
                (k_cache, v_cache, counts, toks, pos, gen, done, ring,
                 gstate) = carry
                live = jnp.logical_and(commit, jnp.logical_not(done))
                slot = block_tables[rows, pos // bs] * bs + pos % bs
                slot = jnp.where(live, slot, -1)
                hidden, (k_cache, v_cache) = forward(
                    params, (k_cache, v_cache), toks[:, None], pos[:, None],
                    block_tables, slot[:, None], pos + 1,
                )
                # PRNG fold-in counter IS the carried generated count, so
                # a frozen row's counter stops with it and a live row's
                # matches the single-step path exactly
                samp_i = _dc.replace(samp, counters=gen)
                # guided mask from the device table: the sync path bakes
                # the same mask into the persistent bias buffer, so
                # adding it here keeps logits (and logprobs) bit-equal
                guided = gstate >= 0
                sel = jnp.where(guided, gstate, 0)
                grow = gtable[sel]                       # [B, V]
                gmask = jnp.where(
                    guided[:, None] & (grow < 0), -1e9, 0.0
                ).astype(jnp.float32)
                if fused:
                    # the finish checks ride INSIDE the epilogue kernel:
                    # the whole per-step tail is one dispatch
                    nt, lp, tv, ti, counts, hard, cand, ring_n = (
                        _sample_and_logprobs(
                            cfg, head(hidden[:, 0], params), samp_i,
                            counts, seen, bias, sample_slots, live,
                            want_top, extra_bias=gmask, fused=True,
                            finish=(gen, pos, min_new, max_new, stop_ids,
                                    ring, stop_hash, stop_hlen),
                            max_model_len=max_len,
                        )
                    )
                    gen_n = gen + live.astype(jnp.int32)
                else:
                    nt, lp, tv, ti, counts = _sample_and_logprobs(
                        cfg, head(hidden[:, 0], params), samp_i, counts,
                        seen, bias, sample_slots, live, want_top,
                        extra_bias=gmask,
                    )
                    gen_n = gen + live.astype(jnp.int32)
                    ring_n = ring_push(ring, nt, live)
                    hard = device_finish_mask(
                        nt, gen_n, pos, stop_ids, min_new, max_new, max_len
                    )
                    cand = stop_candidate_mask(
                        ring_n, gen_n, min_new, stop_hash, stop_hlen
                    )
                # grammar advance on the sampled token: DONE (state 0)
                # completes the constraint; a reject (< 0) is
                # unreachable through the mask but freezes defensively —
                # the host names either verdict on drain. A hard finish
                # (eos at a legal end) wins, mirroring the host's
                # _check_finish-before-guided-advance order.
                gnext = gtable[sel, nt]
                gdone = guided & jnp.logical_not(hard) & (gnext <= 0)
                newly = live & (hard | cand | gdone)
                done_n = done | newly
                # the finishing token still emits (the host streams it);
                # later steps of a frozen row emit -1 pads
                out_tok = jnp.where(live, nt, -1)
                out_lp = jnp.where(live, lp, 0.0)
                adv = live & jnp.logical_not(newly)
                toks_n = jnp.where(adv, nt, toks)
                pos_n = jnp.where(adv, pos + 1, pos)
                gstate_n = jnp.where(adv & guided, gnext, gstate)
                return ((k_cache, v_cache, counts, toks_n, pos_n, gen_n,
                         done_n, ring_n, gstate_n),
                        (out_tok, out_lp, tv, ti))

            init = (k_cache, v_cache, counts, tokens0, positions0, gen0,
                    done0, ring0, gstate0)
            ((k_cache, v_cache, counts, tok_c, pos_c, gen_c, done_c,
              ring_c, gstate_c),
             (toks, lps, tvs, tis)) = jax.lax.scan(
                one, init, jnp.arange(K)
            )
            return (toks, lps, tvs, tis, tok_c, pos_c, gen_c, done_c,
                    ring_c, gstate_c,
                    k_cache, v_cache, counts, seen, bias)

        self._burst_df = jax.jit(
            burst_df,
            donate_argnums=(1, 2, 3, 4, 5),
            in_shardings=(
                self.param_shardings,
                self.cache_sharding, self.cache_sharding,
                self.state_sharding, self.state_sharding, self.state_sharding,
                batch_spec,                  # tokens0 [B]
                batch_spec,                  # positions0 [B]
                batch_spec,                  # gen0 [B]
                batch_spec,                  # done0 [B]
                batch2_spec,                 # ring0 [B, RING_W]
                batch_spec,                  # gstate0 [B]
                batch2_spec,                 # block_tables [B, W]
                samp_spec,
                batch_spec,                  # sample_slots
                batch_spec,                  # commit
                repl,                        # want_top
                batch2_spec,                 # stop_ids [B, E]
                batch_spec,                  # min_new [B]
                batch_spec,                  # max_new [B]
                batch2_spec,                 # stop_hash [B, NS]
                batch2_spec,                 # stop_hlen [B, NS]
                repl,                        # gtable [S, V]
            ),
            out_shardings=(steps_spec, steps_spec, steps3_spec, steps3_spec,
                           batch_spec, batch_spec, batch_spec, batch_spec,
                           batch2_spec, batch_spec,
                           self.cache_sharding, self.cache_sharding,
                           self.state_sharding, self.state_sharding,
                           self.state_sharding),
        )

    def _build_spec_burst(self):
        """Propose-verify rounds chained off the SAME device carry as
        the device-finish burst — the in-carry half of speculative
        decoding (ISSUE 13 / ROADMAP item 2).

        One dispatch = one round: S = K+1 positions run through one
        forward (the pending token + up to K proposals), the full head's
        per-position argmax is the verify, the accepted prefix + the
        correction token commit with the SAME freeze semantics as the
        plain chained burst (finish mask + suffix-hash stop candidates
        per emitted token), and the carry feeds the next round without a
        host barrier. Two jit variants share the traced round body:
        ``_spec_ngram`` derives proposals from the carry's trailing-token
        ring in-program; ``_spec_verify`` takes them as a device array —
        the draft model's chained burst output — so draft/target rounds
        interleave with no host sync between them. Spec-eligible rows
        are greedy and penalty-free (scheduler._spec_eligible), so the
        round needs no sampling params and never touches the
        counts/seen/bias buffers — exactly like the sync verify's
        commit=False dispatch.
        """
        self._spec_ngram = None
        self._spec_verify = None
        cfg_e = self.config
        K = (cfg_e.spec_draft_tokens if cfg_e.spec_draft_model
             else cfg_e.spec_ngram_tokens)
        if K <= 0 or not cfg_e.device_finish_enabled:
            return
        cfg = self.config.model
        mesh = self.mesh
        bs = self.config.kv_block_size
        batch_spec = NamedSharding(mesh, P("dp"))
        batch2_spec = NamedSharding(mesh, P(None, "dp"))
        batchrow_spec = NamedSharding(mesh, P("dp", None))
        repl = NamedSharding(mesh, P())
        forward, head = self._make_forward()
        from .sampling import (
            device_finish_mask,
            ring_push,
            stop_candidate_mask,
        )

        S = K + 1
        max_len = self.config.max_model_len
        match = self.config.spec_ngram_match

        def spec_round(params, k_cache, v_cache, tokens0, positions0,
                       gen0, done0, ring0, gstate0, block_tables, commit,
                       stop_ids, min_new, max_new, stop_hash, stop_hlen,
                       props):
            b = tokens0.shape[0]
            rows = jnp.arange(b)
            live0 = jnp.logical_and(commit, jnp.logical_not(done0))
            valid = props >= 0                               # [B, K]
            row_toks = jnp.concatenate(
                [tokens0[:, None], jnp.where(valid, props, 0)], axis=1
            )                                                # [B, S]
            poss = positions0[:, None] + jnp.arange(S)[None, :]
            slots = block_tables[rows[:, None], poss // bs] * bs + poss % bs
            slots = jnp.where(live0[:, None], slots, -1)
            hidden, (k_cache, v_cache) = forward(
                params, (k_cache, v_cache), row_toks, poss, block_tables,
                slots, positions0 + S,
            )
            greedy = jnp.argmax(
                head(hidden, params), axis=-1
            ).astype(jnp.int32)                              # [B, S]
            m = valid & (greedy[:, :K] == props)
            acc = jnp.cumprod(m.astype(jnp.int32), axis=1).sum(axis=1)
            nprop = jnp.where(live0, valid.astype(jnp.int32).sum(axis=1), 0)

            # acceptance accounting matches the sync verify: proposals
            # that VERIFIED, even if a finish truncates the emit below
            # (the freeze-fold decides what streams, not what counted)
            nacc = jnp.where(live0, acc, 0)

            # fold the emitted positions in order, re-running the exact
            # per-token finish/freeze logic of the plain chained burst
            outs = []
            toks_c, pos_c, gen_c = tokens0, positions0, gen0
            done_c, ring_c = done0, ring0
            for j in range(S):
                t_j = greedy[:, j]
                emit = live0 & jnp.logical_not(done_c) & (j <= acc)
                gen_c = gen_c + emit.astype(jnp.int32)
                ring_c = ring_push(ring_c, t_j, emit)
                hard = device_finish_mask(
                    t_j, gen_c, pos_c, stop_ids, min_new, max_new, max_len
                )
                cand = stop_candidate_mask(
                    ring_c, gen_c, min_new, stop_hash, stop_hlen
                )
                newly = emit & (hard | cand)
                outs.append(jnp.where(emit, t_j, -1))
                adv = emit & jnp.logical_not(newly)
                toks_c = jnp.where(adv, t_j, toks_c)
                pos_c = jnp.where(adv, pos_c + 1, pos_c)
                done_c = done_c | newly
            return (jnp.stack(outs, axis=0), nprop, nacc, toks_c, pos_c,
                    gen_c, done_c, ring_c, gstate0, k_cache, v_cache)

        common_in = (
            self.param_shardings,
            self.cache_sharding, self.cache_sharding,
            batch_spec,      # tokens0
            batch_spec,      # positions0
            batch_spec,      # gen0
            batch_spec,      # done0
            batchrow_spec,   # ring0
            batch_spec,      # gstate0
            batchrow_spec,   # block_tables
            batch_spec,      # commit
            batchrow_spec,   # stop_ids
            batch_spec,      # min_new
            batch_spec,      # max_new
            batchrow_spec,   # stop_hash
            batchrow_spec,   # stop_hlen
        )
        common_out = (
            batch2_spec,     # toks [S, B]
            batch_spec,      # nprop
            batch_spec,      # nacc
            batch_spec, batch_spec, batch_spec, batch_spec,  # tok/pos/gen/done
            batchrow_spec,   # ring
            batch_spec,      # gstate
            self.cache_sharding, self.cache_sharding,
        )

        def spec_ngram(params, k_cache, v_cache, tokens0, positions0,
                       gen0, done0, ring0, gstate0, block_tables, commit,
                       stop_ids, min_new, max_new, stop_hash, stop_hlen):
            props = _ngram_props(ring0, match, K)
            return spec_round(
                params, k_cache, v_cache, tokens0, positions0, gen0,
                done0, ring0, gstate0, block_tables, commit, stop_ids,
                min_new, max_new, stop_hash, stop_hlen, props,
            )

        if cfg_e.spec_draft_model:
            self._spec_verify = jax.jit(
                spec_round,
                donate_argnums=(1, 2),
                in_shardings=common_in + (batchrow_spec,),  # props [B, K]
                out_shardings=common_out,
            )
        else:
            self._spec_ngram = jax.jit(
                spec_ngram,
                donate_argnums=(1, 2),
                in_shardings=common_in,
                out_shardings=common_out,
            )
        self._spec_k = K

    def _build_sp_prefill(self):
        """The sequence-parallel long-context prefill program.

        One compiled shape: [1, S] chunk tokens sharded over the mesh's
        ``sp`` axis (S = config.sp_prefill_bucket(); short/final chunks
        pad into it), fresh K/V scattered into the paged cache exactly
        like the dense ladder, attention = one ring pass over the chunk
        plus the gathered committed prefix (parallel/sequence.py
        sp_chunk_attention), and the dense step's sampling tail on the
        last valid position so the final chunk's sampled token — and its
        logprobs — are bit-identical to what the dense ladder would have
        produced. Non-final chunks dispatch with commit=False and
        nothing reads their outputs.
        """
        self._sp_prefill = None
        cfg_e = self.config
        if cfg_e.sp_size <= 1:
            return
        if "sp" not in self.mesh.axis_names or self.mesh.shape["sp"] <= 1:
            raise ValueError(
                f"sp_size {cfg_e.sp_size} needs an 'sp' mesh axis of that "
                f"size (got mesh {dict(self.mesh.shape)})"
            )
        cfg = self.config.model
        if (self.arch is not llama or cfg.sliding_window
                or cfg.attn_logit_softcap or cfg.num_experts
                or cfg.kv_lora_rank):
            raise ValueError(
                "sequence-parallel prefill currently serves llama-family "
                "GQA dense trunks without sliding windows (the ring "
                "kernel has no MLA/MoE/windowed variant yet)"
            )
        mesh = self.mesh
        sp = cfg_e.sp_size
        head_axis = "tp" if cfg_e.tp_size > 1 else None
        S = cfg_e.sp_prefill_bucket()
        bs = cfg_e.kv_block_size
        # block-table width padded so the gathered prefix (W*bs keys)
        # shards evenly over the axis alongside the chunk's S
        w = cfg_e.blocks_per_seq
        while (w * bs) % sp:
            w += 1
        self._sp_bucket = S
        self._sp_width = w
        fused = self._fused_epilogue_enabled()
        repl = NamedSharding(mesh, P())
        seq_spec = NamedSharding(mesh, P(None, "sp"))
        forward, head = self._make_forward()
        del forward  # the SP trunk has its own

        def sp_step(params, k_cache, v_cache, counts, seen, bias, tokens,
                    positions, block_tables, slot_mapping, context_lens,
                    chunk_start, last_idx, samp, sample_slots, commit,
                    want_top):
            hidden, (k_cache, v_cache) = llama.sp_decoder_forward(
                params, cfg, tokens, positions, (k_cache, v_cache),
                block_tables, slot_mapping, context_lens, chunk_start,
                mesh, sp_axis="sp", head_axis=head_axis,
            )
            b = tokens.shape[0]
            last_logits = head(hidden[jnp.arange(b), last_idx], params)
            next_tokens, lps, top_vals, top_ids, counts = (
                _sample_and_logprobs(
                    cfg, last_logits, samp, counts, seen, bias,
                    sample_slots, commit, want_top, fused=fused,
                )
            )
            return (next_tokens, lps, top_vals, top_ids, k_cache, v_cache,
                    counts, seen, bias)

        samp_spec = SamplingParams(
            temperature=repl, top_k=repl, top_p=repl, min_p=repl,
            presence_penalty=repl, frequency_penalty=repl,
            repetition_penalty=repl, keys=repl, counters=repl,
        )
        self._sp_prefill = jax.jit(
            sp_step,
            donate_argnums=(1, 2, 3, 4, 5),
            in_shardings=(
                self.param_shardings,
                self.cache_sharding, self.cache_sharding,
                self.state_sharding, self.state_sharding,
                self.state_sharding,
                seq_spec,                    # tokens [1, S]
                seq_spec,                    # positions [1, S]
                repl,                        # block_tables [1, W]
                seq_spec,                    # slot_mapping [1, S]
                repl,                        # context_lens [1]
                repl,                        # chunk_start scalar
                repl,                        # last_idx [1]
                samp_spec,
                repl,                        # sample_slots [1]
                repl,                        # commit [1]
                repl,                        # want_top
            ),
            out_shardings=(repl, repl, repl, repl,
                           self.cache_sharding, self.cache_sharding,
                           self.state_sharding, self.state_sharding,
                           self.state_sharding),
        )

    @property
    def sp_ready(self) -> bool:
        """Is the sequence-parallel prefill program built? (The scheduler
        and the disagg prefill worker gate the SP ladder on this.)"""
        return getattr(self, "_sp_prefill", None) is not None

    @property
    def sp_chunk_tokens(self) -> int:
        """Tokens one SP chunk advances (the fixed compiled bucket)."""
        return self._sp_bucket

    def sp_prefill_chunk(
        self,
        prompt,                    # full token list UP TO the chunk end
        start: int,                # chunk's first position (KV before it
        block_ids,                 #   is already committed)
        *,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        min_p: float = 0.0,
        presence_penalty: float = 0.0,
        frequency_penalty: float = 0.0,
        repetition_penalty: float = 1.0,
        seed_keys=None,            # [2] u32 per-request key
        counters: int = 0,
        sample_slot: int = 0,
        commit: bool = False,      # final chunk samples/commits
        want_top: bool = False,
    ):
        """Dispatch ONE sequence-parallel prefill chunk ([start,
        len(prompt)) of the prompt, ≤ sp_chunk_tokens tokens). Returns
        the step-tail device arrays ``(next_tokens, lps, top_vals,
        top_ids)`` — meaningful only on the committing (final) chunk.
        Dispatch-only: no host sync happens here."""
        S = self._sp_bucket
        w = self._sp_width
        bs = self.config.kv_block_size
        suffix = prompt[start:]
        take = len(suffix)
        assert 0 < take <= S, (take, S)
        tokens = np.zeros((1, S), np.int32)
        tokens[0, :take] = suffix
        positions = np.full((1, S), len(prompt) - 1, np.int32)
        positions[0, :take] = np.arange(start, len(prompt))
        slot_map = np.full((1, S), -1, np.int32)
        for i, pos in enumerate(range(start, len(prompt))):
            slot_map[0, i] = block_ids[pos // bs] * bs + pos % bs
        btab = np.zeros((1, w), np.int32)
        btab[0, : len(block_ids)] = block_ids
        if seed_keys is None:
            seed_keys = np.zeros(2, np.uint32)
        samp = SamplingParams(
            temperature=jnp.asarray([temperature], jnp.float32),
            top_k=jnp.asarray([top_k], jnp.int32),
            top_p=jnp.asarray([top_p], jnp.float32),
            min_p=jnp.asarray([min_p], jnp.float32),
            presence_penalty=jnp.asarray([presence_penalty], jnp.float32),
            frequency_penalty=jnp.asarray([frequency_penalty], jnp.float32),
            repetition_penalty=jnp.asarray([repetition_penalty],
                                           jnp.float32),
            keys=jnp.asarray(np.asarray(seed_keys, np.uint32)[None, :]),
            counters=jnp.asarray([counters], jnp.int32),
        )
        with self.compiles.track("prefill_sp", f"s{S}_w{w}"):
            (next_tokens, lps, top_vals, top_ids, k, v, counts, seen,
             bias) = self._sp_prefill(
                self.params, self.kv_cache[0], self.kv_cache[1],
                self.sample_state[0], self.sample_state[1],
                self.sample_state[2],
                jnp.asarray(tokens), jnp.asarray(positions),
                jnp.asarray(btab), jnp.asarray(slot_map),
                jnp.asarray([len(prompt)], jnp.int32),
                jnp.asarray(start, jnp.int32),
                jnp.asarray([take - 1], jnp.int32),
                samp,
                jnp.asarray([sample_slot], jnp.int32),
                jnp.asarray([commit], jnp.bool_),
                jnp.asarray(bool(want_top), jnp.bool_),
            )
        self.kv_cache = (k, v)
        self.sample_state = (counts, seen, bias)
        return next_tokens, lps, top_vals, top_ids

    @property
    def spec_burst_ready(self) -> bool:
        """Are the chained propose-verify programs built? (The scheduler
        gates the spec chain on this; test doubles may just define
        decode_burst_spec.)"""
        return (getattr(self, "_spec_ngram", None) is not None
                or getattr(self, "_spec_verify", None) is not None)

    def decode_burst_spec(
        self,
        tokens0,                   # [B] np (chain start) or device carry
        positions0,
        gen0,
        done0,
        ring0,                     # [B, SUFFIX_RING_W]
        gstate0,                   # [B] (passthrough; spec rows unguided)
        block_tables: np.ndarray,  # [B, W]
        *,
        commit,                    # [B] bool (host np or device)
        stop_ids: np.ndarray,
        min_new: np.ndarray,
        max_new: np.ndarray,
        stop_hash: np.ndarray,
        stop_hlen: np.ndarray,
        proposals=None,            # [B, K] device array (draft) or None (ngram)
    ):
        """One chained propose-verify round; returns ``(toks [S, B],
        nprop [B], nacc [B], carry)`` with -1 pads past each row's
        acceptance/freeze and the same carry tuple as
        ``decode_burst_chained``."""
        b = block_tables.shape[0]
        args = (
            self.params, self.kv_cache[0], self.kv_cache[1],
            jnp.asarray(tokens0, jnp.int32),
            jnp.asarray(positions0, jnp.int32),
            jnp.asarray(gen0, jnp.int32),
            jnp.asarray(done0, jnp.bool_),
            jnp.asarray(ring0, jnp.int32),
            jnp.asarray(gstate0, jnp.int32),
            jnp.asarray(block_tables, jnp.int32),
            jnp.asarray(commit, jnp.bool_),
            jnp.asarray(stop_ids, jnp.int32),
            jnp.asarray(min_new, jnp.int32),
            jnp.asarray(max_new, jnp.int32),
            jnp.asarray(stop_hash, jnp.uint32),
            jnp.asarray(stop_hlen, jnp.int32),
        )
        with self.compiles.track(
            "decode_burst_spec", f"b{b}_w{block_tables.shape[1]}"
        ):
            if proposals is None:
                out = self._spec_ngram(*args)
            else:
                out = self._spec_verify(
                    *args, jnp.asarray(proposals, jnp.int32)
                )
        (toks, nprop, nacc, tok_c, pos_c, gen_c, done_c, ring_c,
         gstate_c, k, v) = out
        self.kv_cache = (k, v)
        return toks, nprop, nacc, (tok_c, pos_c, gen_c, done_c, ring_c,
                                   gstate_c)

    def decode_burst(
        self,
        tokens0: np.ndarray,       # [B] pending token per row
        positions0: np.ndarray,    # [B] its position
        block_tables: np.ndarray,  # [B, W] covering positions0 + K
        temperature: np.ndarray,
        top_k: np.ndarray,
        top_p: np.ndarray,
        *,
        min_p: np.ndarray,
        presence_penalty: np.ndarray,
        frequency_penalty: np.ndarray,
        repetition_penalty: np.ndarray,
        seed_keys: np.ndarray,
        counters: np.ndarray,
        commit: np.ndarray,        # [B] row is live (inactive rows inert)
        want_top: bool = False,
    ):
        """Run the K-step fused decode; returns [K, B]-leading arrays."""
        samp = SamplingParams(
            temperature=jnp.asarray(temperature, jnp.float32),
            top_k=jnp.asarray(top_k, jnp.int32),
            top_p=jnp.asarray(top_p, jnp.float32),
            min_p=jnp.asarray(min_p, jnp.float32),
            presence_penalty=jnp.asarray(presence_penalty, jnp.float32),
            frequency_penalty=jnp.asarray(frequency_penalty, jnp.float32),
            repetition_penalty=jnp.asarray(repetition_penalty, jnp.float32),
            keys=jnp.asarray(seed_keys, jnp.uint32),
            counters=jnp.asarray(counters, jnp.int32),
        )
        b = tokens0.shape[0]
        with self.compiles.track(
            "decode_burst", f"b{b}_w{block_tables.shape[1]}"
        ):
            (toks, lps, tvs, tis, k, v, counts, seen, bias) = self._burst(
                self.params, self.kv_cache[0], self.kv_cache[1],
                self.sample_state[0], self.sample_state[1],
                self.sample_state[2],
                jnp.asarray(tokens0, jnp.int32),
                jnp.asarray(positions0, jnp.int32),
                jnp.asarray(block_tables, jnp.int32),
                samp,
                jnp.arange(b, dtype=jnp.int32),
                jnp.asarray(commit, jnp.bool_),
                jnp.asarray(bool(want_top), jnp.bool_),
            )
        self.kv_cache = (k, v)
        self.sample_state = (counts, seen, bias)
        return toks, lps, tvs, tis

    # guided device tables pad their state dim to this ladder so each
    # bucket is one compiled burst program, not one per grammar
    GUIDED_STATE_BUCKETS = (1, 64, 256, 1024)

    def guided_state_bucket(self, n_states: int) -> int:
        for s in self.GUIDED_STATE_BUCKETS:
            if n_states <= s:
                return s
        return self.GUIDED_STATE_BUCKETS[-1]

    def _dummy_guided_table(self):
        """The shared [1, V] all-reject table for unguided dispatches —
        rows with gstate < 0 never consult it."""
        if getattr(self, "_dummy_gtable", None) is None:
            self._dummy_gtable = jnp.full(
                (1, self.config.model.vocab_size), -1, jnp.int32
            )
        return self._dummy_gtable

    def decode_burst_chained(
        self,
        tokens0,                   # [B] np (chain start) or device carry
        positions0,                # [B] likewise
        gen0,                      # [B] generated-token counts, likewise
        done0,                     # [B] bool done mask, likewise
        block_tables: np.ndarray,  # [B, W]
        temperature: np.ndarray,
        top_k: np.ndarray,
        top_p: np.ndarray,
        *,
        min_p: np.ndarray,
        presence_penalty: np.ndarray,
        frequency_penalty: np.ndarray,
        repetition_penalty: np.ndarray,
        seed_keys: np.ndarray,
        commit: np.ndarray,        # [B] row is a (live) chain member
        stop_ids: np.ndarray,      # [B, STOP_ID_WIDTH] -1-padded stop set
        min_new: np.ndarray,       # [B] i32
        max_new: np.ndarray,       # [B] i32
        ring0=None,                # [B, SUFFIX_RING_W] trailing tokens
        gstate0=None,              # [B] guided table state (-1 unguided)
        stop_hash=None,            # [B, STOP_SEQ_WIDTH] uint32 targets
        stop_hlen=None,            # [B, STOP_SEQ_WIDTH] i32 lengths
        gtable=None,               # [S, V] device table (None = dummy)
        want_top: bool = False,
    ):
        """Run one K-step burst with device-resident finish detection.

        Returns ``(toks, lps, tvs, tis, carry)`` with [K, B]-leading
        output arrays (-1 pads past each row's finish) and ``carry`` the
        next dispatch's device-resident ``(tokens, positions, gen, done,
        ring, gstate)`` — feed it straight back as the leading carry
        arguments to chain bursts without a host round-trip.
        """
        from .sampling import STOP_SEQ_WIDTH, SUFFIX_RING_W

        b = block_tables.shape[0]
        samp = SamplingParams(
            temperature=jnp.asarray(temperature, jnp.float32),
            top_k=jnp.asarray(top_k, jnp.int32),
            top_p=jnp.asarray(top_p, jnp.float32),
            min_p=jnp.asarray(min_p, jnp.float32),
            presence_penalty=jnp.asarray(presence_penalty, jnp.float32),
            frequency_penalty=jnp.asarray(frequency_penalty, jnp.float32),
            repetition_penalty=jnp.asarray(repetition_penalty, jnp.float32),
            keys=jnp.asarray(seed_keys, jnp.uint32),
            counters=jnp.asarray(gen0, jnp.int32),  # carried in-scan
        )
        if ring0 is None:
            ring0 = np.full((b, SUFFIX_RING_W), -1, np.int32)
        if gstate0 is None:
            gstate0 = np.full(b, -1, np.int32)
        if stop_hash is None:
            stop_hash = np.zeros((b, STOP_SEQ_WIDTH), np.uint32)
        if stop_hlen is None:
            stop_hlen = np.zeros((b, STOP_SEQ_WIDTH), np.int32)
        if gtable is None:
            gtable = self._dummy_guided_table()
        with self.compiles.track(
            "decode_burst_df",
            f"b{b}_w{block_tables.shape[1]}_g{gtable.shape[0]}",
        ):
            (toks, lps, tvs, tis, tok_c, pos_c, gen_c, done_c, ring_c,
             gstate_c, k, v, counts, seen, bias) = self._burst_df(
                self.params, self.kv_cache[0], self.kv_cache[1],
                self.sample_state[0], self.sample_state[1],
                self.sample_state[2],
                jnp.asarray(tokens0, jnp.int32),
                jnp.asarray(positions0, jnp.int32),
                jnp.asarray(gen0, jnp.int32),
                jnp.asarray(done0, jnp.bool_),
                jnp.asarray(ring0, jnp.int32),
                jnp.asarray(gstate0, jnp.int32),
                jnp.asarray(block_tables, jnp.int32),
                samp,
                jnp.arange(b, dtype=jnp.int32),
                jnp.asarray(commit, jnp.bool_),
                jnp.asarray(bool(want_top), jnp.bool_),
                jnp.asarray(stop_ids, jnp.int32),
                jnp.asarray(min_new, jnp.int32),
                jnp.asarray(max_new, jnp.int32),
                jnp.asarray(stop_hash, jnp.uint32),
                jnp.asarray(stop_hlen, jnp.int32),
                jnp.asarray(gtable, jnp.int32),
            )
        self.kv_cache = (k, v)
        self.sample_state = (counts, seen, bias)
        return toks, lps, tvs, tis, (tok_c, pos_c, gen_c, done_c, ring_c,
                                     gstate_c)

    def step(
        self,
        tokens: np.ndarray,        # [B, S]
        positions: np.ndarray,     # [B, S]
        block_tables: np.ndarray,  # [B, W]
        slot_mapping: np.ndarray,  # [B, S]
        context_lens: np.ndarray,  # [B]
        last_idx: np.ndarray,      # [B] index of the position to sample from
        temperature: np.ndarray,
        top_k: np.ndarray,
        top_p: np.ndarray,
        key: Optional[jax.Array] = None,
        *,
        min_p: Optional[np.ndarray] = None,
        presence_penalty: Optional[np.ndarray] = None,
        frequency_penalty: Optional[np.ndarray] = None,
        repetition_penalty: Optional[np.ndarray] = None,
        seed_keys: Optional[np.ndarray] = None,   # [B, 2] u32 per-row keys
        counters: Optional[np.ndarray] = None,    # [B] i32 fold-in counters
        sample_slots: Optional[np.ndarray] = None,  # [B] i32 state-row per batch row
        commit: Optional[np.ndarray] = None,      # [B] bool count sampled token
        want_top: bool = True,  # compute top-K alternatives this step?
        targets: Optional[np.ndarray] = None,  # [B, S] next-prompt-token ids
        want_prompt: bool = False,  # compute prompt logprobs at `targets`?
        want_greedy: bool = False,  # per-position argmax (spec verify)?
    ) -> Tuple[jax.Array, ...]:
        """Run one compiled step; returns (next_tokens, logprobs) device arrays.

        Legacy callers pass a single ``key`` (tests, warmup, dry runs): it is
        broadcast into per-row keys with the row index as fold-in counter.
        The scheduler passes per-request ``seed_keys``/``counters`` instead.
        """
        b = tokens.shape[0]
        if seed_keys is None:
            if key is None:
                key = jax.random.PRNGKey(0)
            seed_keys = np.tile(
                np.asarray(jax.random.key_data(key), np.uint32)[None, :], (b, 1)
            )
        if counters is None:
            counters = np.arange(b, dtype=np.int32)
        samp = SamplingParams(
            temperature=jnp.asarray(temperature, jnp.float32),
            top_k=jnp.asarray(top_k, jnp.int32),
            top_p=jnp.asarray(top_p, jnp.float32),
            min_p=jnp.asarray(
                min_p if min_p is not None else np.zeros(b), jnp.float32),
            presence_penalty=jnp.asarray(
                presence_penalty if presence_penalty is not None else np.zeros(b),
                jnp.float32),
            frequency_penalty=jnp.asarray(
                frequency_penalty if frequency_penalty is not None else np.zeros(b),
                jnp.float32),
            repetition_penalty=jnp.asarray(
                repetition_penalty if repetition_penalty is not None else np.ones(b),
                jnp.float32),
            keys=jnp.asarray(seed_keys, jnp.uint32),
            counters=jnp.asarray(counters, jnp.int32),
        )
        if sample_slots is None:
            sample_slots = np.arange(b, dtype=np.int32)
        if commit is None:
            commit = np.zeros(b, bool)
        if targets is None:
            targets = np.zeros_like(tokens)
        s = tokens.shape[1]
        with self.compiles.track(
            "prefill" if s > 1 else "decode",
            f"b{b}_s{s}_w{block_tables.shape[1]}",
        ):
            (next_tokens, lps, top_vals, top_ids, prompt_lps, greedy_all,
             k, v, counts, seen, bias) = self._step(
                self.params, self.kv_cache[0], self.kv_cache[1],
                self.sample_state[0], self.sample_state[1],
                self.sample_state[2],
                jnp.asarray(tokens, jnp.int32),
                jnp.asarray(positions, jnp.int32),
                jnp.asarray(block_tables, jnp.int32),
                jnp.asarray(slot_mapping, jnp.int32),
                jnp.asarray(context_lens, jnp.int32),
                jnp.asarray(last_idx, jnp.int32),
                samp,
                jnp.asarray(sample_slots, jnp.int32),
                jnp.asarray(commit, jnp.bool_),
                jnp.asarray(bool(want_top), jnp.bool_),
                jnp.asarray(targets, jnp.int32),
                jnp.asarray(bool(want_prompt), jnp.bool_),
                jnp.asarray(bool(want_greedy), jnp.bool_),
            )
        self.kv_cache = (k, v)
        self.sample_state = (counts, seen, bias)
        return next_tokens, lps, top_vals, top_ids, prompt_lps, greedy_all

    @property
    def embed_ready(self) -> bool:
        """Can this runner serve the embeddings workload? Llama-family
        GQA dense trunks without sliding windows (embed_forward runs the
        cacheless dense-attention trunk)."""
        cfg = self.config.model
        return (self.arch is llama and not cfg.sliding_window
                and not cfg.num_experts and not cfg.kv_lora_rank
                and self.config.pp_size == 1)

    def embed_prompts(self, prompts) -> np.ndarray:
        """Batched prefill-only embeddings: prompts → [n, D] float32.

        Rides the batched-prefill shape discipline — rows pad to the
        PREFILL_ROW_BUCKETS ladder, lengths to the prefill bucket ladder
        (one compiled program per (rows, bucket), built on first use) —
        but through the CACHELESS trunk (models/llama.embed_forward): no
        block allocation, no KV writes, no decode slot. Blocking (host
        sync inside); callers on an event loop run it in an executor.
        """
        if not self.embed_ready:
            raise ValueError(
                "embeddings are served by llama-family GQA dense trunks "
                "only (no MoE/MLA/sliding-window embed path yet)"
            )
        cfg = self.config
        out = np.zeros((len(prompts), cfg.model.hidden_size), np.float32)
        i = 0
        while i < len(prompts):
            batch = prompts[i : i + cfg.PREFILL_ROW_BUCKETS[-1]]
            rows = cfg.prefill_row_bucket(len(batch))
            bucket = cfg.bucket_for(max(len(p) for p in batch))
            tokens = np.zeros((rows, bucket), np.int32)
            positions = np.zeros((rows, bucket), np.int32)
            valid = np.ones(rows, np.int32)
            for j, p in enumerate(batch):
                tokens[j, : len(p)] = p
                positions[j, : len(p)] = np.arange(len(p))
                positions[j, len(p):] = len(p) - 1
                valid[j] = len(p)
            prog = self._embed_progs.get((rows, bucket))
            if prog is None:
                mesh = self.mesh
                arch = self.arch

                def embed_fn(params, t, pos, vl):
                    return arch.embed_forward(
                        params, self.config.model, t, pos, vl
                    )

                repl = NamedSharding(mesh, P())
                prog = jax.jit(
                    embed_fn,
                    in_shardings=(self.param_shardings, repl, repl, repl),
                    out_shardings=repl,
                )
                self._embed_progs[(rows, bucket)] = prog
            with self.compiles.track("embed", f"r{rows}_s{bucket}"):
                vecs = prog(
                    self.params, jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.asarray(valid),
                )
            out[i : i + len(batch)] = np.asarray(vecs)[: len(batch)]
            i += len(batch)
        return out

    def set_sample_row(
        self, slot: int, prompt_ids, generated_ids=(), logit_bias=None,
        guided_mask=None,
    ) -> None:
        """Install sampling state for a slot at admission: prompt presence,
        generated-token counts (non-empty when resuming a preempted
        stream), and the request's OpenAI logit_bias row — plus, for
        guided decoding, the initial token mask (``guided_mask``: dense
        [V] float32 the logit_bias entries add onto)."""
        v = self.config.model.vocab_size
        # defense in depth: the engine rejects out-of-vocab prompts at
        # admission (serving.py), but this state write must never fault
        # the scheduler loop — numpy fancy indexing neither clamps nor
        # drops, so filter
        seen_row = np.zeros(v, bool)
        if len(prompt_ids):
            ids = np.asarray(prompt_ids, np.int64)
            seen_row[ids[(ids >= 0) & (ids < v)]] = True
        counts_row = np.zeros(v, np.int32)
        if len(generated_ids):
            gids = np.asarray(generated_ids, np.int64)
            np.add.at(counts_row, gids[(gids >= 0) & (gids < v)], 1)
        bias_row = (
            np.asarray(guided_mask, np.float32).copy()
            if guided_mask is not None else np.zeros(v, np.float32)
        )
        for tid, b in (logit_bias or {}).items():
            tid = int(tid)
            if 0 <= tid < v:
                bias_row[tid] += float(b)
        with self.compiles.track("sample_row", f"v{v}"):
            self.sample_state = self._set_row_jit(
                self.sample_state[0], self.sample_state[1],
                self.sample_state[2],
                jnp.asarray(slot, jnp.int32), jnp.asarray(counts_row),
                jnp.asarray(seen_row), jnp.asarray(bias_row),
            )

    # ---------- paged-block gather / scatter ----------
    #
    # The KV data-movement primitive behind disaggregated prefill→decode
    # transfer and host-memory offload — the TPU-native role of the
    # reference's CUDA block-copy kernel + NIXL RDMA path (reference:
    # lib/llm/src/kernels/block_copy.cu:40-758, lib/llm/src/kv/layer.rs
    # CopyStream). XLA compiles the gather/scatter over the [L, N, bs, H, D]
    # cache; block counts are bucketed so each bucket compiles once.

    def _build_sample_row(self):
        repl = NamedSharding(self.mesh, P())

        def set_row(counts, seen, bias, slot, counts_row, seen_row, bias_row):
            return (
                counts.at[slot].set(counts_row),
                seen.at[slot].set(seen_row),
                bias.at[slot].set(bias_row),
            )

        self._set_row_jit = jax.jit(
            set_row,
            donate_argnums=(0, 1, 2),
            in_shardings=(self.state_sharding, self.state_sharding,
                          self.state_sharding, repl, repl, repl, repl),
            out_shardings=(self.state_sharding, self.state_sharding,
                           self.state_sharding),
        )

        def set_bias(bias, slot, bias_row):
            return bias.at[slot].set(bias_row)

        # bias-only row update (guided decoding rewrites its mask every
        # step; counts/seen must not be touched mid-stream)
        self._set_bias_jit = jax.jit(
            set_bias,
            donate_argnums=(0,),
            in_shardings=(self.state_sharding, repl, repl),
            out_shardings=self.state_sharding,
        )

        def edit_bias(bias, slot, ids, vals):
            row = bias[slot]
            # pad ids are vocab_size (out of range) → dropped
            row = row.at[ids].set(vals, mode="drop")
            return bias.at[slot].set(row)

        # sparse per-step edits: guided masks change only at a trie
        # node's neighborhood (a handful of ids), not across the vocab —
        # one compiled program per id-count bucket, no [V] H2D per token
        self._edit_bias_jit = jax.jit(
            edit_bias,
            donate_argnums=(0,),
            in_shardings=(self.state_sharding, repl, repl, repl),
            out_shardings=self.state_sharding,
        )

    BIAS_EDIT_BUCKETS = (8, 32, 128)

    def set_bias_row(self, slot: int, bias_row: np.ndarray) -> None:
        """Replace ONE slot's sampler bias row (guided decoding's
        per-step token mask; also carries the request's logit_bias)."""
        counts, seen, bias = self.sample_state
        with self.compiles.track(
            "guided_mask", f"v{self.config.model.vocab_size}"
        ):
            self.sample_state = (
                counts, seen,
                self._set_bias_jit(
                    bias, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(bias_row, jnp.float32),
                ),
            )

    def edit_bias_entries(self, slot: int, ids, vals) -> bool:
        """Sparse update of ONE slot's bias row: ``row[ids] = vals``.

        ids/vals pad to a small static bucket (pad id = vocab_size,
        dropped by the scatter). Returns False when the edit exceeds the
        largest bucket — the caller falls back to set_bias_row."""
        n = len(ids)
        bucket = next(
            (b for b in self.BIAS_EDIT_BUCKETS if n <= b), None
        )
        if bucket is None:
            return False
        v = self.config.model.vocab_size
        ids_p = np.full(bucket, v, np.int32)
        vals_p = np.zeros(bucket, np.float32)
        ids_p[:n] = np.asarray(ids, np.int32)
        vals_p[:n] = np.asarray(vals, np.float32)
        counts, seen, bias = self.sample_state
        with self.compiles.track("guided_mask_edit", f"n{bucket}"):
            self.sample_state = (
                counts, seen,
                self._edit_bias_jit(
                    bias, jnp.asarray(slot, jnp.int32),
                    jnp.asarray(ids_p), jnp.asarray(vals_p),
                ),
            )
        return True

    BLOCK_OP_BUCKETS = (1, 2, 4, 8, 16, 32, 64)

    def _build_block_ops(self):
        repl = NamedSharding(self.mesh, P())
        # transferred blocks use the LOGICAL trailing dims — the cache's
        # lane padding (ops/attention.lane_pad) stays on-device and off
        # the wire; gather slices it away, scatter re-pads with zeros
        cfg = self.config.model
        if getattr(cfg, "kv_lora_rank", 0):
            true_dims = (cfg.kv_lora_rank, cfg.qk_rope_head_dim)
        else:
            true_dims = (cfg.head_dim, cfg.head_dim)
        # the wire layout is always [L, n, bs, H, D]; a pp-staged cache
        # ([P, L/P, N, ...]) flattens its stage axis at the gather and
        # re-splits at the scatter, so disagg transfer / host offload see
        # one format regardless of pipeline layout. Mixed MLA trunks
        # ({"pre", "stg"} sides) flatten with prefix layers leading —
        # the same order deepseek.forward runs them.
        staged = self.config.pp_size > 1
        n_pre = self._pp_prefix_layers

        def gather(k_cache, v_cache, ids):
            # per-slab indexing: only the GATHERED blocks concatenate,
            # never the full cache (a {"pre","stg"} concat would move
            # the whole cache per 64-block bucket)
            def g(c, dim):
                if isinstance(c, dict):
                    stg = c["stg"].reshape(-1, *c["stg"].shape[2:])
                    return jnp.concatenate(
                        [c["pre"][:, ids, ..., :dim],
                         stg[:, ids, ..., :dim]], axis=0,
                    )
                if staged:
                    c = c.reshape(-1, *c.shape[2:])
                return c[:, ids, ..., :dim]

            return g(k_cache, true_dims[0]), g(v_cache, true_dims[1])

        self._gather_jit = jax.jit(
            gather,
            in_shardings=(self.cache_sharding, self.cache_sharding, repl),
            out_shardings=(repl, repl),
        )

        def scatter(k_cache, v_cache, ids, k_blocks, v_blocks):
            def sc(c, blocks):
                if isinstance(c, dict):
                    blocks = _pad_minor(blocks, c["pre"].shape[-1])
                    blocks = blocks.astype(c["pre"].dtype)
                    stg_shape = c["stg"].shape
                    stg = c["stg"].reshape(-1, *stg_shape[2:])
                    return {
                        # per-slab scatter: blocks split on the layer
                        # axis (prefix layers lead the wire layout)
                        "pre": c["pre"].at[:, ids].set(blocks[:n_pre]),
                        "stg": stg.at[:, ids].set(blocks[n_pre:])
                        .reshape(stg_shape),
                    }
                blocks = _pad_minor(blocks, c.shape[-1]).astype(c.dtype)
                if staged:
                    shape = c.shape
                    c = c.reshape(-1, *shape[2:])
                    return c.at[:, ids].set(blocks).reshape(shape)
                return c.at[:, ids].set(blocks)

            return sc(k_cache, k_blocks), sc(v_cache, v_blocks)

        self._scatter_jit = jax.jit(
            scatter,
            donate_argnums=(0, 1),
            in_shardings=(self.cache_sharding, self.cache_sharding, repl, repl, repl),
            out_shardings=(self.cache_sharding, self.cache_sharding),
        )

    def _bucket_ids(self, n: int) -> int:
        for b in self.BLOCK_OP_BUCKETS:
            if n <= b:
                return b
        return self.BLOCK_OP_BUCKETS[-1]

    def gather_blocks(self, block_ids) -> Tuple[np.ndarray, np.ndarray]:
        """Read KV blocks out of HBM → host arrays [L, n, bs, KVH, D] ×2."""
        return self.blocks_to_host(*self.gather_blocks_device(block_ids))

    @staticmethod
    def blocks_to_host(k_dev, v_dev) -> Tuple[np.ndarray, np.ndarray]:
        """Host-sync one gathered (k, v) block frame.

        The blocking half of the streamed-transfer split: callers on an
        event loop dispatch ``gather_blocks_device`` inline (cheap, and it
        must serialize with ``step``'s donated cache buffers) and run this
        device→host copy in an executor, so the wire pump never stalls the
        loop (disagg/prefill_worker.py's bounded per-chunk frames).
        """
        return np.asarray(jax.device_get(k_dev)), np.asarray(jax.device_get(v_dev))

    def gather_blocks_device(self, block_ids):
        """Read KV blocks as DEVICE arrays [L, n, bs, KVH, D] ×2.

        Same bucketed gather as gather_blocks without the host round-trip.
        Dispatch-only (no host sync): feeds the collective transfer plane
        (disagg/ici_transfer.py, HBM→HBM — must never bounce through
        numpy) and the streamed prefill pipeline's chunk-sized frames,
        which pair it with ``blocks_to_host`` off-loop.
        """
        ids = list(block_ids)
        ks, vs = [], []
        i = 0
        while i < len(ids):
            chunk = ids[i : i + self.BLOCK_OP_BUCKETS[-1]]
            bucket = self._bucket_ids(len(chunk))
            padded = chunk + [chunk[-1]] * (bucket - len(chunk))
            with self.compiles.track("kv_gather", f"n{bucket}"):
                k, v = self._gather_jit(
                    self.kv_cache[0], self.kv_cache[1],
                    jnp.asarray(padded, jnp.int32)
                )
            ks.append(k[:, : len(chunk)])
            vs.append(v[:, : len(chunk)])
            i += len(chunk)
        if len(ks) == 1:
            return ks[0], vs[0]
        return jnp.concatenate(ks, axis=1), jnp.concatenate(vs, axis=1)

    def scatter_blocks(self, block_ids, k_blocks, v_blocks) -> None:
        """Write KV block data [L, n, bs, KVH, D] into HBM cache slots.

        Accepts numpy OR already-device-resident jax arrays (callers that
        must not block the event loop stage with ``jax.device_put`` first).
        """
        ids = list(block_ids)
        assert k_blocks.shape[1] == len(ids), (k_blocks.shape, len(ids))
        kb_all = jnp.asarray(k_blocks)
        vb_all = jnp.asarray(v_blocks)
        i = 0
        while i < len(ids):
            chunk = ids[i : i + self.BLOCK_OP_BUCKETS[-1]]
            bucket = self._bucket_ids(len(chunk))
            pad = bucket - len(chunk)
            padded_ids = chunk + [chunk[-1]] * pad
            kb = kb_all[:, i : i + len(chunk)]
            vb = vb_all[:, i : i + len(chunk)]
            if pad:
                # duplicate the last block's data for the repeated pad ids —
                # identical values land on the same slot, so order is benign
                kb = jnp.concatenate([kb, jnp.repeat(kb[:, -1:], pad, axis=1)], axis=1)
                vb = jnp.concatenate([vb, jnp.repeat(vb[:, -1:], pad, axis=1)], axis=1)
            with self.compiles.track("kv_scatter", f"n{bucket}"):
                k, v = self._scatter_jit(
                    self.kv_cache[0], self.kv_cache[1],
                    jnp.asarray(padded_ids, jnp.int32), kb, vb,
                )
            self.kv_cache = (k, v)
            i += len(chunk)

    def warmup(self, decode_batch: Optional[int] = None) -> None:
        """Compile the serving programs up front: the decode program per
        KV-width bucket plus the largest prefill bucket.

        The scheduler sizes decode block tables with
        EngineConfig.kv_width_bucket, so serving touches a ladder of
        widths, not just blocks_per_seq; compiling the ladder here keeps
        multi-ten-second TPU compiles out of the first requests' latency
        (the analog of GPU engines' startup capture sweeps).

        Resilience, layered (a Mosaic compile can HANG, not just fail,
        and a hung compile wedges a host's shared compile service for
        every process — so a try/except alone is not enough):

        1. Under ``attention_impl: auto`` on TPU, every Pallas kernel the
           engine would compile is first probed standalone on tiny shapes
           in a SUBPROCESS with a hard timeout (ops/probe.py). Timeout or
           failure → the engine resolves to the XLA path before any
           in-process Pallas compile ever starts.
        2. If an in-process compile still fails at full shapes (probe
           passed on tiny ones), the try/except falls back to XLA. The
           donated cache/sample-state buffers may already be consumed by
           a partially-executed step, so they are re-initialized before
           the retry.
        """
        from ..ops.attention import resolve_attention_impl

        cfg = self.config.model
        if (resolve_attention_impl(cfg.attention_impl) == "pallas"
                and resolve_attention_impl("auto") == "pallas"):
            # probe EXPLICIT pallas too, not just auto: the wedge risk is
            # the first Mosaic compile on a shared-compile-service host,
            # and that risk doesn't care how the impl was selected. Only
            # the failure handling differs — auto falls back to XLA,
            # explicit refuses loudly instead of compiling in-process.
            # (resolve("auto") == "pallas" ⇔ a TPU backend — CPU runs,
            # where Mosaic can't wedge anything, skip the probe.)
            import os

            from ..ops.probe import probe_serving_kernels

            timeout_s = float(os.environ.get("DYN_PALLAS_PROBE_TIMEOUT_S", "180"))
            if not probe_serving_kernels(
                mla=cfg.kv_lora_rank > 0,
                softcap=bool(cfg.attn_logit_softcap),
                fp8_kv=self.config.kv_cache_dtype == "fp8",
                sinks=cfg.model_family == "gptoss",
                verify=bool(self.config.spec_ngram_tokens
                            or self.config.spec_draft_model),
                sp_prefill=self.config.sp_size > 1,
                epilogue=self.config.fused_epilogue != "off",
                timeout_s=timeout_s,
            ):
                if cfg.attention_impl != "auto":
                    raise RuntimeError(
                        "attention_impl='pallas' was requested explicitly "
                        "but the kernel probe failed or timed out; refusing "
                        "the in-process Mosaic compile (a hung compile "
                        "wedges this host's shared compile service). Use "
                        "attention_impl='auto' for automatic XLA fallback."
                    )
                logger.warning(
                    "pallas kernel probe failed or timed out; this engine "
                    "serves on the XLA attention path"
                )
                cfg.attention_impl = "xla"
                self._build_step()
                self._build_burst()
                self._build_spec_burst()
                # the SP prefill routes attention (ring-kernel vs
                # gather) and its sampling tail off the same impl
                self._build_sp_prefill()
                self.compiles.reset_seen()  # rebuilt programs recompile
        if (cfg.attn_logit_softcap or cfg.sliding_window) and \
                resolve_attention_impl(cfg.attention_impl) == "pallas":
            # the Pallas kernels implement softcapping and windowed masks
            # natively (the window rides as a scalar operand; windowed
            # decode walks only the window's pages) — logged AFTER the
            # probe decision so it is only ever true
            logger.info(
                "model uses %s: serving on the Pallas windowed/softcap "
                "kernel variants",
                " + ".join(
                    n for n, on in (
                        ("logit softcapping", cfg.attn_logit_softcap),
                        ("sliding-window masks", cfg.sliding_window),
                    ) if on
                ),
            )
        try:
            self._warmup_once(decode_batch)
        except Exception:
            if cfg.attention_impl != "auto":
                raise
            logger.exception(
                "pallas warmup failed; falling back to the XLA attention "
                "path for this engine"
            )
            cfg.attention_impl = "xla"
            self._build_step()
            self._build_burst()
            self._build_spec_burst()
            self._build_sp_prefill()
            self._reinit_device_state()
            self.compiles.reset_seen()  # rebuilt programs recompile
            self._warmup_once(decode_batch)

    def _reinit_device_state(self) -> None:
        """(Re)build the donated device state: the paged KV cache and the
        per-slot sampling state (generated-token counts, prompt presence,
        OpenAI logit_bias rows — [num_slots, vocab]; see engine/sampling.py).

        Called from __init__ and from the warmup fallback: a step that
        fails DURING execution (after dispatch) has already consumed the
        donated kv_cache/sample_state buffers, so the XLA retry needs
        fresh arrays. Params are never donated and survive."""
        cfg = self.config
        cache = self.arch.init_kv_cache(
            cfg.model, cfg.num_kv_blocks, cfg.kv_block_size, self.kv_dtype
        )
        if cfg.pp_size > 1:
            from ..parallel.pipeline import stage_cache

            cache = stage_cache(tuple(cache), cfg.pp_size,
                                prefix_layers=self._pp_prefix_layers)
        self.kv_cache = tuple(
            jax.device_put(c, self.cache_sharding) for c in cache
        )
        b, v = cfg.max_batch_size, cfg.model.vocab_size
        self.sample_state = (
            jax.device_put(jnp.zeros((b, v), jnp.int32), self.state_sharding),
            jax.device_put(jnp.zeros((b, v), jnp.bool_), self.state_sharding),
            jax.device_put(jnp.zeros((b, v), jnp.float32), self.state_sharding),
        )

    def _warmup_once(self, decode_batch: Optional[int] = None) -> None:
        b = decode_batch or self.config.max_batch_size
        # the sample-row install program is shape-invariant and otherwise
        # compiles at the FIRST admission — a needless late compile on
        # the first real request (flagged by the CompileTracker; writing
        # zero rows to slot 0 is inert, admission overwrites them)
        self.set_sample_row(0, [])
        zeros2 = np.zeros((b, 1), np.int32)
        for w in self.config.kv_width_buckets():
            self.step(
                zeros2, zeros2, np.zeros((b, w), np.int32),
                np.full((b, 1), -1, np.int32),
                np.ones(b, np.int32), np.zeros(b, np.int32),
                np.zeros(b, np.float32), np.zeros(b, np.int32),
                np.ones(b, np.float32),
                jax.random.PRNGKey(0),
            )
        # the fused multi-step decode program over the same width ladder
        # (inert rows: commit all-False writes nothing and samples noise)
        if self._burst is not None:
            z1 = np.zeros(b, np.int32)
            for w in self.config.kv_width_buckets():
                self.decode_burst(
                    z1, z1, np.zeros((b, w), np.int32),
                    np.zeros(b, np.float32), z1, np.ones(b, np.float32),
                    min_p=np.zeros(b, np.float32),
                    presence_penalty=np.zeros(b, np.float32),
                    frequency_penalty=np.zeros(b, np.float32),
                    repetition_penalty=np.ones(b, np.float32),
                    seed_keys=np.zeros((b, 2), np.uint32), counters=z1,
                    commit=np.zeros(b, bool), want_top=False,
                )
        # the device-finish burst variant over the same ladder (inert:
        # commit all-False, so no row writes KV or counts); compiling it
        # here keeps the persistent loop's first chain off the late-
        # compile path exactly like the plain burst above
        if getattr(self, "_burst_df", None) is not None:
            from .sampling import STOP_ID_WIDTH

            z1 = np.zeros(b, np.int32)
            for w in self.config.kv_width_buckets():
                self.decode_burst_chained(
                    z1, z1, z1, np.zeros(b, bool),
                    np.zeros((b, w), np.int32),
                    np.zeros(b, np.float32), z1, np.ones(b, np.float32),
                    min_p=np.zeros(b, np.float32),
                    presence_penalty=np.zeros(b, np.float32),
                    frequency_penalty=np.zeros(b, np.float32),
                    repetition_penalty=np.ones(b, np.float32),
                    seed_keys=np.zeros((b, 2), np.uint32),
                    commit=np.zeros(b, bool),
                    stop_ids=np.full((b, STOP_ID_WIDTH), -1, np.int32),
                    min_new=z1, max_new=np.full(b, 1, np.int32),
                    want_top=False,
                )
        # the chained propose-verify round (spec state in the burst
        # carry) over the same ladder; inert like the burst warmups
        if self._spec_ngram is not None or self._spec_verify is not None:
            from .sampling import (
                STOP_ID_WIDTH,
                STOP_SEQ_WIDTH,
                SUFFIX_RING_W,
            )

            z1 = np.zeros(b, np.int32)
            K = self._spec_k
            for w in self.config.kv_width_buckets():
                self.decode_burst_spec(
                    z1, z1, z1, np.zeros(b, bool),
                    np.full((b, SUFFIX_RING_W), -1, np.int32),
                    np.full(b, -1, np.int32),
                    np.zeros((b, w), np.int32),
                    commit=np.zeros(b, bool),
                    stop_ids=np.full((b, STOP_ID_WIDTH), -1, np.int32),
                    min_new=z1, max_new=np.full(b, 1, np.int32),
                    stop_hash=np.zeros((b, STOP_SEQ_WIDTH), np.uint32),
                    stop_hlen=np.zeros((b, STOP_SEQ_WIDTH), np.int32),
                    proposals=(
                        None if self._spec_verify is None
                        else np.full((b, K), -1, np.int32)
                    ),
                )
        # the ngram-speculative verify shape (S = K+1 on decode-width
        # tables) over the same ladder
        if self.config.spec_ngram_tokens:
            sK = self.config.spec_ngram_tokens + 1
            zs = np.zeros((b, sK), np.int32)
            for w in self.config.kv_width_buckets():
                self.step(
                    zs, zs, np.zeros((b, w), np.int32),
                    np.full((b, sK), -1, np.int32),
                    np.ones(b, np.int32), np.zeros(b, np.int32),
                    np.zeros(b, np.float32), np.zeros(b, np.int32),
                    np.ones(b, np.float32),
                    jax.random.PRNGKey(0), want_greedy=True,
                )
        # the sequence-parallel prefill program (ONE compiled shape):
        # inert dispatch — every slot is the drop sentinel, commit is
        # False — so the long-context admission class never pays its
        # multi-second compile on the first real 128k prompt
        if getattr(self, "_sp_prefill", None) is not None:
            S_sp, w_sp = self._sp_bucket, self._sp_width
            repl_tok = np.zeros((1, S_sp), np.int32)
            with self.compiles.track("prefill_sp", f"s{S_sp}_w{w_sp}"):
                outs_sp = self._sp_prefill(
                    self.params, self.kv_cache[0], self.kv_cache[1],
                    self.sample_state[0], self.sample_state[1],
                    self.sample_state[2],
                    jnp.asarray(repl_tok), jnp.asarray(repl_tok),
                    jnp.asarray(np.zeros((1, w_sp), np.int32)),
                    jnp.asarray(np.full((1, S_sp), -1, np.int32)),
                    jnp.asarray([1], jnp.int32), jnp.asarray(0, jnp.int32),
                    jnp.asarray([0], jnp.int32),
                    SamplingParams(
                        temperature=jnp.zeros(1, jnp.float32),
                        top_k=jnp.zeros(1, jnp.int32),
                        top_p=jnp.ones(1, jnp.float32),
                        min_p=jnp.zeros(1, jnp.float32),
                        presence_penalty=jnp.zeros(1, jnp.float32),
                        frequency_penalty=jnp.zeros(1, jnp.float32),
                        repetition_penalty=jnp.ones(1, jnp.float32),
                        keys=jnp.zeros((1, 2), jnp.uint32),
                        counters=jnp.zeros(1, jnp.int32),
                    ),
                    jnp.asarray([0], jnp.int32),
                    jnp.asarray([False], jnp.bool_),
                    jnp.asarray(False, jnp.bool_),
                )
            # the inert dispatch consumed the donated cache/state buffers
            # — adopt the returned ones (values unchanged: drop-sentinel
            # slots wrote nothing, commit=False counted nothing)
            self.kv_cache = (outs_sp[4], outs_sp[5])
            self.sample_state = (outs_sp[6], outs_sp[7], outs_sp[8])
        # prefill-shaped programs (largest bucket, full table width) over
        # the batched-prefill row ladder, so the flash-prefill kernel's
        # compiles also happen — and fail — here rather than on the first
        # real prompt burst
        s = self.config.prefill_buckets[-1]
        w = self.config.blocks_per_seq
        for r in self.config.prefill_row_buckets():
            self.step(
                np.zeros((r, s), np.int32), np.zeros((r, s), np.int32),
                np.zeros((r, w), np.int32), np.full((r, s), -1, np.int32),
                np.ones(r, np.int32), np.zeros(r, np.int32),
                np.zeros(r, np.float32), np.zeros(r, np.int32),
                np.ones(r, np.float32),
                jax.random.PRNGKey(0),
            )
