"""Constrained decoding: guided_choice tries + guided JSON grammars.

Two constraint families behind one cursor interface the scheduler
drives (``allowed() -> (ids, at_end)``, ``advance(token) -> verdict``):

- ``TrieConstraint`` — the completion must be exactly one of N strings;
  a token trie over their canonical tokenizations (vLLM guided_choice
  semantics; reference surface: nvext extra fields,
  lib/llm/src/protocols/openai/chat_completions.rs:38-40).
- ``JsonConstraint`` — the completion must be valid JSON
  (``response_format={"type": "json_object"}``) or validate against a
  JSON-schema subset (``json_schema``: object/required, string, number,
  integer, boolean, null, enum, array). Implemented TPU-host-side as a
  character-level pushdown machine over IMMUTABLE state tuples, so the
  token mask for a machine state is computed once — by simulating every
  vocab piece through the machine — and cached per state signature in
  the shared ``JsonGrammar``. Steady-state guided decoding therefore
  costs a dict lookup per token; only the first visit to a new parser
  state pays the O(vocab) sweep. (Same amortization idea as outlines/
  xgrammar FSM-token-mask precomputation, built here without the regex
  compilation machinery: JSON's machine is small enough to walk
  directly.)

The machine state is ``(stack, mode)``: ``stack`` a tuple of container
frames (object frames carry the schema node id, used keys, and the
pending property; array frames the items node id), ``mode`` the scalar
sub-state (value-start, in-string escape counts, number sub-grammar,
literal progress, enum-trie position). Both are small hashable tuples —
the whole point: two requests in the same parser situation share one
cached mask.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

GUIDED_END = -1  # terminal marker key inside a guided-choice trie

_WS = " \t\n\r"
_DIGITS = "0123456789"
_HEX = "0123456789abcdefABCDEF"
# number sub-states after which the number may legally end
_NUM_CAN_END = ("int0", "int", "frac", "exp")


def build_choice_trie(choice_ids: Sequence[Sequence[int]]) -> dict:
    """Token trie over the guided choices' canonical tokenizations:
    nested {token_id: child} dicts with GUIDED_END marking a complete
    choice (choices may be prefixes of one another)."""
    root: dict = {}
    for ids in choice_ids:
        node = root
        for t in ids:
            node = node.setdefault(int(t), {})
        node[GUIDED_END] = True
    return root


class TrieConstraint:
    """Cursor over a choice trie (one per request).

    ``path`` records the tokens consumed so far: the trie nodes are
    plain dicts without stable identities across rebuilds, so the path
    is the canonical cursor state the device-table compiler
    (``compile_device_table``) keys its state map on."""

    def __init__(self, choice_ids: Sequence[Sequence[int]]):
        self._choice_ids = choice_ids
        self.node: Optional[dict] = build_choice_trie(choice_ids)
        self.path: Tuple[int, ...] = ()

    def reset(self) -> None:
        """Back to the start (preemption-resume re-walks from scratch)."""
        self.node = build_choice_trie(self._choice_ids)
        self.path = ()

    def state_key(self):
        """Hashable signature of the cursor position — two equal keys
        imply identical allowed sets (the scheduler skips mask edits on
        no-change advances)."""
        return id(self.node)

    def allowed(self) -> Tuple[List[int], bool]:
        node = self.node or {}
        return [t for t in node if t != GUIDED_END], GUIDED_END in node

    def advance(self, token_id: int) -> str:
        node = (self.node or {}).get(int(token_id))
        if node is None:
            return "derail"
        self.node = node
        self.path = self.path + (int(token_id),)
        if not any(t != GUIDED_END for t in node):
            return "done"  # choice complete, no longer continuation
        return "ok"


# ---------------------------------------------------------------------------
# schema compilation
# ---------------------------------------------------------------------------

_UNSUPPORTED_KEYS = (
    "pattern", "format", "minLength", "maxLength", "minimum", "maximum",
    "exclusiveMinimum", "exclusiveMaximum", "multipleOf", "minItems",
    "maxItems", "uniqueItems", "minProperties", "maxProperties",
    "oneOf", "anyOf", "allOf", "not", "if", "then", "else", "$ref",
    "patternProperties", "additionalItems", "const",
)


def _trie_has_unused(node: dict, used) -> bool:
    """Any terminal under ``node`` naming a property not yet used?"""
    for k, v in node.items():
        if k == GUIDED_END:
            if v not in used:
                return True
        elif _trie_has_unused(v, used):
            return True
    return False


def _char_trie(words: Sequence[str]) -> dict:
    """{char: child} trie; GUIDED_END→word marks a complete word."""
    root: dict = {}
    for w in words:
        node = root
        for ch in w:
            node = node.setdefault(ch, {})
        node[GUIDED_END] = w
    return root


def compile_schema(schema) -> List[dict]:
    """JSON-schema subset → a node list (node 0 is the root).

    Every keyword we cannot ENFORCE raises ValueError — silently
    ignoring e.g. ``pattern`` would emit outputs that fail the caller's
    own validation, the one thing a guided request exists to prevent.
    Annotation keywords (title/description/default/examples) pass.
    """
    nodes: List[dict] = []

    def add(node: dict) -> int:
        nodes.append(node)
        return len(nodes) - 1

    def walk(s) -> int:
        if s is True or s == {}:
            return add({"kind": "any"})
        if not isinstance(s, dict):
            raise ValueError(f"unsupported schema {s!r}")
        for k in _UNSUPPORTED_KEYS:
            if k in s:
                raise ValueError(
                    f"json_schema keyword {k!r} is not supported by "
                    "guided decoding on this server"
                )
        if "enum" in s:
            vals = s["enum"]
            if not isinstance(vals, list) or not vals:
                raise ValueError("enum must be a non-empty list")
            for v in vals:
                if not isinstance(v, (str, int, float, bool)) and v is not None:
                    raise ValueError(
                        "enum values must be scalars (string/number/"
                        "boolean/null)"
                    )
            return add({"kind": "enum",
                        "trie": _char_trie([json.dumps(v) for v in vals])})
        t = s.get("type")
        if isinstance(t, list):
            raise ValueError("union 'type' lists are not supported")
        if t == "object" or (t is None and "properties" in s):
            props = s.get("properties")
            if props is None:
                if s.get("required"):
                    # 'required' without 'properties' cannot be enforced
                    # by the key machine — same contract as the keyword
                    # list above: never silently drop a constraint
                    raise ValueError(
                        "'required' without 'properties' is not "
                        "supported by guided decoding on this server"
                    )
                return add({"kind": "anyobj"})
            if not isinstance(props, dict) or not props:
                raise ValueError("'properties' must be a non-empty object")
            for name in props:
                # keys are walked through the trie as RAW characters —
                # names that need JSON string escaping would either emit
                # unparseable text or dead-end mid-key
                if (not isinstance(name, str) or not name
                        or any(c in '"\\' or c < " " for c in name)):
                    raise ValueError(
                        f"property name {name!r} needs JSON string "
                        "escaping, which guided decoding does not "
                        "support in schema keys"
                    )
            nid = add({})  # reserve: children may reference forward
            required = s.get("required", [])
            if not isinstance(required, list) or not set(required) <= set(props):
                raise ValueError("'required' must list property names")
            nodes[nid] = {
                "kind": "object",
                "props": {k: walk(v) for k, v in props.items()},
                "keytrie": _char_trie(list(props)),
                "required": frozenset(required),
            }
            return nid
        if t == "array":
            nid = add({})
            nodes[nid] = {"kind": "array", "items": walk(s.get("items", True))}
            return nid
        if t in ("string", "number", "integer", "boolean", "null"):
            return add({"kind": t})
        if t is None:
            return add({"kind": "any"})
        raise ValueError(f"unsupported schema type {t!r}")

    walk(schema)
    return nodes


# ---------------------------------------------------------------------------
# the character machine
# ---------------------------------------------------------------------------
#
# state = (stack, mode)
#   stack frames: ("o", node_id|None, used: tuple[str,...], pending|None)
#                 ("a", items_node_id|None)
#   modes: ("val", node_id|None)        value start (ws ok)
#          ("aval0", node_id|None)      after '[': value or ']'
#          ("post",)                    value done: ws , } ]
#          ("key0",) ("key1",)          object: expect key (key0 also })
#          ("kstr", esc, trie_id)       in key string (trie_id None = free)
#          ("colon",)                   between key and ':'
#          ("str", esc)                 in value string; esc: 0 plain,
#                                       1 after backslash, 2..5 hex left
#          ("num", ns)                  ns per _NUM sub-grammar
#          ("lit", word, i)             inside true/false/null
#          ("enum", node_id, pos)       walking an enum trie; pos = tuple
#                                       of chars consumed (trie path)
#          ("end",)                     top-level value complete


class JsonGrammar:
    """Compiled constraint shared by every request with the same spec:
    the schema nodes, the vocab piece table, and the state→mask cache."""

    def __init__(self, pieces: Sequence[Optional[str]],
                 schema: Optional[dict] = None, max_depth: int = 16):
        self.pieces = pieces
        self.max_depth = max_depth
        self.nodes = compile_schema(schema) if schema is not None else None
        self._mask_cache: Dict[tuple, List[int]] = {}

    # -- machine ----------------------------------------------------------

    # structural whitespace (between tokens of the JSON grammar) is
    # bounded per run: without a cap, greedy decoding on a weak model
    # can legally emit indentation forever and never close the value.
    # In-string whitespace is content and stays unbounded.
    MAX_WS_RUN = 3
    _WS_STRUCTURAL = frozenset(
        ("val", "objval", "aval0", "post", "key0", "key1", "colon", "end"))

    def initial(self) -> tuple:
        root = 0 if self.nodes is not None else None
        if self.nodes is None:
            # json_object: the reply must BE an object (OpenAI semantics),
            # but everything nested inside is free-form JSON
            return (((), ("objval", None)), 0)
        return (((), ("val", root)), 0)

    def step(self, state: tuple, ch: str) -> Optional[tuple]:
        """One character over the FULL state ``(core, ws_run)``; None =
        the character is illegal here (including a structural-whitespace
        run past MAX_WS_RUN)."""
        core, ws = state
        nxt = self._step_core(core, ch)
        if nxt is None:
            return None
        if ch in _WS and nxt == core and core[1][0] in self._WS_STRUCTURAL:
            return None if ws >= self.MAX_WS_RUN else (core, ws + 1)
        return (nxt, 0)

    def _node(self, nid) -> dict:
        return self.nodes[nid] if nid is not None and self.nodes else {"kind": "any"}

    def _start_value(self, stack, nid, ch, allow_close=None):
        """Dispatch a value's first character under schema node ``nid``.
        ``allow_close``: (")]"/"}" char, state-after) for aval0/key0."""
        kind = self._node(nid)["kind"] if nid is not None else "any"
        if ch in _WS:
            return None  # caller keeps the current mode for ws
        if kind == "enum":
            trie = self._node(nid)["trie"]
            if ch in trie:
                return self._enum_step(stack, nid, (ch,))
            return None
        ok_obj = kind in ("any", "object", "anyobj")
        ok_arr = kind in ("any", "array")
        ok_str = kind in ("any", "string")
        ok_num = kind in ("any", "number", "integer")
        ok_true = kind in ("any", "boolean")
        ok_null = kind in ("any", "null")
        if ch == "{" and ok_obj and len(stack) < self.max_depth:
            oid = nid if kind == "object" else None
            return (stack + (("o", oid, (), None),), ("key0",))
        if ch == "[" and ok_arr and len(stack) < self.max_depth:
            items = self._node(nid)["items"] if kind == "array" else None
            return (stack + (("a", items),), ("aval0", items))
        if ch == '"' and ok_str:
            return (stack, ("str", 0))
        if ok_num:
            is_int = kind == "integer"
            if ch == "-":
                return (stack, ("num", "sign", is_int))
            if ch == "0":
                return (stack, ("num", "int0", is_int))
            if ch in "123456789":
                return (stack, ("num", "int", is_int))
        if ch == "t" and ok_true:
            return (stack, ("lit", "true", 1))
        if ch == "f" and ok_true:
            return (stack, ("lit", "false", 1))
        if ch == "n" and ok_null:
            return (stack, ("lit", "null", 1))
        return None

    def _finish_value(self, stack) -> tuple:
        if not stack:
            return ((), ("end",))
        return (stack, ("post",))

    def _enum_step(self, stack, nid, pos) -> Optional[tuple]:
        node = self._node(nid)["trie"]
        for ch in pos:
            node = node.get(ch)
            if node is None:
                return None
        if not any(k != GUIDED_END for k in node):
            # childless terminal: the enum value is complete right here
            # (a terminal WITH children — "a" prefixing "ab" — stays
            # open; the next char or an eos resolves it)
            return self._finish_value(stack)
        return (stack, ("enum", nid, pos))

    def _step_core(self, state: tuple, ch: str) -> Optional[tuple]:
        """One character over the core ``(stack, mode)`` state; None =
        the character is illegal here."""
        stack, mode = state
        m = mode[0]

        if m == "end":
            return state if ch in _WS else None

        if m in ("val", "objval", "aval0"):
            if ch in _WS:
                return state
            if m == "aval0" and ch == "]":
                return self._finish_value(stack[:-1])
            if m == "objval":
                # top-level of json_object: the value must be an object
                if ch == "{" :
                    return (stack + (("o", None, (), None),), ("key0",))
                return None
            return self._start_value(stack, mode[1], ch)

        if m == "post":
            if ch in _WS:
                return state
            if not stack:
                return None
            top = stack[-1]
            if top[0] == "o":
                if ch == ",":
                    node = self._node(top[1])
                    if (node.get("kind") == "object"
                            and set(node["props"]) <= set(top[2])):
                        return None  # every property used: must close
                    return (stack, ("key1",))
                if ch == "}":
                    node = self._node(top[1])
                    if (node.get("kind") == "object"
                            and not node["required"] <= set(top[2])):
                        return None  # required keys still missing
                    return self._finish_value(stack[:-1])
            else:  # array
                if ch == ",":
                    return (stack, ("val", top[1]))
                if ch == "]":
                    return self._finish_value(stack[:-1])
            return None

        if m in ("key0", "key1"):
            if ch in _WS:
                return state
            top = stack[-1]
            if ch == "}" and m == "key0":
                node = self._node(top[1])
                if (node.get("kind") == "object" and node["required"]):
                    return None  # an empty object misses required keys
                return self._finish_value(stack[:-1])
            if ch == '"':
                node = self._node(top[1])
                if node.get("kind") == "object":
                    if not _trie_has_unused(node["keytrie"], top[2]):
                        return None  # no unused property left to name
                    return (stack, ("kstr", 0, ()))
                return (stack, ("kstr", 0, None))
            return None

        if m == "kstr":
            esc, pos = mode[1], mode[2]
            if pos is None:  # free-form key: full string grammar
                nxt = self._str_char(esc, ch)
                if nxt is None:
                    return None
                if nxt == "close":
                    return (stack, ("colon",))
                return (stack, ("kstr", nxt, None))
            # schema keys: plain chars walked through the property trie
            top = stack[-1]
            node = self._node(top[1])
            trie = node["keytrie"]
            cur = trie
            for c in pos:
                cur = cur[c]
            if ch == '"':
                name = cur.get(GUIDED_END)
                if name is None or name in top[2]:
                    return None  # not a property / already used
                frame = ("o", top[1], top[2], name)
                return (stack[:-1] + (frame,), ("colon",))
            if ch in cur and _trie_has_unused(cur[ch], top[2]):
                # only descend branches that still lead to an UNUSED
                # property — walking into "name" twice would dead-end at
                # the closing quote with no legal continuation
                return (stack, ("kstr", 0, pos + (ch,)))
            return None

        if m == "colon":
            if ch in _WS:
                return state
            if ch != ":":
                return None
            top = stack[-1]
            node = self._node(top[1])
            if node.get("kind") == "object":
                name = top[3]
                frame = ("o", top[1], tuple(sorted(set(top[2]) | {name})), None)
                return (stack[:-1] + (frame,), ("val", node["props"][name]))
            return (stack, ("val", None))

        if m == "str":
            nxt = self._str_char(mode[1], ch)
            if nxt is None:
                return None
            if nxt == "close":
                return self._finish_value(stack)
            return (stack, ("str", nxt))

        if m == "num":
            return self._num_char(stack, mode[1], ch, mode[2])

        if m == "lit":
            word, i = mode[1], mode[2]
            if ch != word[i]:
                return None
            if i + 1 == len(word):
                return self._finish_value(stack)
            return (stack, ("lit", word, i + 1))

        if m == "enum":
            nid, pos = mode[1], mode[2]
            trie = self._node(nid)["trie"]
            cur = trie
            for c in pos:
                cur = cur[c]
            if ch in cur:
                return self._enum_step(stack, nid, pos + (ch,))
            if GUIDED_END in cur:
                # value complete; the char belongs to the enclosing
                # context (",", "}", ws, ...)
                return self._step_core(self._finish_value(stack), ch)
            return None

        raise AssertionError(f"unknown mode {mode!r}")

    @staticmethod
    def _str_char(esc: int, ch: str):
        """String-body char: returns the next esc sub-state, "close", or
        None. esc: 0 plain, 1 after backslash, 2..5 = hex digits left."""
        if esc == 0:
            if ch == '"':
                return "close"
            if ch == "\\":
                return 1
            if "\x00" <= ch <= "\x1f":
                return None  # control chars must be escaped
            return 0
        if esc == 1:
            if ch == "u":
                return 5
            if ch in '"\\/bfnrt':
                return 0
            return None
        if ch in _HEX:
            return 0 if esc == 2 else esc - 1
        return None

    _NUM_TABLE = {
        "sign": {"0": "int0", **{d: "int" for d in "123456789"}},
        "int0": {".": "dot", "e": "e", "E": "e"},
        "int": {**{d: "int" for d in _DIGITS}, ".": "dot",
                "e": "e", "E": "e"},
        "dot": {d: "frac" for d in _DIGITS},
        "frac": {**{d: "frac" for d in _DIGITS}, "e": "e", "E": "e"},
        "e": {"+": "esign", "-": "esign", **{d: "exp" for d in _DIGITS}},
        "esign": {d: "exp" for d in _DIGITS},
        "exp": {d: "exp" for d in _DIGITS},
    }

    def _num_char(self, stack, ns: str, ch: str,
                  is_int: bool) -> Optional[tuple]:
        nxt = self._NUM_TABLE[ns].get(ch)
        if nxt is not None:
            if is_int and nxt in ("dot", "e"):
                return None  # integer schema: no fraction, no exponent
            return (stack, ("num", nxt, is_int))
        if ns in _NUM_CAN_END:
            # the number ends before this char; reprocess it one level up
            return self._step_core(self._finish_value(stack), ch)
        return None

    # -- token masks -------------------------------------------------------

    def run_piece(self, state: tuple, piece: str) -> Optional[tuple]:
        for ch in piece:
            state = self.step(state, ch)
            if state is None:
                return None
        return state

    def allowed_tokens(self, state: tuple) -> List[int]:
        """Token ids whose full piece string is legal from ``state``.
        Cached per state: two requests in the same parser situation —
        or one request revisiting it (e.g. successive string-body
        tokens) — share the sweep."""
        cached = self._mask_cache.get(state)
        if cached is not None:
            return cached
        out = []
        for tid, piece in enumerate(self.pieces):
            if not piece or "�" in piece:
                continue  # specials / partial-UTF8 byte tokens
            if self.run_piece(state, piece) is not None:
                out.append(tid)
        self._mask_cache[state] = out
        return out

    def at_end(self, state: tuple) -> bool:
        (stack, mode), _ws = state
        if mode[0] == "end":
            return True
        # a top-level number (or an enum at a terminal that prefixes a
        # longer value) can only terminate on eos: there is no closing
        # delimiter to advance the machine
        if not stack and mode[0] == "num" and mode[1] in _NUM_CAN_END:
            return True
        if not stack and mode[0] == "enum":
            cur = self._node(mode[1])["trie"]
            for c in mode[2]:
                cur = cur[c]
            return GUIDED_END in cur
        return False


class JsonConstraint:
    """Per-request cursor over a shared JsonGrammar."""

    def __init__(self, grammar: JsonGrammar):
        self.grammar = grammar
        self.state = grammar.initial()

    def reset(self) -> None:
        """Back to the start (preemption-resume re-walks from scratch)."""
        self.state = self.grammar.initial()

    def state_key(self):
        """Hashable signature of the machine state — equal keys imply
        identical allowed sets. String-body tokens typically leave the
        state unchanged, so guided-JSON steady state skips the per-token
        mask edit entirely (the module docstring's O(1) claim)."""
        return self.state

    def allowed(self) -> Tuple[List[int], bool]:
        return (self.grammar.allowed_tokens(self.state),
                self.grammar.at_end(self.state))

    def advance(self, token_id: int) -> str:
        pieces = self.grammar.pieces
        piece = pieces[token_id] if 0 <= token_id < len(pieces) else None
        if not piece:
            return "derail"
        nxt = self.grammar.run_piece(self.state, piece)
        if nxt is None:
            return "derail"
        self.state = nxt
        return "done" if nxt[0][1][0] == "end" else "ok"


# ---------------------------------------------------------------------------
# device transition tables (the guided mask inside the burst carry)
# ---------------------------------------------------------------------------
#
# The persistent decode chain (scheduler._decode_chained) cannot pay a
# host mask edit per token, so a BOUNDED constraint compiles to a dense
# device table ``state × token → next state`` (-1 = reject): the burst
# program computes the additive mask from the current state's row and
# advances the per-row grammar-state carry on the sampled token, all
# inside the scan. State 0 is the reserved DONE terminal — transitioning
# into it means the constraint completed (the host's ``advance`` verdict
# "done"), and eos ids map to it at every legal-end state. Grammars
# whose reachable state set exceeds the bound (free-form guided_json,
# deep schemas) return None and keep the host sync path EXPLICITLY —
# the scheduler counts the fallback, never silently downgrades.


class DeviceGuidedTable:
    """Compiled device transition table + the host-state → id map."""

    DONE = 0  # reserved terminal state id

    def __init__(self, table, state_ids, kind: str):
        import numpy as _np

        self.table = _np.asarray(table, _np.int32)  # [S, V]
        self.state_ids = state_ids                  # host key → state id
        self.kind = kind                            # "trie" | "json"
        self.n_states = self.table.shape[0]
        self._dev = {}                              # bucket → device array

    def state_id(self, constraint) -> Optional[int]:
        """Table id of a live cursor's CURRENT state (None = unmapped —
        the cursor wandered somewhere the BFS never reached, which only
        a bug can produce; the scheduler falls back loudly)."""
        key = (constraint.path if isinstance(constraint, TrieConstraint)
               else constraint.state)
        return self.state_ids.get(key)

    def device(self, bucket: int):
        """The table as a device array padded to ``bucket`` states
        (rows of -1) — padding buckets bound the number of compiled
        burst programs. Cached per bucket: the H2D upload happens once
        per chain, not per dispatch."""
        dev = self._dev.get(bucket)
        if dev is None:
            import jax.numpy as jnp
            import numpy as _np

            padded = _np.full((bucket, self.table.shape[1]), -1, _np.int32)
            padded[: self.n_states] = self.table
            dev = jnp.asarray(padded)
            self._dev[bucket] = dev
        return dev


def compile_device_table(
    constraint,
    vocab_size: int,
    eos_ids: Sequence[int] = (),
    max_states: int = 256,
    budget_s: float = 2.0,
) -> Optional[DeviceGuidedTable]:
    """BFS the constraint's reachable states into a device table.

    Works on a FRESH walk of the constraint's definition (the live
    cursor is never touched). Returns None when the state set exceeds
    ``max_states`` or the sweep exceeds ``budget_s`` — the caller keeps
    the request on the host sync path and names the reason. Runs on an
    executor thread (the per-state vocab sweep is the same O(vocab)
    work JsonGrammar.allowed_tokens amortizes; dynlint pins the
    scheduler against running it on the event loop).
    """
    import time as _time

    import numpy as np

    eos = [int(e) for e in (eos_ids or []) if 0 <= int(e) < vocab_size]
    deadline = _time.monotonic() + budget_s

    if isinstance(constraint, TrieConstraint):
        root = build_choice_trie(constraint._choice_ids)

        def key_of(path):
            return tuple(path)

        def node_at(path):
            node = root
            for t in path:
                node = node[t]
            return node

        def expand(path):
            node = node_at(path)
            ids = [t for t in node if t != GUIDED_END and 0 <= t < vocab_size]
            at_end = GUIDED_END in node
            out = []
            for t in ids:
                child = node[t]
                done = not any(k != GUIDED_END for k in child)
                out.append((t, None if done else path + (t,)))
            return out, at_end

        start_key = ()
    elif isinstance(constraint, JsonConstraint):
        grammar = constraint.grammar

        def key_of(state):
            return state

        def expand(state):
            ids = [t for t in grammar.allowed_tokens(state)
                   if 0 <= t < vocab_size]
            at_end = grammar.at_end(state)
            out = []
            for t in ids:
                nxt = grammar.run_piece(state, grammar.pieces[t])
                done = nxt[0][1][0] == "end"
                out.append((t, None if done else nxt))
            return out, at_end

        start_key = grammar.initial()
    else:
        return None

    # state 0 = DONE; real states from 1
    state_ids: Dict[tuple, int] = {start_key: 1}
    rows: List[Optional[List[Tuple[int, Optional[tuple]]]]] = [None, None]
    at_ends: List[bool] = [False, False]
    queue = [start_key]
    while queue:
        if _time.monotonic() > deadline:
            return None
        key = queue.pop(0)
        sid = state_ids[key]
        trans, at_end = expand(key)
        rows[sid] = trans
        at_ends[sid] = at_end
        for _t, nxt_key in trans:
            if nxt_key is None or nxt_key in state_ids:
                continue
            if len(state_ids) + 1 > max_states:
                return None
            state_ids[nxt_key] = len(state_ids) + 1
            rows.append(None)
            at_ends.append(False)
            queue.append(nxt_key)

    n = len(state_ids) + 1
    table = np.full((n, vocab_size), -1, np.int32)
    for key, sid in state_ids.items():
        for t, nxt_key in rows[sid]:
            table[sid, t] = (
                DeviceGuidedTable.DONE if nxt_key is None
                else state_ids[nxt_key]
            )
        if at_ends[sid]:
            for e in eos:
                table[sid, e] = DeviceGuidedTable.DONE
    return DeviceGuidedTable(table, dict(state_ids), (
        "trie" if isinstance(constraint, TrieConstraint) else "json"
    ))


def build_piece_table(tokenizer, vocab_size: int) -> List[Optional[str]]:
    """The exact text each token id appends to a decode stream.

    ``decode([id])`` alone can drop a leading space (decoder cleanup is
    applied at sequence start), so the piece is recovered from the
    SECOND occurrence in ``decode([id, id])`` — mid-sequence rendering
    is what concatenative masking must model. Specials decode to ""
    (skip_special_tokens) → None → banned from every mask; partial-UTF8
    byte tokens carry U+FFFD and are banned by the grammar sweep.
    """
    pieces: List[Optional[str]] = [None] * vocab_size
    tv = tokenizer.vocab_size
    n = min(vocab_size, tv() if callable(tv) else tv)
    for i in range(n):
        try:
            p1 = tokenizer.decode([i])
        except Exception:
            continue
        if not p1:
            continue
        try:
            p2 = tokenizer.decode([i, i])
        except Exception:
            pieces[i] = p1
            continue
        pieces[i] = p2[len(p1):] if p2 != p1 + p1 and len(p2) > len(p1) else p1
    return pieces
