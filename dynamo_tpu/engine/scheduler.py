"""Continuous-batching scheduler: the engine's beating heart.

An asyncio loop interleaving bucketed prefills with batched decode steps
over a fixed set of slots (static shapes → no recompiles as membership
changes). Per-request state tracks paged blocks, chained block hashes (for
prefix cache + KV events), and cooperative cancellation.

The reference outsourced all of this to vLLM/SGLang (SURVEY.md §7
"the JAX serving engine itself" is hard-part #1) — this is the native
replacement: admission → prefill (prefix-cache aware) → decode loop →
finish/free, with ForwardPassMetrics-style telemetry for the KV router.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..protocols.common import (
    EngineOutput,
    FinishReason,
    PreprocessedRequest,
    TokenLogprob,
)
from ..runtime.engine import AsyncEngineContext
from ..telemetry.flight import FlightRecorder, flight_recorder
from ..telemetry.registry import STEP_BUCKETS, MetricsRegistry
from ..tokens import TokenSequence
from ..utils import faults
from .block_allocator import BlockAllocator, KvEventSink
from .config import EngineConfig
from .model_runner import ModelRunner
from .sampling import (
    STOP_ID_WIDTH,
    STOP_SEQ_WIDTH,
    SUFFIX_RING_W,
    host_row,
    ring_init,
    seed_to_key,
    stop_id_row,
    stop_seq_rows,
)

logger = logging.getLogger(__name__)


# constrained decoding lives in engine/guided.py; re-exported here for
# callers/tests that import the trie primitives from the scheduler
from .guided import (  # noqa: F401,E402
    GUIDED_END,
    TrieConstraint,
    build_choice_trie,
    compile_device_table,
)


def ngram_propose(history: List[int], match: int, k: int) -> List[int]:
    """Prompt-lookup proposal: find the most recent earlier occurrence of
    the trailing ``match``-gram in the sequence's own history and return
    up to ``k`` tokens that followed it. Reference analog: the ngram
    speculative decoding of the engines the reference delegates to."""
    n = len(history)
    if n < match + 1 or k <= 0:
        return []
    tail = np.asarray(history[-match:], np.int64)
    h = np.asarray(history, np.int64)
    # windows over h[:-1]: every start i has at least one continuation
    # token, and the trailing gram itself (start n-match) is excluded
    win = np.lib.stride_tricks.sliding_window_view(h[:-1], match)
    hits = np.nonzero((win == tail).all(axis=1))[0]
    if hits.size == 0:
        return []
    # latest match whose continuation is full-length; else the earliest
    # (longest) one — a repetitive tail would otherwise propose almost
    # nothing because the most recent occurrence abuts the history end
    full = hits[hits + match + k <= n]
    i = int(full[-1]) if full.size else int(hits[0])
    return [int(t) for t in history[i + match: i + match + k]]


def prefill_bucket_cap(cfg: EngineConfig, rows: int = 1) -> Optional[int]:
    """Largest prefill bucket such that ``rows * bucket`` fits the
    per-step token budget (the ITL bound counts padded positions, so the
    cap is on the padded product). None when even the smallest bucket
    overruns — the caller sheds rows (the scheduler) or floors at the
    smallest bucket (the prefill worker: one chunk must still advance or
    prefill livelocks). No budget = no cap.

    Shared by the scheduler's local chunked prefill and the disagg
    prefill worker's streamed chunking — both sides MUST derive the same
    ladder or remote chunk shapes drift from local ones.
    """
    budget = cfg.max_prefill_tokens_per_step
    if not budget:
        return cfg.prefill_buckets[-1]
    allowed = [b for b in cfg.prefill_buckets if rows * b <= budget]
    return allowed[-1] if allowed else None


def build_prefill_arrays(cfg: EngineConfig, prompt: List[int], num_cached: int,
                         block_ids: List[int], bucket: Optional[int] = None):
    """Batch-of-1 arrays for one bucketed prefill step.

    Shared by the scheduler's local prefill and the disagg prefill worker.
    Returns (tokens, positions, block_tables, slot_mapping, context_lens,
    last_idx) — the leading arguments of ``ModelRunner.step``. Pass
    ``bucket`` to pad to a caller-chosen bucket (the batched prefill path
    pads every row to the batch's common bucket).
    """
    suffix = prompt[num_cached:]
    bucket = bucket or cfg.bucket_for(len(suffix))
    w = cfg.blocks_per_seq
    bs = cfg.kv_block_size

    tokens = np.zeros((1, bucket), np.int32)
    tokens[0, : len(suffix)] = suffix
    positions = np.full((1, bucket), num_cached + len(suffix) - 1, np.int32)
    positions[0, : len(suffix)] = np.arange(num_cached, len(prompt))
    slot_map = np.full((1, bucket), -1, np.int32)
    for i, pos in enumerate(range(num_cached, len(prompt))):
        slot_map[0, i] = block_ids[pos // bs] * bs + pos % bs
    btab = np.zeros((1, w), np.int32)
    btab[0, : len(block_ids)] = block_ids
    ctx_lens = np.asarray([len(prompt)], np.int32)
    last_idx = np.asarray([len(suffix) - 1], np.int32)
    return tokens, positions, btab, slot_map, ctx_lens, last_idx


@dataclasses.dataclass
class EngineRequest:
    request_id: str
    prompt: List[int]
    req: PreprocessedRequest
    ctx: AsyncEngineContext
    out_queue: asyncio.Queue
    # sampling scalars (one slot row each; see engine/sampling.py)
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    min_p: float = 0.0
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    repetition_penalty: float = 1.0
    base_key: Optional[np.ndarray] = None  # uint32[2] per-request PRNG key
    want_logprobs: bool = False
    logprobs_n: int = 0  # alternatives per token (OpenAI top_logprobs)
    # OutputOptions.prompt_logprobs: logprob of every prompt token given
    # its prefix, computed during prefill (device rows collected per
    # chunk, converted once on the final chunk)
    want_prompt_lps: bool = False
    prompt_lp_parts: List = dataclasses.field(default_factory=list)
    # sent once with the first output — a preempted request's re-prefill
    # must not recompute or re-emit them mid-stream
    prompt_lps_emitted: bool = False
    # runtime state
    slot: int = -1
    block_ids: List[int] = dataclasses.field(default_factory=list)
    num_cached: int = 0
    context_len: int = 0          # tokens whose KV is (being) written
    pending_token: int = -1       # sampled but KV not yet written
    generated: int = 0
    seq: Optional[TokenSequence] = None
    registered_blocks: int = 0
    finish: Optional[FinishReason] = None
    # chunked-prefill progress (tokens of prefill_tokens with KV written)
    prefill_tokens: List[int] = dataclasses.field(default_factory=list)
    prefill_pos: int = 0
    # preemption-resume: generated tokens already emitted before preemption;
    # re-prefilled (prompt + resume_tokens) so the stream CONTINUES
    resume_tokens: List[int] = dataclasses.field(default_factory=list)
    # guided decoding: the constraint cursor (TrieConstraint for
    # guided_choice — built at admission; JsonConstraint for guided_json
    # — attached by serving.generate, which owns the grammar cache) and
    # the token ids its mask currently allows (for sparse bias edits)
    guided: Optional[object] = None
    guided_allowed: List[int] = dataclasses.field(default_factory=list)
    # disaggregated prefill state
    remote_future: Optional[asyncio.Future] = None
    remote_deadline: float = 0.0
    remote_attempted: bool = False
    # cluster-KV-fabric prefix pull (kv/fabric.py): the in-flight pull
    # (a _PendingPull while queued in scheduler.pending_pull), whether a
    # pull was already tried (one attempt per request — the fallback
    # must not loop), and whether a committed pull pre-allocated this
    # request's blocks (``_start_prefill`` then skips allocation)
    pull: Optional[object] = None
    pull_attempted: bool = False
    pull_ready: bool = False
    # monotonic deadline before which the pull plan is not re-run for
    # this request (a no-plan outcome is sticky on the ~1 ms loop
    # cadence — the ownership view only changes on peer-event cadence)
    pull_backoff_until: float = 0.0
    # held out of LOCAL admission while another request's in-flight
    # pull fetches (part of) this prompt's prefix — cleared early by
    # that pull's commit/fallback, bounded by its deadline
    pull_hold_until: float = 0.0
    # monotonic deadline before which the remote-eligibility probe is not
    # re-run (set when a prefix-hit rejection made it pointless for a while;
    # time-based — the scheduler loop can spin every ~1 ms)
    remote_backoff_until: float = 0.0
    # telemetry: monotonic time of the last token emission (0 = none yet);
    # drives the inter-token-latency histogram and the first_token span
    last_emit_t: float = 0.0
    # dispatch-ahead decode emitted tokens for this request since the
    # last trace mark — a ``decode_pipeline`` stage is stamped when the
    # pipelined segment ends (finish or drain), so span attribution
    # separates overlapped decode from the synchronous tail
    pipeline_span_open: bool = False
    # device-resident finish detection: the admission-time classification
    # (hoisted out of the per-token hot path — _check_finish consults
    # these precomputed sets instead of re-deriving eos/stop lists every
    # token) plus the packed device stop-id row for the chained burst.
    # ``device_checkable`` means every finish condition is expressible
    # on device: eos/hidden-stop/max-tokens within STOP_ID_WIDTH, and
    # stop STRINGS only via their canonical token sequences within the
    # suffix-ring bounds (the device-approximate path). ``chain_fallback``
    # names WHY a request is not checkable so the scheduler's
    # sync-fallback counter attributes every sync pass. Guided decoding
    # is checked live at dispatch (the constraint attaches after
    # admission and its device table compiles in an executor).
    device_checkable: bool = False
    chain_fallback: Optional[str] = None
    device_frozen: bool = False  # finish came from the device mask
    fin_eos: frozenset = dataclasses.field(default_factory=frozenset)
    fin_stop: frozenset = dataclasses.field(default_factory=frozenset)
    fin_min_new: int = 0
    fin_max_new: int = 16384
    fin_stop_row: Optional[np.ndarray] = None
    # canonical stop-string token sequences (host-exact check in
    # _check_finish on EVERY path) + their packed device hash rows
    fin_stop_seqs: tuple = ()
    fin_stop_hash: Optional[np.ndarray] = None
    fin_stop_hlen: Optional[np.ndarray] = None
    # trailing emitted tokens (prompt + generated, ending with the
    # pending token): the host mirror of the burst carry's suffix ring —
    # feeds the exact stop-seq check and the chain-fill ring
    ring_tail: deque = dataclasses.field(
        default_factory=lambda: deque(maxlen=SUFFIX_RING_W)
    )
    # chain-transient flags: the guided bias row was reset to
    # logit_bias-only for a device-table chain (reinstalled at barrier),
    # and the row froze on a suffix-hash FALSE positive (resumes at the
    # barrier; gates the drain's pad handling meanwhile)
    chain_bias_reset: bool = False
    chain_fp: bool = False
    # memoized guided-table cache key (the trie key is a tuple over
    # every choice's token ids — too heavy to rebuild twice per pass)
    guided_key: Optional[tuple] = None

    def __post_init__(self):
        self.classify_finish()

    def classify_finish(self) -> None:
        """Precompute the finish-check state once per request."""
        sc = self.req.stop_conditions
        so = self.req.sampling_options
        self.fin_min_new = self.min_new
        self.fin_max_new = self.max_new
        self.fin_eos = (
            frozenset() if sc.ignore_eos
            else frozenset(int(t) for t in (self.req.eos_token_ids or []))
        )
        self.fin_stop = frozenset(
            int(t) for t in (sc.stop_token_ids_hidden or [])
        )
        row = stop_id_row(
            self.req.eos_token_ids, sc.stop_token_ids_hidden, sc.ignore_eos
        )
        n = so.n
        self.fin_stop_row = row
        self.fin_stop_seqs = ()
        self.fin_stop_hash = None
        self.fin_stop_hlen = None
        reason = None
        if row is None:
            reason = "stop_ids_overflow"
        elif n is not None and n > 1:
            # serving fans n>1 into independent n=1 children; a direct
            # multi-choice request stays on the host path defensively
            reason = "n_gt_1"
        if sc.stop:
            seqs = [
                tuple(int(t) for t in s)
                for s in (getattr(sc, "stop_token_seqs", None) or [])
                if s
            ]
            if seqs and len(seqs) == len(sc.stop):
                # host-exact stop-seq finish applies on EVERY path (sync
                # and chained stay byte-identical); the packed hash rows
                # are the device approximation's inputs
                self.fin_stop_seqs = tuple(seqs)
                packed = stop_seq_rows(seqs)
                if packed is not None:
                    self.fin_stop_hash, self.fin_stop_hlen = packed
                elif reason is None:
                    reason = "stop_seqs_overflow"
            elif reason is None:
                # no canonical tokenizations shipped (direct engine API
                # callers): text-level stops stay a host/backend concern
                reason = "stop_seqs_unavailable"
        self.chain_fallback = reason
        self.device_checkable = reason is None

    @property
    def max_new(self) -> int:
        # `is None`, not falsy: an explicit 0 means an empty completion —
        # the serving layer fast-paths it, but the invariant lives HERE
        mt = self.req.stop_conditions.max_tokens
        return 16384 if mt is None else mt

    @property
    def min_new(self) -> int:
        return self.req.stop_conditions.min_tokens or 0


class _HostBatchState:
    """Persistent ``(B, ·)`` host-side decode arrays.

    The decode hot loop used to rebuild every sampling array (temp/
    top_k/top_p/min_p/pres/freq/rep/keys) and the block table from
    per-request Python loops on EVERY pass, even when batch membership
    was unchanged — O(B·blocks_per_seq) of pure host overhead per
    dispatch. These arrays now persist across passes and mutate only
    when a slot's occupant changes (``install``) or a live row grows
    blocks (``sync_blocks``). Rows of departed requests keep stale
    values: they ride with ``commit=False``, so nothing reads their
    outputs and the device never counts their samples.
    """

    def __init__(self, cfg: EngineConfig):
        b = cfg.max_batch_size
        self.temp = np.zeros(b, np.float32)
        self.top_k = np.zeros(b, np.int32)
        self.top_p = np.ones(b, np.float32)
        self.min_p = np.zeros(b, np.float32)
        self.pres = np.zeros(b, np.float32)
        self.freq = np.zeros(b, np.float32)
        self.rep = np.ones(b, np.float32)
        self.keys = np.zeros((b, 2), np.uint32)
        self.btab = np.zeros((b, cfg.blocks_per_seq), np.int32)
        # blocks of each row already mirrored into ``btab``
        self.synced_blocks = np.zeros(b, np.int32)
        # device-finish state (membership-static, consumed by the chained
        # burst): packed stop-token ids, the min/max token bounds, and
        # the stop-string suffix-hash targets
        self.stop_ids = np.full((b, STOP_ID_WIDTH), -1, np.int32)
        self.min_new = np.zeros(b, np.int32)
        self.max_new = np.full(b, np.iinfo(np.int32).max, np.int32)
        self.stop_hash = np.zeros((b, STOP_SEQ_WIDTH), np.uint32)
        self.stop_hlen = np.zeros((b, STOP_SEQ_WIDTH), np.int32)

    def install(self, er: "EngineRequest") -> None:
        """(Re)write one slot's rows at admission / membership change."""
        i = er.slot
        (self.temp[i], self.top_k[i], self.top_p[i], self.min_p[i],
         self.pres[i], self.freq[i], self.rep[i]) = (
            er.temperature, er.top_k, er.top_p, er.min_p,
            er.presence_penalty, er.frequency_penalty,
            er.repetition_penalty,
        )
        self.keys[i] = er.base_key
        self.min_new[i] = er.fin_min_new
        self.max_new[i] = min(er.fin_max_new, np.iinfo(np.int32).max)
        self.stop_ids[i] = (
            er.fin_stop_row if er.fin_stop_row is not None else -1
        )
        self.stop_hash[i] = (
            er.fin_stop_hash if er.fin_stop_hash is not None else 0
        )
        self.stop_hlen[i] = (
            er.fin_stop_hlen if er.fin_stop_hlen is not None else 0
        )
        n = len(er.block_ids)
        self.btab[i, :n] = er.block_ids
        self.btab[i, n:] = 0
        self.synced_blocks[i] = n

    def sync_blocks(self, er: "EngineRequest") -> None:
        """Mirror a live row's grown (or rolled-back) block list."""
        i = er.slot
        n = len(er.block_ids)
        s = int(self.synced_blocks[i])
        if n == s:
            return
        if n < s:
            self.btab[i, n:s] = 0
        else:
            self.btab[i, s:n] = er.block_ids[s:]
        self.synced_blocks[i] = n


@dataclasses.dataclass
class _SpPrefill:
    """The in-flight sequence-parallel prefill: one oversized prompt
    advancing a mesh-wide chunk per scheduler pass. Chunks are
    dispatch-only (no host sync); the final chunk's outputs — and the
    early decode burst chained off its device-resident sampled token —
    reconcile together in ``_sp_finish``."""

    er: EngineRequest
    t0: float                       # ladder start (monotonic)
    chunks: int = 0
    final_dispatch_t: float = 0.0


@dataclasses.dataclass
class _PendingPull:
    """One in-flight prefix pull (scheduler.pending_pull entry).

    The request already holds its full prompt allocation; ``targets``
    (the pull destination blocks) are PINNED for the duration so
    nothing reclaims a slot with a scatter in flight. The scheduler
    owns both ends: pin at submit, unpin at reap — commit, fallback,
    cancel, and drain all funnel through the reap path."""

    plan: object                    # kv.fabric.PullPlan
    task: asyncio.Task              # the fabric.pull coroutine
    targets: List[int]              # destination block ids (pinned)
    hashes: List[int]               # the prompt's full hash chain
    deadline: float                 # monotonic fallback deadline


@dataclasses.dataclass
class _InflightBurst:
    """One dispatched-but-unreconciled decode burst (pipeline depth 2).

    Everything the host needs to reconcile the burst AFTER the next one
    is already on device: the device-resident output arrays (synced in
    one executor hop — the loop's only host sync) and the carry
    (``last_tokens``) the next burst consumes without a host round-trip.
    """

    active: List["EngineRequest"]  # rows committed at dispatch
    toks: object                   # device [K, B] sampled tokens
    lps: object                    # device [K, B] their logprobs
    tv: object                     # device [K, B, KW] top alternatives
    ti: object
    k_steps: int
    last_tokens: object            # device [B]: the next burst's tokens0
    # chained (device-finish) bursts: dispatch timestamp for the
    # drain-lag histogram, and the flag that switches _apply_burst to
    # frozen-row semantics (-1 pads skipped, device-finish counted)
    dispatch_t: float = 0.0
    device_finish: bool = False
    # device-time accounting (telemetry/device_time.py): HBM bytes this
    # burst must stream and the tokens it samples, fixed at dispatch
    read_bytes: float = 0.0
    tokens: int = 0
    # chained propose-verify round (scheduler._decode_chained_spec):
    # [S, B] outputs with -1 pads past acceptance, plus the per-row
    # proposed/accepted counts for the acceptance-length histogram
    spec: bool = False
    nprop: object = None           # device [B] proposal counts
    nacc: object = None            # device [B] accepted-token counts


class Scheduler:
    def __init__(
        self,
        runner: ModelRunner,
        config: EngineConfig,
        events: Optional[KvEventSink] = None,
        disagg=None,  # Optional[RemotePrefillCoordinator]
        draft_runner: Optional[ModelRunner] = None,
        registry: Optional[MetricsRegistry] = None,
        flight: Optional[FlightRecorder] = None,
    ):
        self.runner = runner
        self.config = config
        self.disagg = disagg
        # flight recorder: the process-wide engine-event ring every layer
        # records into (telemetry/flight.py); injectable for tests
        self.flight = flight if flight is not None else flight_recorder()
        # draft-model speculation: the draft's paged cache mirrors the
        # target's block ids — every prefill chunk replays on the draft,
        # and the decode loop proposes with the draft's K-step burst
        self.draft = draft_runner
        # shared metrics registry: the scheduler's, the allocator's, and
        # (attached below) the disagg coordinator's instruments all render
        # in the frontend's single /metrics exposition
        self.registry = registry or MetricsRegistry()
        sink = events or KvEventSink()
        tier2 = None
        if config.host_kv_blocks > 0:
            from ..kv import KvHostTier

            # device-array gather: offload staging keeps the D2H copy
            # asynchronous (host_tier.drain materializes later)
            tier2 = KvHostTier(
                runner.gather_blocks_device, runner.scatter_blocks,
                config.host_kv_blocks,
            )
        cold = None
        if config.cold_tier_blocks > 0:
            from ..kv import KvColdTier

            # content-addressed spill tier: host-tier-evicted blocks
            # survive to disk; residency is advertised through the cold
            # event hooks so routers can score rehydratable prefixes
            cold = KvColdTier(
                config.cold_tier_dir, config.cold_tier_blocks,
                registry=self.registry,
                on_stored=lambda hashes, parent: sink.on_stored_cold(
                    hashes, parent),
                on_removed=lambda hashes: sink.on_removed_cold(hashes),
            )
            tier2.on_evict = cold.offer
        self.allocator = BlockAllocator(
            config.num_kv_blocks, config.kv_block_size,
            config.enable_prefix_caching, sink, tier2=tier2,
            registry=self.registry, flight=self.flight,
        )
        # cluster KV fabric (kv/fabric.py): cross-worker prefix pull +
        # cold-tier rehydration. Built whenever either capability is
        # configured; the CLI/discovery layer attaches the peer view
        # (event feed + pull-server descriptors) onto scheduler.fabric.
        self.fabric = None
        if (config.prefix_pull or cold is not None) \
                and config.enable_prefix_caching:
            from ..kv import KvFabric

            self.fabric = KvFabric(
                runner, self.allocator,
                engine_id=f"eng-{id(self):x}",
                block_size=config.kv_block_size,
                cold=cold,
                peer_pull=config.prefix_pull,
                min_pull_blocks=config.prefix_pull_min_blocks,
                pull_timeout_s=config.prefix_pull_timeout_s,
                registry=self.registry,
                flight=self.flight,
            )
        self.pending_pull: List[EngineRequest] = []
        # sequence-parallel long-context prefill (config.sp_size > 1,
        # docs/long_context.md): oversized prompts admitted past the
        # long_prefill_threshold_tokens class queue here and advance one
        # SP chunk per loop pass — one prompt owns the mesh at a time
        # (the program is batch-of-1 by construction)
        self.sp_queue: List[EngineRequest] = []
        self.sp_active: Optional[_SpPrefill] = None
        self.waiting: deque = deque()
        # persistent decode-step host arrays (see _HostBatchState)
        self._host = _HostBatchState(config)
        self.pending_remote: List[EngineRequest] = []
        self.slots: List[Optional[EngineRequest]] = [None] * config.max_batch_size
        # the prefill BATCH: up to max_prefill_batch requests whose
        # chunked prefills run as rows of one step
        self.prefilling: List[EngineRequest] = []
        self.wake = asyncio.Event()
        self._rng = np.random.default_rng(config.seed)
        self._task: Optional[asyncio.Task] = None
        self._stopping = False
        # drain gate (recovery/): True stops ALL admission — local slot
        # claims, remote-prefill submits — while committed work proceeds;
        # exported in metrics() so the KV router skips this worker
        self.draining = False
        # telemetry (ForwardPassMetrics analog, SURVEY.md §2.2 KV metrics)
        self.prefix_hit_tokens = 0
        self.prefix_total_tokens = 0
        self.steps = 0
        # ngram speculative decoding acceptance telemetry
        self.spec_proposed = 0
        self.spec_accepted = 0
        # dispatch-ahead decode pipeline (config.decode_pipeline_depth=2):
        # the burst dispatched but not yet reconciled on the host, the
        # device-idle bookkeeping behind the bubble histogram, and a
        # dispatch counter for tests/metrics
        self._inflight: Optional[_InflightBurst] = None
        self._last_burst_done_t: Optional[float] = None
        self.pipeline_bursts = 0
        # persistent decode loop (config.device_finish): chained bursts
        # dispatched off the device-resident carry, reconciled by the
        # async row drain. Membership is FIXED for a chain's lifetime
        # (finished rows freeze on device); it compacts only at the
        # chain barrier (admission, preemption, KV-OOM, drain, stop).
        self._chain: deque = deque()   # _InflightBurst FIFO awaiting drain
        self._chain_members: List[EngineRequest] = []
        # device (tokens, pos, gen, done, ring, gstate)
        self._chain_carry = None
        self._chain_dispatched = 0     # bursts since the chain started
        self._chain_pos0: Dict[int, int] = {}  # slot → context at start
        self._last_chain_len = 0
        # which program family the open chain runs: None (closed),
        # "plain" (decode_burst_chained) or "spec" (propose-verify
        # rounds) — switching kinds forces the barrier first
        self._chain_kind: Optional[str] = None
        # a suffix-hash stop candidate the host could not confirm (hash
        # collision): the chain closes at the next pass and the row
        # resumes byte-identically
        self._chain_fp = False
        # compiled guided device tables, shared across requests with the
        # same grammar: key → DeviceGuidedTable (None = exceeded the
        # state bound; sync path keeps the request, counted). In-flight
        # executor compiles in _guided_table_futs.
        self._guided_tables: Dict[tuple, object] = {}
        self._guided_table_futs: Dict[tuple, object] = {}
        # watchdog heartbeat: stamped at the top of EVERY loop pass, so a
        # loop wedged INSIDE a pass (hung compile, dead device sync) goes
        # stale while a healthy-but-waiting loop stays fresh
        self.last_loop_t = time.monotonic()
        self._build_instruments()
        if disagg is not None and getattr(disagg, "registry", None) is not None:
            self.registry.attach(disagg.registry)
        # the runner's XLA compile instruments render in this scrape too
        # (FakeRunner test doubles carry no tracker — guard)
        compiles = getattr(runner, "compiles", None)
        if compiles is not None:
            self.registry.attach(compiles.registry)
        # live device-time + roofline accounting: observations feed at
        # the loop's EXISTING reconciliation seams (executor host syncs,
        # is_ready row drains) — never an added hot-path sync
        self.device_time = getattr(runner, "device_time", None)
        if self.device_time is not None:
            self.registry.attach(self.device_time.registry)

    def _build_instruments(self) -> None:
        """Register the scheduler's Prometheus instruments (the full
        catalog is documented in docs/observability.md)."""
        reg = self.registry
        self._step_hist = reg.histogram(
            "dynamo_scheduler_step_duration_seconds",
            "One scheduler loop pass that made progress",
            buckets=STEP_BUCKETS,
        )
        self._phase_hist = reg.histogram(
            "dynamo_scheduler_phase_duration_seconds",
            "Loop-phase latency, labelled phase="
            "admission|prefill|decode|host_sync; phases are disjoint "
            "(host_sync time is carved out of its enclosing phase)",
            buckets=STEP_BUCKETS,
        )
        # device→host sync time accumulated inside the current
        # prefill/decode phase window — subtracted from that window's
        # observation so summing phase series never double-counts
        self._host_sync_s = 0.0
        self._itl_hist = reg.histogram(
            "dynamo_scheduler_inter_token_latency_seconds",
            "Gap between consecutive token emissions of one request",
            buckets=STEP_BUCKETS,
        )
        self._bubble_hist = reg.histogram(
            "dynamo_engine_decode_pipeline_bubble_seconds",
            "Host-observed device-idle gap between consecutive decode "
            "bursts (0 when the next burst was dispatched while the "
            "previous one was still executing on device)",
            buckets=STEP_BUCKETS,
        )
        reg.callback_gauge(
            "dynamo_engine_decode_pipeline_depth",
            "Decode dispatch depth in effect: 2 while a burst is in "
            "flight ahead of host reconciliation, else 1",
            # dynrace: domain(executor)
            lambda: 2 if (self._inflight is not None or self._chain) else 1,
        )
        self._device_finished_ctr = reg.counter(
            "dynamo_engine_device_finished_rows_total",
            "Rows whose finish (eos/hidden-stop/max-tokens/model-len) "
            "was detected inside the decode burst program and frozen on "
            "device instead of ending the burst",
        )
        self._drain_lag_hist = reg.histogram(
            "dynamo_engine_decode_drain_lag_seconds",
            "Chained decode: one burst's dispatch-to-host-reconciliation "
            "lag — how far the asynchronous row drain runs behind the "
            "device",
            buckets=STEP_BUCKETS,
        )
        reg.callback_gauge(
            "dynamo_engine_decode_burst_chain_length",
            "Decode bursts dispatched since the last host barrier: the "
            "open chain's running count, else the last completed "
            "chain's length (>1 means the host barrier is no longer "
            "per burst)",
            # dynrace: domain(executor)
            lambda: self._chain_dispatched or self._last_chain_len,
        )
        self._sync_fallback_ctr = reg.counter(
            "dynamo_engine_sync_fallback_total",
            "Decode passes that fell back to the per-burst host-sync "
            "path while the persistent chain was enabled, labelled "
            "reason= with the constraint that forced it (the shrunken "
            "fallback ladder: every remaining sync pass is attributed)",
        )
        self._spec_accept_hist = reg.histogram(
            "dynamo_engine_spec_accept_length",
            "Accepted speculative tokens per propose-verify round "
            "(chained in-carry rounds; proposals that verify on-chip)",
            buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0),
        )
        self._preemptions = reg.counter(
            "dynamo_scheduler_preemptions_total",
            "Requests evicted back to the waiting queue on KV OOM",
        )
        # sequence-parallel long-context prefill (docs/long_context.md)
        self._sp_chunks_c = reg.counter(
            "dynamo_engine_prefill_sp_chunks_total",
            "Mesh-wide sequence-parallel prefill chunks dispatched "
            "(each advances sp_prefill_bucket() tokens of one oversized "
            "prompt across the sp axis)",
        )
        self._sp_tokens_c = reg.counter(
            "dynamo_engine_prefill_sp_tokens_total",
            "Prompt tokens prefilled through the sequence-parallel "
            "program (suffix tokens only; prefix-cache hits excluded)",
        )
        reg.callback_gauge(
            "dynamo_engine_prefill_sp_axis_depth",
            "Size of the mesh's sequence-parallel axis (1 = the SP "
            "program is not built; long prompts take the dense ladder)",
            # dynrace: domain(executor)
            lambda: self.config.sp_size,
        )
        self._sp_exposed_h = reg.histogram(
            "dynamo_engine_prefill_sp_exposed_seconds",
            "Handoff exposure of one SP prefill: time after the final "
            "chunk's dispatch during which NO decode work for the "
            "request was in flight — ~0 when the early decode burst "
            "chained off the device-resident first token, else the "
            "whole final-chunk drain",
            buckets=STEP_BUCKETS,
        )
        self._spec_proposed_ctr = reg.counter(
            "dynamo_scheduler_spec_proposed_tokens_total",
            "Speculative tokens proposed (ngram or draft model)",
        )
        self._spec_accepted_ctr = reg.counter(
            "dynamo_scheduler_spec_accepted_tokens_total",
            "Speculative tokens accepted by the verify step",
        )
        reg.callback_gauge(
            "dynamo_scheduler_active_slots",
            "Batch slots currently decoding or prefilling",
            # off-loop render vs loop-side slot assignment: count over a
            # list() snapshot, never the live slot table
            # dynrace: domain(executor)
            lambda: sum(1 for s in list(self.slots) if s is not None),
        )
        reg.callback_gauge(
            "dynamo_scheduler_total_slots",
            "Configured max_batch_size",
            # dynrace: domain(executor)
            lambda: self.config.max_batch_size,
        )
        reg.callback_gauge(
            "dynamo_scheduler_slot_occupancy_ratio",
            "active_slots / total_slots",
            # dynrace: domain(executor)
            lambda: (
                sum(1 for s in list(self.slots) if s is not None)
                / self.config.max_batch_size
            ),
        )
        reg.callback_gauge(
            "dynamo_scheduler_waiting_requests",
            "Admission queue depth (local waiting + pending remote "
            "prefill + pending prefix pulls)",
            # dynrace: domain(executor)
            lambda: (len(self.waiting) + len(self.pending_remote)
                     + len(self.pending_pull)),
        )
        reg.callback_gauge(
            "dynamo_scheduler_draining_info",
            "1 while this engine is gated for drain/recovery (admission "
            "refused, routers skip it) — the fleet hub's per-worker "
            "drain-state column reads this",
            # dynrace: domain(executor)
            lambda: 1.0 if self.draining else 0.0,
        )
        reg.callback_gauge(
            "dynamo_kv_prefix_hit_ratio",
            "Prompt tokens served from the prefix cache / all prompt tokens",
            # dynrace: domain(executor)
            lambda: (
                self.prefix_hit_tokens / self.prefix_total_tokens
                if self.prefix_total_tokens else 0.0
            ),
        )

    def _observe_host_sync(self, dt: float) -> None:
        self._phase_hist.observe(dt, phase="host_sync")
        self._host_sync_s += dt

    # ---------- public API ----------

    def start(self) -> None:
        # any compile past this point interrupts live serving — the
        # tracker tags it "late" (the recompile-storm signal)
        for r in (self.runner, self.draft):
            compiles = getattr(r, "compiles", None)
            if compiles is not None:
                compiles.mark_serving_started()
        if self.fabric is not None and self.fabric.cold is not None:
            # restart-warm on EVERY embedding (single-process serve,
            # tests, distributed workers): prime the cold index off-loop
            # so spilled prefixes survive a process restart. refresh()
            # is idempotent — the CLI's distributed wiring also primes.
            self.fabric.hold_task(
                asyncio.get_running_loop().run_in_executor(
                    None, self.fabric.cold.refresh
                )
            )
        self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        self._stopping = True
        self.wake.set()
        if self._task:
            await self._task
        for er in self.pending_remote:
            if self.disagg is not None:
                self.disagg.cancel(er.request_id)
            self._finish(er, FinishReason.CANCELLED)
        self.pending_remote.clear()
        for er in self.pending_pull:
            self._release_pull(er)
            self._finish(er, FinishReason.CANCELLED)
        self.pending_pull.clear()
        if self.fabric is not None:
            await self.fabric.close()
        if self.disagg is not None:
            await self.disagg.close()

    def _prepare_request(self, er: EngineRequest) -> None:
        """Per-request host fields shared by local admission and
        migration admit (everything except the PRNG key, which a
        migrated request brings along)."""
        so = er.req.sampling_options
        (er.temperature, er.top_k, er.top_p, er.min_p, er.presence_penalty,
         er.frequency_penalty, er.repetition_penalty) = host_row(so)
        # logprobs is a COUNT: 0 = chosen token's logprob with no
        # alternatives (None = off) — bool() would drop the 0 case
        er.want_logprobs = er.req.output_options.logprobs is not None
        er.logprobs_n = int(er.req.output_options.logprobs or 0)
        er.want_prompt_lps = er.req.output_options.prompt_logprobs is not None

    def add_request(self, er: EngineRequest) -> None:
        self._prepare_request(er)
        so = er.req.sampling_options
        if so.seed is not None:
            # per-request key: seeded sampling is reproducible AND isolated
            # from batchmates (each slot samples from its own PRNG stream)
            er.base_key = seed_to_key(int(so.seed))
        else:
            er.base_key = self._rng.integers(
                0, 2**32, size=2, dtype=np.uint32
            )
        er.ctx.add_stage("queued")
        self.waiting.append(er)
        self.wake.set()

    # ---------- drain / migration surface (recovery/) ----------

    def set_draining(self, draining: bool = True) -> None:
        """Gate admission: committed work proceeds, nothing new starts.
        The flag rides the metrics() snapshot so KV routers skip this
        worker, and the watchdog treats a draining engine as stopping
        (gated queued work must not read as starvation)."""
        self.draining = draining
        self.wake.set()

    async def seize(self, hard: bool = False, timeout_s: float = 5.0) -> None:
        """Stop the loop for drain/migration.

        Graceful (``hard=False``) lets the loop run its normal exit
        barriers — every dispatched burst reconciles and streams its
        tokens — and escalates to a cancel after ``timeout_s`` (a
        half-wedged loop must not hang the drain). Hard cancels
        immediately: a loop wedged inside a pass (the watchdog-trip
        case) would never finish a barrier. Un-reconciled device work is
        abandoned — its tokens were never emitted, so the committed host
        state the migration packages stays exact.
        """
        self._stopping = True
        self.draining = True
        self.wake.set()
        task, self._task = self._task, None
        if task is not None:
            if not hard:
                try:
                    await asyncio.wait_for(asyncio.shield(task), timeout_s)
                except asyncio.TimeoutError:
                    logger.warning(
                        "graceful seize timed out after %.1fs; cancelling "
                        "the scheduler loop", timeout_s,
                    )
                    hard = True
            if hard and not task.done():
                task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
            except Exception:
                logger.exception("scheduler loop raised during seize")
        if self._inflight is not None or self._chain:
            self.flight.record(
                "scheduler.burst_abandon",
                inflight=self._inflight is not None,
                chained=len(self._chain),
            )
        self._inflight = None
        self._chain.clear()
        self._chain_members = []
        self._chain_carry = None
        self._chain_dispatched = 0
        self._chain_pos0 = {}
        self._chain_kind = None
        self._chain_fp = False

    def extract_requests(self) -> List[EngineRequest]:
        """Detach every live request (slots, prefill batch, waiting
        queue, pending remote prefills) WITHOUT finishing their streams
        — the recovery controller migrates or fails each one. Requests
        keep their block lists; the caller owns releasing them (after a
        hot migration gathers the KV). Call only after ``seize``."""
        out: List[EngineRequest] = []
        for i, er in enumerate(self.slots):
            if er is None:
                continue
            self.slots[i] = None
            er.slot = -1
            out.append(er)
        self.prefilling.clear()
        # SP-mid-prefill requests migrate cold (partial KV is never
        # packaged); any dispatched chunk work is abandoned with them
        self.sp_queue.clear()
        self.sp_active = None
        while self.waiting:
            out.append(self.waiting.popleft())
        for er in self.pending_remote:
            if self.disagg is not None:
                self.disagg.cancel(er.request_id, reason="drain")
            er.remote_future = None
            out.append(er)
        self.pending_remote.clear()
        for er in self.pending_pull:
            # in-flight pulls abort; the request migrates cold (its
            # blocks hold no registered KV — packaging frees them)
            self._release_pull(er)
            out.append(er)
        self.pending_pull.clear()
        for er in out:
            self.flight.record(
                "scheduler.extract", request_id=er.request_id,
                trace_id=er.ctx.trace_id, generated=er.generated,
                blocks=len(er.block_ids),
            )
        return out

    def admit_migrated(self, er: EngineRequest, committed_tokens: List[int],
                       block_ids: List[int]) -> bool:
        """Admit a request migrated from a draining peer.

        Hot (``block_ids`` non-empty, their KV already scattered): enter
        the decode loop directly, exactly like a committed remote prefill
        — except nothing is emitted here; every token up to and
        including the pending one already streamed from the source.
        Cold: join the waiting queue; the preemption-resume machinery
        re-prefills ``prompt + resume_tokens`` and continues the stream.
        Returns False (caller frees the blocks and nacks) when no slot
        is free at install time."""
        self._prepare_request(er)
        if er.base_key is None:
            # source predates per-request keys (or state was trimmed):
            # fresh key — sampled continuations diverge from the
            # counterfactual un-migrated stream, greedy ones do not
            er.base_key = self._rng.integers(0, 2**32, size=2,
                                             dtype=np.uint32)
        er.ctx.add_stage("migration.resume")
        self.flight.record(
            "scheduler.migrate_in", request_id=er.request_id,
            trace_id=er.ctx.trace_id, hot=bool(block_ids),
            generated=er.generated,
        )
        if not block_ids:
            # cold: never try remote prefill for a resumed stream (the
            # remote path would restart from the prompt alone)
            er.remote_attempted = bool(er.resume_tokens)
            self.waiting.append(er)
            self.wake.set()
            return True
        slot = self._free_slot()
        if slot is None:
            return False
        bs = self.config.kv_block_size
        er.slot = slot
        er.block_ids = list(block_ids)
        er.context_len = len(committed_tokens)
        er.num_cached = 0
        er.resume_tokens = []
        er.seq = TokenSequence(committed_tokens, block_size=bs)
        er.registered_blocks = 0
        # every fallible step runs BEFORE the slot publishes: a failed
        # install (e.g. a geometry surprise the receiver's reserve gate
        # missed) must leave this scheduler exactly as it was — the
        # written host-state row is harmless while the slot stays empty
        self._host.install(er)
        # penalty/PRNG state: presence of the prompt plus counts of every
        # generated token (including the pending one — it was sampled and
        # emitted; only its KV write is still owed)
        gen = list(committed_tokens[len(er.prompt):])
        if er.pending_token >= 0:
            gen = gen + [er.pending_token]
        er.ring_tail.clear()
        er.ring_tail.extend(
            (list(committed_tokens)
             + ([er.pending_token] if er.pending_token >= 0 else [])
             )[-SUFFIX_RING_W:]
        )
        self.runner.set_sample_row(
            slot, er.prompt, gen,
            logit_bias=er.req.sampling_options.logit_bias,
        )
        # completed prefix blocks become matchable here too — a migrated
        # prefix seeds this worker's prefix cache
        self._register_completed_blocks(er)
        self.slots[slot] = er
        self.wake.set()
        return True

    def metrics(self) -> dict:
        active = sum(1 for s in self.slots if s is not None)
        out = {
            "request_active_slots": active,
            "request_total_slots": self.config.max_batch_size,
            "kv_active_blocks": self.allocator.used,
            "kv_total_blocks": self.allocator.num_blocks,
            "num_requests_waiting": (
                len(self.waiting) + len(self.pending_remote)
                + len(self.pending_pull)
            ),
            "gpu_cache_usage_perc": self.allocator.usage(),
            "gpu_prefix_cache_hit_rate": (
                self.prefix_hit_tokens / self.prefix_total_tokens
                if self.prefix_total_tokens else 0.0
            ),
            # KV routers exclude draining workers from every decision
            # (kv_router/scheduler.py) — the snapshot is the fastest
            # deregistration channel there is
            "draining": self.draining,
        }
        if self.config.spec_ngram_tokens or self.draft is not None:
            out["spec_proposed_tokens"] = self.spec_proposed
            out["spec_accepted_tokens"] = self.spec_accepted
        if self.config.decode_pipeline_depth >= 2:
            out["decode_pipeline_bursts"] = self.pipeline_bursts
        if self.config.device_finish_enabled:
            out["decode_burst_chain_length"] = (
                self._chain_dispatched or self._last_chain_len
            )
        if self.allocator.tier2 is not None:
            out.update(self.allocator.tier2.metrics())
        if self.fabric is not None and self.fabric.cold is not None:
            out.update(self.fabric.cold.metrics())
        if self.disagg is not None:
            out.update(self.disagg.metrics())
        return out

    # ---------- watchdog surface (telemetry/watchdog.py) ----------

    def watchdog_probe(self) -> dict:
        """Liveness snapshot the stall watchdog samples: heartbeat stamp
        of the last loop pass, the dispatch counter, and the pending-work
        breakdown (local waiting vs remote-prefill waits vs active
        slots)."""
        return {
            "heartbeat_t": self.last_loop_t,
            "steps": self.steps,
            "queue_depth": len(self.waiting),
            "pending_remote": len(self.pending_remote),
            # pull waits own their deadline (fallback → local), so the
            # watchdog must not read them as starvation — same contract
            # as remote waits
            "pending_pull": len(self.pending_pull),
            "active": sum(1 for s in self.slots if s is not None),
            # a draining engine's gated queue must not read as
            # starvation — recovery owns it now, not the watchdog
            "stopping": self._stopping or self.draining,
        }

    def request_table(self) -> List[dict]:
        """Active request snapshot for the flight artifact: every slot's
        occupant plus the waiting/pending-remote queues."""
        out = []
        for i, er in enumerate(self.slots):
            if er is None:
                continue
            out.append({
                "state": (
                    "prefilling" if er in self.prefilling
                    else "sp_prefilling" if self._is_sp(er)
                    else "decoding"
                ),
                "slot": i,
                "request_id": er.request_id,
                "trace_id": er.ctx.trace_id,
                "prompt_tokens": len(er.prompt),
                "generated": er.generated,
                "context_len": er.context_len,
                "blocks": len(er.block_ids),
                "guided": er.guided is not None,
            })
        for state, ers in (("waiting", list(self.waiting)),
                           ("pending_remote", self.pending_remote),
                           ("pending_pull", self.pending_pull)):
            out.extend({
                "state": state,
                "request_id": er.request_id,
                "trace_id": er.ctx.trace_id,
                "prompt_tokens": len(er.prompt),
                "generated": er.generated,
            } for er in ers)
        return out

    # ---------- helpers ----------

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.slots):
            if s is None:
                return i
        return None

    def _emit(self, er: EngineRequest, token: int, logprob: Optional[float],
              top: Optional[dict] = None,
              prompt_lps: Optional[list] = None) -> None:
        now = time.monotonic()
        if er.last_emit_t:
            self._itl_hist.observe(now - er.last_emit_t)
        else:
            er.ctx.add_stage("first_token")
        er.last_emit_t = now
        out = EngineOutput(
            token_ids=[token],
            finish_reason=er.finish,
            logprobs=(
                [TokenLogprob(token, logprob, top)]
                if logprob is not None else None
            ),
            prompt_logprobs=prompt_lps,
        )
        er.out_queue.put_nowait(out)

    def _top_row(self, er: EngineRequest, top_vals, top_ids, row: int):
        """The request's top-N alternatives dict from a step's [B, K]
        top-logprob arrays (None unless the request asked for them)."""
        if not er.want_logprobs or er.logprobs_n <= 0:
            return None
        n = min(er.logprobs_n, top_vals.shape[1])
        return {
            int(t): float(v)
            for t, v in zip(top_ids[row, :n], top_vals[row, :n])
        }

    def _finish(self, er: EngineRequest, reason: FinishReason, emit: bool = True) -> None:
        er.finish = reason
        self.flight.record(
            "scheduler.finish", request_id=er.request_id,
            trace_id=er.ctx.trace_id, reason=str(reason),
            generated=er.generated, device_finished=er.device_frozen,
        )
        er.ctx.add_stage("completion")
        if emit:
            er.out_queue.put_nowait(EngineOutput(token_ids=[], finish_reason=reason))
        er.out_queue.put_nowait(None)  # stream end sentinel
        if er.slot >= 0:
            self.slots[er.slot] = None
        self.allocator.free_blocks(er.block_ids)
        er.block_ids = []

    def _advance_row(self, er: EngineRequest, token: int) -> None:
        """Commit ONE sampled token to host state: the previous pending
        token's KV is now written (push + register), the new token
        becomes pending, and finish checks run. The single shared
        implementation behind the synchronous decode loop, the
        speculative accept loop, and the pipeline's reconciliation —
        one copy, so the paths' streams cannot drift."""
        er.seq.push(er.pending_token)
        er.context_len += 1
        self._register_completed_blocks(er)
        er.pending_token = token
        er.generated += 1
        # the ring tail mirrors the burst carry's suffix ring (ends with
        # the pending token) — _check_finish's stop-seq compare and the
        # next chain fill both read it
        er.ring_tail.append(token)
        er.finish = self._check_finish(er, token)

    def _ensure_block_for(self, er: EngineRequest, position: int) -> bool:
        """Make sure a block exists covering ``position``."""
        bs = self.config.kv_block_size
        needed = position // bs + 1
        while len(er.block_ids) < needed:
            try:
                # flush deferred: the decode loop grows many sequences per
                # step and batches the eviction-offload gather afterwards
                er.block_ids.append(self.allocator.allocate_block(flush=False))
            except MemoryError:
                return False
        return True

    def _register_completed_blocks(self, er: EngineRequest) -> None:
        """Hash-register blocks whose KV is complete (matchable + KV events).

        ``er.seq`` mirrors exactly the tokens whose KV sits in cache, so its
        frozen blocks line up 1:1 with ``er.block_ids``."""
        n_complete = min(er.context_len // self.config.kv_block_size, len(er.seq.blocks))
        for i in range(er.registered_blocks, n_complete):
            blk = er.seq.blocks[i]
            self.allocator.register_complete(
                er.block_ids[i], blk.sequence_hash, blk.parent_sequence_hash
            )
        er.registered_blocks = max(er.registered_blocks, n_complete)

    # ---------- the loop ----------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopping:
            progressed = False
            pass_t0 = time.monotonic()
            # watchdog heartbeat (telemetry/watchdog.py): a wedge INSIDE
            # this pass — hung compile, dead host sync — leaves it stale
            self.last_loop_t = pass_t0

            # drop cancelled requests (client disconnects / kills)
            for er in list(self.waiting):
                if er.ctx.is_stopped:
                    self.waiting.remove(er)
                    self._finish(er, FinishReason.CANCELLED)
            for er in [s for s in self.slots if s is not None]:
                if er.ctx.is_stopped:
                    if er in self.prefilling:
                        self.prefilling.remove(er)
                    self._sp_drop(er)
                    self._finish(er, FinishReason.CANCELLED)

            # remote prefill completions / cancellations / timeouts
            if self.pending_remote:
                progressed |= self._reap_remote()

            # prefix-pull completions / fallbacks / timeouts
            if self.pending_pull:
                progressed |= self._reap_pulls()

            # admission, pulls first: a prefix pull is only a block
            # reservation + a transfer (no local compute), and a pulled
            # prefix shrinks the suffix every later decision (remote
            # prefill, local chunking) sees
            t_adm = time.monotonic()
            admitted = False
            if (self.fabric is not None and not self.draining
                    and self.fabric.may_hold_any()):
                for er in list(self.waiting):
                    if len(self.pending_pull) >= self.config.max_batch_size:
                        break
                    if self._try_submit_pull(er):
                        self.waiting.remove(er)
                        progressed = admitted = True
            if self.disagg is not None and not self.draining:
                for er in list(self.waiting):
                    if (len(self.pending_remote)
                            >= self.config.max_batch_size):
                        break
                    if await self._try_submit_remote(er):
                        self.waiting.remove(er)
                        progressed = admitted = True

            # local admission: claim a slot + blocks, join the prefill
            # batch (up to max_prefill_batch prompts prefill together).
            # Requests held for an overlapping in-flight prefix pull
            # (pull_hold_until) are skipped, not admitted to recompute
            # what the pull is about to install; everyone else keeps
            # FIFO order.
            # both ladders honor the prefill-batch cap: SP-routed
            # admissions pre-allocate their WHOLE prompt's blocks while
            # the single-owner ladder serves one prompt at a time, so an
            # unbounded sp_queue would pin the block pool idle and
            # preempt-thrash live decode streams — oversize backlogs
            # wait block-free in `waiting`, exactly like the dense path
            while (self.waiting
                   and not self.draining
                   and len(self.prefilling) < self.config.max_prefill_batch
                   and (len(self.sp_queue)
                        + (1 if self.sp_active is not None else 0)
                        < self.config.max_prefill_batch)
                   and self._free_slot() is not None):
                now_h = time.monotonic()
                er = next((e for e in self.waiting
                           if e.pull_hold_until <= now_h), None)
                if er is None:
                    break  # everyone waiting is held on a pull
                try:
                    self._start_prefill(er)
                except MemoryError:
                    break  # no memory — wait for a sequence to finish
                self.waiting.remove(er)
                progressed = admitted = True
            if admitted:
                self._phase_hist.observe(
                    time.monotonic() - t_adm, phase="admission"
                )

            # one prefill step (≤ max_prefill_tokens_per_step tokens,
            # split across the batch) per loop pass, interleaved with the
            # decode step below so active streams keep a bounded ITL
            # while prompts prefill (reference analog: chunked-prefill +
            # batching of the engines behind
            # examples/llm/components/worker.py:72-74)
            if self.prefilling:
                t_pf = time.monotonic()
                self._host_sync_s = 0.0
                # prefill work interleaves into the device stream: the
                # burst-to-burst idle clock no longer means anything
                self._last_burst_done_t = None
                await self._prefill_chunk(loop, list(self.prefilling))
                self._phase_hist.observe(
                    max(0.0, time.monotonic() - t_pf - self._host_sync_s),
                    phase="prefill",
                )
                progressed = True

            # sequence-parallel long-context ladder: one mesh-wide chunk
            # per pass (dispatch-only until the final chunk), so decode
            # ITL stays bounded while a 128k prompt prefills across the
            # slice
            if self.sp_active is not None or self.sp_queue:
                t_sp = time.monotonic()
                self._host_sync_s = 0.0
                self._last_burst_done_t = None
                if await self._sp_advance(loop):
                    self._phase_hist.observe(
                        max(0.0,
                            time.monotonic() - t_sp - self._host_sync_s),
                        phase="prefill",
                    )
                    progressed = True

            # decode every active slot: one token, or a fused K-step
            # burst (multi_step_decode) when nothing is waiting on the
            # runner — prefill work pins K to 1 so chunked-prefill
            # interleaving (bounded TTFT) is never traded for throughput
            active = [
                s for s in self.slots
                if s is not None and s not in self.prefilling
                and not self._is_sp(s)
            ]
            if active:
                t_dec = time.monotonic()
                self._host_sync_s = 0.0
                runner_idle = not (self.prefilling or self.waiting
                                   or self.pending_remote
                                   or self.sp_active is not None
                                   or self.sp_queue)
                speculating = (
                    self.config.spec_ngram_tokens > 0
                    or self.draft is not None
                )
                spec_now = (speculating and runner_idle
                            and all(self._spec_eligible(er) for er in active))
                chain_on = (self.config.device_finish_enabled
                            and self.config.decode_pipeline_depth >= 2)
                spec_reason = (
                    self._spec_chain_reason(active, runner_idle)
                    if (spec_now and chain_on) else None
                )
                if spec_now and chain_on and spec_reason is None:
                    # persistent loop, speculative: chain propose-verify
                    # rounds off the device-resident carry — no host
                    # barrier between draft/target rounds
                    await self._decode_chained_spec(loop, active)
                elif not spec_now and self._chain_ok(active, runner_idle):
                    # persistent loop: chain the next burst off the
                    # device-resident carry; finished rows freeze on
                    # device and drain asynchronously
                    await self._decode_chained(loop, active)
                else:
                    # the chain did not engage this pass: attribute the
                    # sync fallback to its reason (acceptance criterion:
                    # every remaining sync pass is named)
                    if chain_on:
                        reason = (
                            spec_reason if spec_now
                            else self._chain_block_reason(
                                active, runner_idle)
                        )
                        if reason:
                            self._note_sync_fallback(reason)
                    if not spec_now and self._pipeline_ok(
                            active, runner_idle):
                        # dispatch-ahead: burst k+1 goes to the device
                        # before burst k's tokens are synced on the host
                        await self._chain_barrier(loop)
                        active = [er for er in active if er.finish is None]
                        if active:
                            await self._decode_pipelined(loop, active)
                    else:
                        await self._chain_barrier(loop)
                        active = [er for er in active if er.finish is None]
                        if self._inflight is not None:
                            # sync barrier: reconcile the in-flight burst
                            # before any non-pipelined dispatch
                            # (membership, masks, or the program shape
                            # is changing)
                            await self._drain_pipeline(loop)
                            active = [er for er in active
                                      if er.finish is None]
                        if not active:
                            pass
                        elif spec_now:
                            # speculative verify (ngram or draft-model
                            # proposals) on the host sync path
                            await self._decode_spec(loop, active)
                        else:
                            k_steps = self.config.multi_step_decode
                            if k_steps > 1 and not runner_idle:
                                k_steps = 1
                            await self._decode(loop, active, k_steps)
                self._phase_hist.observe(
                    max(0.0, time.monotonic() - t_dec - self._host_sync_s),
                    phase="decode",
                )
                progressed = True
            elif self._chain or self._chain_members:
                # every chained row finished or was cancelled while the
                # chain was still dispatching: reconcile the queue and
                # close the chain (frozen rows' pads apply as no-ops)
                await self._chain_barrier(loop)
                progressed = True
            elif self._inflight is not None:
                # every pipelined row finished or was cancelled while its
                # successor burst was in flight: reconcile the orphan (all
                # rows skip at apply — pure over-decode, nothing emits)
                await self._drain_pipeline(loop)
                progressed = True

            # materialize staged host-tier offloads now that this pass's
            # device work is already dispatched: the D2H copies overlapped
            # the step; drain only waits out any straggler
            if self.allocator.tier2 is not None:
                self.allocator.tier2.drain()

            if not progressed:
                self.wake.clear()
                # about to sleep: the device-idle clock must not count
                # request-starved idle as a pipeline bubble
                self._last_burst_done_t = None
                if self.device_time is not None:
                    self.device_time.idle()
                if not self.waiting and not any(self.slots):
                    if self.pending_remote or self.pending_pull:
                        # sleep but wake on remote/pull completion — the
                        # bounded wait keeps deadline checks live even
                        # if a stalled pull never completes its future
                        try:
                            await asyncio.wait_for(self.wake.wait(), timeout=0.5)
                        except asyncio.TimeoutError:
                            pass
                    else:
                        await self.wake.wait()
                else:
                    await asyncio.sleep(0.001)
            else:
                self._step_hist.observe(time.monotonic() - pass_t0)
                await asyncio.sleep(0)  # let I/O run between steps

        # stopping: reconcile any chained or dispatch-ahead burst so no
        # sampled tokens are silently dropped and no device work is
        # abandoned
        await self._chain_barrier(loop)
        await self._drain_pipeline(loop)

    # ---------- dispatch-ahead decode (pipeline depth 2) ----------

    def _pipeline_ok(self, active: List[EngineRequest],
                     runner_idle: bool) -> bool:
        """May this pass decode dispatch-ahead?

        Guided decoding (per-token host mask edits), speculative decoding
        (both proposal sources), ``n>1`` fan-out, prefill/admission work,
        and rows within two bursts of the model-len horizon all force the
        existing synchronous path — selected per-pass, never mid-burst.
        A batch-membership surprise (a row active now that was not in the
        dispatched burst) drains defensively.
        """
        cfg = self.config
        if cfg.decode_pipeline_depth < 2 or not runner_idle:
            return False
        if self.draft is not None or cfg.spec_ngram_tokens > 0:
            return False
        K = cfg.multi_step_decode
        for er in active:
            if er.guided is not None:
                return False
            n = er.req.sampling_options.n
            if n is not None and n > 1:
                return False
            if er.context_len + 2 * K + 1 > cfg.max_model_len:
                return False
        infl = self._inflight
        if infl is not None:
            live = {id(er) for er in infl.active if er.finish is None}
            if live != {id(er) for er in active}:
                return False
        return True

    async def _decode_pipelined(self, loop,
                                active: List[EngineRequest]) -> None:
        """One pipelined pass: dispatch burst k+1, then reconcile burst k
        on the host while k+1 executes on device.

        The carry (burst k's last sampled tokens) is already device-
        resident inside the burst program's outputs, so burst k+1
        consumes it without a host round-trip; the host then syncs,
        detokenizes, streams, and finish-checks burst k's tokens during
        burst k+1's device time. Block headroom for ``2*K`` positions is
        reserved before every dispatch, so the in-flight burst can never
        write to an unallocated slot; if reservation fails, the pipeline
        drains (sync barrier) and the synchronous path — which owns
        preemption — takes the pass.
        """
        cfg = self.config
        b = cfg.max_batch_size
        k_steps = cfg.multi_step_decode
        infl = self._inflight
        # device is ``ahead`` tokens past the host's committed state
        ahead = infl.k_steps if infl is not None else 0

        for er in active:
            # 2*K from the host context: covers the burst dispatched now
            # (positions ahead..ahead+K-1 past the committed state) and
            # keeps the invariant once reconciliation advances the host
            ok = all(
                self._ensure_block_for(er, er.context_len + j)
                for j in range(2 * k_steps)
            )
            if not ok:
                # KV OOM: preemption needs fully-committed host state —
                # drain, then let the sync path preempt/decode this pass
                self.allocator.flush_offload()
                await self._drain_pipeline(loop)
                live = [e for e in active if e.finish is None]
                if live:
                    await self._decode(loop, live, k_steps)
                return
        # one batched host-offload gather for this pass's evictions,
        # before the dispatch below overwrites the evicted slots
        self.allocator.flush_offload()

        hs = self._host
        positions0 = np.zeros(b, np.int32)
        ctrs = np.zeros(b, np.int32)
        commit = np.zeros(b, bool)
        for er in active:
            i = er.slot
            hs.sync_blocks(er)
            positions0[i] = er.context_len + ahead
            ctrs[i] = er.generated + ahead
            commit[i] = True
        w = cfg.kv_width_bucket(max(len(er.block_ids) for er in active))
        btab = hs.btab[:, :w].copy()
        if infl is None:
            # pipeline fill (first burst after a drain): tokens from host
            tokens0 = np.zeros(b, np.int32)
            for er in active:
                tokens0[er.slot] = er.pending_token
        else:
            tokens0 = infl.last_tokens  # device-resident carry
        want_top = any(er.logprobs_n > 0 for er in active)

        # device-idle bookkeeping: if the previous burst's outputs are
        # already materialized when this dispatch goes out, the device
        # ran dry — charge the gap since the last host reconciliation
        # (a host-observed approximation; 0 while the device is busy)
        now = time.monotonic()
        if self._last_burst_done_t is not None:
            if infl is None:
                self._bubble_hist.observe(now - self._last_burst_done_t)
            else:
                ready = getattr(infl.last_tokens, "is_ready", lambda: True)()
                self._bubble_hist.observe(
                    now - self._last_burst_done_t if ready else 0.0
                )
        self._last_burst_done_t = None

        toks, lps, tv, ti = self.runner.decode_burst(
            tokens0, positions0, btab, hs.temp, hs.top_k, hs.top_p,
            min_p=hs.min_p, presence_penalty=hs.pres,
            frequency_penalty=hs.freq, repetition_penalty=hs.rep,
            seed_keys=hs.keys, counters=ctrs, commit=commit,
            want_top=want_top,
        )
        self.steps += 1
        self.pipeline_bursts += 1
        self.flight.record(
            "scheduler.burst_dispatch", k_steps=k_steps, rows=len(active),
            pipelined=True, carried=infl is not None,
            requests=[er.request_id for er in active[:8]],
        )
        dt = self.device_time
        self._inflight = _InflightBurst(
            active=list(active), toks=toks, lps=lps, tv=tv, ti=ti,
            k_steps=k_steps, last_tokens=toks[k_steps - 1],
            dispatch_t=now,
            read_bytes=dt.decode_read_bytes(
                k_steps, sum(er.context_len for er in active),
            ) if dt is not None else 0.0,
            tokens=k_steps * len(active),
        )
        if infl is not None:
            # burst k+1 is on device — reconcile burst k while it runs
            await self._apply_burst(loop, infl)
            if all(er.finish is not None for er in self._inflight.active):
                # burst k finished every row: k+1 is pure over-decode —
                # reconcile it now instead of leaving an orphan in flight
                await self._drain_pipeline(loop)

    async def _apply_burst(self, loop, infl: _InflightBurst,
                           ready_hint: Optional[float] = None) -> None:
        """Host half of the pipeline: sync the burst's sampled tokens
        (the decode loop's ONLY host sync), emit/stream them, run finish
        checks, and retro-invalidate rows that finished one burst late.

        ``ready_hint`` is the moment an ``is_ready`` probe saw the
        outputs materialized (the async row drain) — the device-time
        observation below prefers it over the post-sync stamp so drain
        lag and D2H copy time are not charged as device compute."""
        t_sync = time.monotonic()

        def _sync_burst():
            # chaos site: DYN_FAULT=decode_burst_hang wedges THIS thread
            # — the exact executor-side shape of a hung Mosaic compile
            # or a dead device mid-sync (utils/faults.py)
            faults.maybe_hang("decode_burst_hang")
            if infl.spec:
                # spec rounds carry no logprob outputs (spec-eligible
                # rows want none) but do carry acceptance accounting
                return (np.asarray(infl.toks), None, None, None,
                        np.asarray(infl.nprop), np.asarray(infl.nacc))
            return (np.asarray(infl.toks), np.asarray(infl.lps),
                    np.asarray(infl.tv), np.asarray(infl.ti), None, None)

        toks, lpn, tv, ti, nprop, nacc = await loop.run_in_executor(
            None, _sync_burst)
        self._observe_host_sync(time.monotonic() - t_sync)
        self._last_burst_done_t = time.monotonic()
        if self.device_time is not None and infl.dispatch_t:
            self.device_time.observe(
                "decode_burst_df" if infl.device_finish else "decode_burst",
                "decode", infl.dispatch_t,
                ready_hint if ready_hint is not None
                else self._last_burst_done_t,
                read_bytes=infl.read_bytes, tokens=infl.tokens,
            )
        for j in range(infl.k_steps):
            for er in infl.active:
                if er.finish is not None:
                    continue  # finished/cancelled: over-decode discarded
                token = int(toks[j, er.slot])
                if infl.device_finish and token < 0:
                    if er.chain_fp:
                        continue  # already flagged: resumes at barrier
                    if infl.spec and j > 0:
                        # spec rounds pad past the acceptance length —
                        # every LIVE row still emits its correction at
                        # j=0, so only a j=0 pad means a frozen row
                        continue
                    if (er.fin_stop_hash is not None
                            and er.finish is None):
                        # the device's suffix-hash stop candidate froze
                        # this row, but the host's EXACT token-suffix
                        # check (_check_finish, ran on every emitted
                        # token above) never fired: a hash collision.
                        # Flag it — the chain closes at the next pass
                        # and the row resumes byte-identically from its
                        # committed state (no tokens were lost: frozen
                        # rows never over-decode).
                        er.chain_fp = True
                        self._chain_fp = True
                        self._note_sync_fallback("stop_false_positive")
                        self.flight.record(
                            "scheduler.stop_false_positive",
                            request_id=er.request_id,
                            trace_id=er.ctx.trace_id,
                            generated=er.generated,
                        )
                        continue
                    # -1 pad: the device froze this row at an earlier
                    # step, whose application above set er.finish. A pad
                    # with NO host verdict means the device mask and the
                    # host mirror diverged — finishing the row loudly
                    # beats decoding a frozen zombie forever.
                    logger.error(
                        "device froze %s without a host finish verdict "
                        "(device_finish_mask / _check_finish mirror "
                        "divergence?); forcing STOP", er.request_id,
                    )
                    er.finish = FinishReason.STOP
                    # emit=True: unlike the normal path, no preceding
                    # _emit carried the finish_reason — the client must
                    # still see one before the stream sentinel
                    self._finish_pipelined(er, emit=True)
                    continue
                self._advance_row(er, token)
                if infl.device_finish and er.guided is not None:
                    # chained guided rows: advance the host cursor
                    # (verdicts only — the device computed the mask; the
                    # barrier reinstalls the host mask if needed)
                    self._guided_after_token(er, edit=False)
                er.pipeline_span_open = True
                self._emit(
                    er, token,
                    (float(lpn[j, er.slot])
                     if (lpn is not None and er.want_logprobs) else None),
                    (self._top_row(er, tv[j], ti[j], er.slot)
                     if tv is not None else None),
                )
                if er.finish is not None:
                    if infl.device_finish:
                        # the device's mask froze this row at exactly
                        # this step — the host check is the mirror that
                        # names the reason and finalizes bookkeeping
                        er.device_frozen = True
                        self._device_finished_ctr.inc()
                    self._finish_pipelined(er)
        if infl.spec and nprop is not None:
            for er in infl.active:
                p = int(nprop[er.slot])
                if p <= 0:
                    continue  # frozen rows propose nothing this round
                a = int(nacc[er.slot])
                self.spec_proposed += p
                self.spec_accepted += min(a, p)
                self._spec_proposed_ctr.inc(p)
                self._spec_accepted_ctr.inc(min(a, p))
                self._spec_accept_hist.observe(float(a))

    def _finish_pipelined(self, er: EngineRequest, emit: bool = False) -> None:
        """A pipelined row finished (possibly one burst late): truncate
        the over-decoded tokens (never emitted), roll the headroom blocks
        holding only over-decoded KV back into the allocator, stamp the
        ``decode_pipeline`` span, and free the slot.

        The in-flight burst's writes to the rolled-back blocks are
        harmless: the blocks are anonymous (never registered), and device
        dispatch ordering lands those writes before any later program's
        writes to a reallocated slot.
        """
        bs = self.config.kv_block_size
        keep = -(-er.context_len // bs)  # blocks covering committed KV
        rolled = max(0, len(er.block_ids) - keep)
        er.block_ids = self.allocator.rollback_tail(er.block_ids, keep)
        self.flight.record(
            "scheduler.rollback", request_id=er.request_id,
            trace_id=er.ctx.trace_id, blocks=rolled,
            reason=str(er.finish),
        )
        self._host.sync_blocks(er)
        if er.pipeline_span_open:
            er.ctx.add_stage("decode_pipeline")
            er.pipeline_span_open = False
        # emit=False on the normal path: the finishing token's _emit
        # already carried the finish_reason. The mirror-divergence
        # fallback passes emit=True — nothing was emitted there.
        self._finish(er, er.finish, emit=emit)

    async def _drain_pipeline(self, loop) -> None:
        """Sync barrier: reconcile the in-flight burst (if any) so every
        synchronous consumer — preemption, prefill interleave, spec or
        guided decode, shutdown — sees fully-committed host state."""
        infl, self._inflight = self._inflight, None
        if infl is None:
            return
        self.flight.record(
            "scheduler.burst_drain", k_steps=infl.k_steps,
            rows=len(infl.active),
        )
        await self._apply_burst(loop, infl)
        for er in infl.active:
            # still-live rows close their pipelined span here so the
            # synchronous tail that follows is attributed separately
            # (finished rows were stamped by _finish_pipelined; cancelled
            # rows already carry their completion mark)
            if er.finish is None and er.pipeline_span_open:
                er.ctx.add_stage("decode_pipeline")
                er.pipeline_span_open = False

    # ---------- persistent decode loop (config.device_finish) ----------

    # bursts allowed in flight ahead of the async drain: beyond this the
    # dispatcher waits out the oldest sync (the device has CHAIN_MAX
    # bursts queued — it cannot run dry while the host catches up), so
    # per-burst device output buffers stay bounded
    CHAIN_MAX_INFLIGHT = 4

    def _note_sync_fallback(self, reason: str) -> None:
        self._sync_fallback_ctr.inc(reason=reason)

    def _chain_ok(self, active: List[EngineRequest],
                  runner_idle: bool) -> bool:
        return self._chain_block_reason(active, runner_idle) is None

    def _chain_block_reason(self, active: List[EngineRequest],
                            runner_idle: bool) -> Optional[str]:
        """Why can this pass NOT chain a plain burst off the device
        carry? None = it can. The shrunken fallback ladder: stop-string
        rows chain via the suffix-hash approximation, guided rows via a
        compiled device table, n>1 arrives as independent n=1 children —
        what remains is named here and counted per sync pass
        (dynamo_engine_sync_fallback_total{reason})."""
        cfg = self.config
        if not (cfg.device_finish_enabled
                and cfg.decode_pipeline_depth >= 2):
            return "disabled"
        if not runner_idle:
            return "not_idle"
        if not active:
            return "no_rows"
        if self._chain_fp:
            # a suffix-hash false positive froze a row the host must
            # resume: close the chain first (the barrier clears this)
            return "stop_false_positive"
        if self.draft is not None:
            # plain (non-spec) chaining would starve the draft's mirror
            # cache for these rows; draft engines chain through the
            # propose-verify rounds instead
            return "draft_mirror"
        if self._chain_members and self._chain_kind not in (None, "plain"):
            return "chain_kind"
        tables = set()
        for er in active:
            if not er.device_checkable:
                return er.chain_fallback or "not_checkable"
            if er.fin_stop_seqs and not cfg.device_stop_strings:
                return "stop_strings_disabled"
            if er.guided is not None:
                r = self._guided_chain_reason(er)
                if r:
                    return r
                tables.add(id(self._guided_tables[
                    self._guided_table_key(er)]))
        if len(tables) > 1:
            # the burst program takes ONE transition table; requests
            # sharing a grammar share a table (the common case), mixed
            # grammars wait for membership to separate them
            return "guided_multi_grammar"
        if self._chain_members:
            member_ids = {id(m) for m in self._chain_members}
            if any(id(er) not in member_ids for er in active):
                return "membership"
        return None

    def _spec_chain_reason(self, active: List[EngineRequest],
                           runner_idle: bool) -> Optional[str]:
        """Why can this pass NOT chain propose-verify rounds? (Callers
        established spec_now: speculation configured, runner idle, every
        row spec-eligible — greedy, penalty-free, unguided.)"""
        cfg = self.config
        if not (cfg.device_finish_enabled
                and cfg.decode_pipeline_depth >= 2):
            return "disabled"
        if not runner_idle:
            return "not_idle"
        if self._chain_fp:
            return "stop_false_positive"
        if not getattr(self.runner, "spec_burst_ready",
                       hasattr(self.runner, "decode_burst_spec")):
            return "spec_program"
        P = (cfg.spec_draft_tokens if self.draft is not None
             else cfg.spec_ngram_tokens)
        n = self._chain_dispatched
        for er in active:
            if not er.device_checkable:
                return er.chain_fallback or "not_checkable"
            if er.fin_stop_seqs and not cfg.device_stop_strings:
                return "stop_strings_disabled"
            # conservative horizon guard: the host's committed context
            # lags the drain queue, so bound by the chain's own dispatch
            # count — the round's S-position forward must stay inside
            # the model-len horizon (the sync verify makes the same
            # per-pass check)
            pos0 = self._chain_pos0.get(er.slot, er.context_len)
            if pos0 + (n + 1) * (P + 1) + 1 > cfg.max_model_len:
                return "spec_near_horizon"
        if self._chain_members:
            if self._chain_kind not in (None, "spec"):
                return "chain_kind"
            member_ids = {id(m) for m in self._chain_members}
            if any(id(er) not in member_ids for er in active):
                return "membership"
        return None

    # ---------- guided device tables (engine/guided.py) ----------

    # compiled tables kept at most this many distinct grammars: each is
    # a dense [states, vocab] int32 (tens of MB at real vocab sizes), so
    # adversarial per-request unique choice lists must not grow memory
    # without bound. LRU; eviction is safe mid-chain because every
    # chained pass re-checks presence (_guided_chain_reason) BEFORE the
    # dispatch reads the cache — a missing table just recompiles.
    GUIDED_TABLE_CACHE = 16

    def _guided_table_key(self, er: EngineRequest) -> tuple:
        if er.guided_key is not None:
            return er.guided_key
        eos = tuple(sorted(int(t) for t in (er.req.eos_token_ids or [])))
        g = er.guided
        if isinstance(g, TrieConstraint):
            key = ("trie",
                   tuple(tuple(int(t) for t in c) for c in g._choice_ids),
                   eos)
        else:
            # JsonConstraint: the grammar object is shared across
            # requests with the same spec (serving's cache), so its
            # identity keys
            key = ("json", id(g.grammar), eos)
        er.guided_key = key
        return key

    def _compile_guided_table(self, er: EngineRequest):
        """Executor-side table compile (also called directly by tests).
        Returns the DeviceGuidedTable or None (bound exceeded)."""
        return compile_device_table(
            er.guided, self.config.model.vocab_size,
            er.req.eos_token_ids or [],
            max_states=self.config.guided_table_max_states,
        )

    def _guided_chain_reason(self, er: EngineRequest) -> Optional[str]:
        """Is this guided row chainable right now? Kicks the (executor)
        table compile on first sight; the row serves on the sync path
        until the table lands."""
        if not self.config.guided_device_table:
            return "guided_disabled"
        key = self._guided_table_key(er)
        if key in self._guided_tables:
            table = self._guided_tables[key]
            # LRU touch + cap: evict the coldest grammar's table when a
            # new one would exceed the bound (re-checked every pass, so
            # an evicted-then-needed table simply recompiles)
            self._guided_tables.pop(key)
            self._guided_tables[key] = table
            while len(self._guided_tables) > self.GUIDED_TABLE_CACHE:
                self._guided_tables.pop(
                    next(iter(self._guided_tables)))
            if table is None:
                return "guided_table_bound"
            if table.state_id(er.guided) is None:
                # the cursor is in a state the BFS never reached — only
                # a bug can produce this; stay on the sync path loudly
                logger.warning(
                    "guided cursor state unmapped in the device table "
                    "for %s; keeping the sync path", er.request_id,
                )
                return "guided_state_unmapped"
            return None
        if key not in self._guided_table_futs:
            # the per-state vocab sweep must never run on the event
            # loop — compile in an executor, chain once it lands
            loop = asyncio.get_running_loop()
            fut = loop.run_in_executor(
                None, self._compile_guided_table, er
            )

            def _done(f, key=key):
                try:
                    self._guided_tables[key] = f.result()
                except Exception:
                    logger.exception("guided device-table compile failed")
                    self._guided_tables[key] = None
                self._guided_table_futs.pop(key, None)
                self.wake.set()

            fut.add_done_callback(_done)
            self._guided_table_futs[key] = fut
        return "guided_table_pending"

    def _chain_ready(self, infl: _InflightBurst) -> bool:
        """Non-blocking: are this burst's outputs already materialized?
        (Host test doubles return numpy — always ready.)"""
        return getattr(infl.toks, "is_ready", lambda: True)()

    async def _apply_chain_head(self, loop) -> None:
        """Reconcile the oldest queued chained burst (FIFO — token order
        per row) and record its drain lag."""
        infl = self._chain.popleft()
        # outputs already materialized? then NOW is the ready stamp the
        # device-time estimator should use — the sync below only copies
        ready_hint = time.monotonic() if self._chain_ready(infl) else None
        await self._apply_burst(loop, infl, ready_hint=ready_hint)
        self._drain_lag_hist.observe(time.monotonic() - infl.dispatch_t)

    async def _chain_prologue(self, loop, active, kind):
        """The shared open/validate ladder of a chained pass: reconcile
        a predating plain dispatch-ahead burst, barrier on a chain-KIND
        switch (plain ↔ spec program families), open the chain if none
        is, and resolve the live member list. Returns ``(active, live,
        members)`` or None — None means every fallback already ran and
        the caller just returns."""
        if self._inflight is not None:
            # a plain dispatch-ahead burst predates this chain: reconcile
            # it first so the chain starts from fully-committed state
            await self._drain_pipeline(loop)
            active = [er for er in active if er.finish is None]
            if not active:
                return None
        if self._chain_members and self._chain_kind not in (None, kind):
            await self._chain_barrier(loop)
            active = [er for er in active if er.finish is None]
            if not active:
                return None
        if not self._chain_members:
            self._chain_members = list(active)
            self._chain_kind = kind
            self._chain_carry = None
            self._chain_dispatched = 0
            self._chain_pos0 = {er.slot: er.context_len for er in active}
        members = self._chain_members
        live = [er for er in members if er.finish is None]
        if not live:
            await self._chain_barrier(loop)
            return None
        return active, live, members

    async def _chain_reserve(self, loop, active, live, advance,
                             sync_steps) -> bool:
        """Block headroom for the chain's next dispatch: positions a
        never-frozen row runs through ``chain_pos0 + (n+1)*advance - 1``
        — reserve one past that (the carry slot), capped at the
        model-len horizon (the device freezes rows there; blocks past it
        are never touched). False ⇒ KV OOM: preemption needs fully-
        committed host state, so the chain closed at a barrier and the
        pass already fell back to one sync decode."""
        cfg = self.config
        n = self._chain_dispatched
        for er in live:
            limit = min(self._chain_pos0[er.slot] + (n + 1) * advance,
                        cfg.max_model_len - 1)
            if not self._ensure_block_for(er, limit):
                self.allocator.flush_offload()
                self._note_sync_fallback("kv_oom")
                await self._chain_barrier(loop)
                rest = [er for er in active if er.finish is None]
                if rest:
                    await self._decode(loop, rest, sync_steps)
                return False
            self._host.sync_blocks(er)
        self.allocator.flush_offload()
        return True

    def _chain_masks(self, members, live):
        """(commit mask, block-table slice) for one chained dispatch."""
        cfg = self.config
        commit = np.zeros(cfg.max_batch_size, bool)
        for er in members:
            commit[er.slot] = er.finish is None
        w = cfg.kv_width_bucket(max(len(er.block_ids) for er in live))
        return commit, self._host.btab[:, :w].copy()

    def _chain_fill(self, live, with_guided):
        """The chain-fill carry from committed host state (first
        dispatch of a chain). Spec chains carry no guided cursors
        (spec-eligible rows are unguided by admission)."""
        b = self.config.max_batch_size
        tokens0 = np.zeros(b, np.int32)
        positions0 = np.zeros(b, np.int32)
        gen0 = np.zeros(b, np.int32)
        done0 = np.zeros(b, bool)
        ring0 = np.full((b, SUFFIX_RING_W), -1, np.int32)
        gstate0 = np.full(b, -1, np.int32)
        for er in live:
            tokens0[er.slot] = er.pending_token
            positions0[er.slot] = er.context_len
            gen0[er.slot] = er.generated
            ring0[er.slot] = ring_init(er.ring_tail)
            if with_guided and er.guided is not None:
                gstate0[er.slot] = self._guided_tables[
                    self._guided_table_key(er)].state_id(er.guided)
        return tokens0, positions0, gen0, done0, ring0, gstate0

    def _chain_observe_bubble(self, tokens0) -> None:
        """Device-idle bookkeeping (same approximation as the pipelined
        path): a carry already materialized at dispatch time means the
        device ran dry since the last reconciliation. Must run BEFORE
        the dispatch consumes ``self._chain_carry``."""
        now = time.monotonic()
        if self._last_burst_done_t is not None:
            if self._chain_carry is None:
                self._bubble_hist.observe(now - self._last_burst_done_t)
            else:
                ready = getattr(tokens0, "is_ready", lambda: True)()
                self._bubble_hist.observe(
                    now - self._last_burst_done_t if ready else 0.0
                )
        self._last_burst_done_t = None

    async def _chain_drain(self, loop, members) -> None:
        """Asynchronous row drain after a chained dispatch: reconcile
        every burst whose outputs already materialized (never gating the
        dispatch), enforce the in-flight bound, and close the chain when
        every member finished (anything still queued is frozen
        over-decode)."""
        while self._chain and self._chain_ready(self._chain[0]):
            await self._apply_chain_head(loop)
        while len(self._chain) >= self.CHAIN_MAX_INFLIGHT:
            await self._apply_chain_head(loop)
        if all(er.finish is not None for er in members):
            await self._chain_barrier(loop)

    async def _decode_chained(self, loop,
                              active: List[EngineRequest]) -> None:
        """One persistent-loop pass: dispatch the next burst straight off
        the device-resident carry — WITHOUT waiting for any previous
        burst's host reconciliation — then drain whatever bursts have
        already materialized.

        Finished rows freeze inside the burst program (no sampling, no
        KV writes, -1 pads out), so membership never changes mid-chain:
        the commit mask marks members, the device ``done`` mask marks
        frozen rows, and rows cancelled on the host simply drop out of
        the commit mask at the next dispatch. Block headroom is tracked
        against the chain's own dispatch count (the host's committed
        ``context_len`` lags by the whole drain queue), capped at the
        model-len horizon — the device's LENGTH check freezes rows there,
        so near-horizon rows stay chained instead of forcing sync.
        """
        cfg = self.config
        k_steps = max(1, cfg.multi_step_decode)
        opened = await self._chain_prologue(loop, active, "plain")
        if opened is None:
            return
        active, live, members = opened
        n = self._chain_dispatched
        if not await self._chain_reserve(loop, active, live, k_steps,
                                         k_steps):
            return

        hs = self._host
        commit, btab = self._chain_masks(members, live)
        want_top = any(er.logprobs_n > 0 for er in members)
        # guided members ride the device transition table: ONE table per
        # chain (_chain_block_reason enforced it), their bias rows reset
        # to logit_bias-only so the in-program mask is not double-applied
        # (the barrier reinstalls the host mask)
        gtable_dev = None
        guided_live = [er for er in live if er.guided is not None]
        if guided_live:
            table = self._guided_tables[
                self._guided_table_key(guided_live[0])]
            bucket = self.runner.guided_state_bucket(table.n_states)
            gtable_dev = table.device(bucket)
            for er in guided_live:
                if not er.chain_bias_reset:
                    self._set_plain_bias(er)
                    er.chain_bias_reset = True
        if self._chain_carry is None:
            (tokens0, positions0, gen0, done0, ring0,
             gstate0) = self._chain_fill(live, with_guided=True)
        else:
            (tokens0, positions0, gen0, done0, ring0,
             gstate0) = self._chain_carry

        self._chain_observe_bubble(tokens0)

        toks, lps, tv, ti, carry = self.runner.decode_burst_chained(
            tokens0, positions0, gen0, done0, btab,
            hs.temp, hs.top_k, hs.top_p,
            min_p=hs.min_p, presence_penalty=hs.pres,
            frequency_penalty=hs.freq, repetition_penalty=hs.rep,
            seed_keys=hs.keys, commit=commit, stop_ids=hs.stop_ids,
            min_new=hs.min_new, max_new=hs.max_new,
            ring0=ring0, gstate0=gstate0,
            stop_hash=hs.stop_hash, stop_hlen=hs.stop_hlen,
            gtable=gtable_dev, want_top=want_top,
        )
        self._chain_carry = carry
        self._chain_dispatched += 1
        self.steps += 1
        self.pipeline_bursts += 1
        self.flight.record(
            "scheduler.burst_dispatch", k_steps=k_steps, rows=len(live),
            pipelined=True, chained=True,
            chain_len=self._chain_dispatched,
            requests=[er.request_id for er in live[:8]],
        )
        dt = self.device_time
        self._chain.append(_InflightBurst(
            active=list(live), toks=toks, lps=lps, tv=tv, ti=ti,
            k_steps=k_steps, last_tokens=None,
            dispatch_t=time.monotonic(), device_finish=True,
            read_bytes=dt.decode_read_bytes(
                k_steps,
                sum(min(self._chain_pos0[er.slot] + n * k_steps,
                        cfg.max_model_len) for er in live),
            ) if dt is not None else 0.0,
            tokens=k_steps * len(live),
        ))
        await self._chain_drain(loop, members)

    async def _decode_chained_spec(self, loop,
                                   active: List[EngineRequest]) -> None:
        """One chained propose-verify pass: ONE spec round dispatched
        straight off the device-resident carry — proposals from the
        carry's trailing-token ring (ngram) or from the draft model's
        chained burst on the SAME carry (draft), verified by one
        S = K+1-position forward whose accepted prefix + correction
        commit with the plain chain's freeze semantics. No host barrier
        between rounds: the draft consumes the target's device carry
        directly, acceptance folds into the carry on device, and the
        async row drain reconciles rounds as their outputs materialize
        (per-row acceptance lengths ride back for the
        dynamo_engine_spec_accept_length histogram).
        """
        cfg = self.config
        b = cfg.max_batch_size
        P = (cfg.spec_draft_tokens if self.draft is not None
             else cfg.spec_ngram_tokens)
        S = P + 1
        opened = await self._chain_prologue(loop, active, "spec")
        if opened is None:
            return
        active, live, members = opened
        # headroom: a round advances a never-frozen row by at most S
        # positions (accepted prefix + correction); near-horizon rounds
        # never dispatch (_spec_chain_reason barriers them first)
        n = self._chain_dispatched
        if not await self._chain_reserve(loop, active, live, S, 1):
            return

        hs = self._host
        commit, btab = self._chain_masks(members, live)
        if self._chain_carry is None:
            (tokens0, positions0, gen0, done0, ring0,
             gstate0) = self._chain_fill(live, with_guided=False)
        else:
            (tokens0, positions0, gen0, done0, ring0,
             gstate0) = self._chain_carry

        props = None
        if self.draft is not None:
            # draft round chained off the SAME carry: its burst consumes
            # the target's device-resident tokens/positions and its
            # commit mask is gated by the device done carry — no host
            # barrier anywhere in the draft → verify round trip
            import jax.numpy as jnp

            commit_dev = jnp.logical_and(
                jnp.asarray(commit),
                jnp.logical_not(jnp.asarray(done0, jnp.bool_)),
            )
            dtemp, dtop_k, dtop_p, dkw = self._inert_sampling(b)
            dtoks, *_ = self.draft.decode_burst(
                tokens0, positions0, btab, dtemp, dtop_k, dtop_p,
                commit=commit_dev, want_top=False, **dkw,
            )
            props = jnp.transpose(dtoks[:P])  # [B, P] device proposals
            self.steps += 1

        self._chain_observe_bubble(tokens0)

        toks, nprop, nacc, carry = self.runner.decode_burst_spec(
            tokens0, positions0, gen0, done0, ring0, gstate0, btab,
            commit=commit, stop_ids=hs.stop_ids, min_new=hs.min_new,
            max_new=hs.max_new, stop_hash=hs.stop_hash,
            stop_hlen=hs.stop_hlen, proposals=props,
        )
        self._chain_carry = carry
        self._chain_dispatched += 1
        self.steps += 1
        self.pipeline_bursts += 1
        self.flight.record(
            "scheduler.burst_dispatch", k_steps=S, rows=len(live),
            pipelined=True, chained=True, spec=True,
            chain_len=self._chain_dispatched,
            requests=[er.request_id for er in live[:8]],
        )
        dt = self.device_time
        self._chain.append(_InflightBurst(
            active=list(live), toks=toks, lps=None, tv=None, ti=None,
            k_steps=S, last_tokens=None,
            dispatch_t=time.monotonic(), device_finish=True,
            spec=True, nprop=nprop, nacc=nacc,
            read_bytes=dt.decode_read_bytes(
                1,
                sum(min(self._chain_pos0[er.slot] + n * S + S,
                        cfg.max_model_len) for er in live),
            ) if dt is not None else 0.0,
            tokens=len(live),
        ))
        await self._chain_drain(loop, members)

    def _set_plain_bias(self, er: EngineRequest) -> None:
        """Reset one slot's bias row to the request's logit_bias alone —
        a device-table chain computes the guided mask in-program, so the
        host-installed mask must not double-apply."""
        v = self.config.model.vocab_size
        row = np.zeros(v, np.float32)
        for tid, bv in (er.req.sampling_options.logit_bias or {}).items():
            tid = int(tid)
            if 0 <= tid < v:
                row[tid] += float(bv)
        self.runner.set_bias_row(er.slot, row)

    def _reinstall_guided_mask(self, er: EngineRequest) -> None:
        """Back to host-masked guided decoding (chain closed): rebuild
        the dense mask from the CURRENT cursor state — the drain
        advanced the host cursor token-by-token, so it is exact."""
        mask = self._guided_mask(er)
        for tid, bv in (er.req.sampling_options.logit_bias or {}).items():
            tid = int(tid)
            if 0 <= tid < len(mask):
                mask[tid] += float(bv)
        self.runner.set_bias_row(er.slot, mask)

    async def _chain_barrier(self, loop) -> None:
        """Host barrier: reconcile every queued chained burst and close
        the chain — the ONLY place chain membership compacts. Runs before
        admission-driven sync passes, preemption, program-family
        switches, and shutdown."""
        if not self._chain and not self._chain_members:
            return
        bursts = self._chain_dispatched
        while self._chain:
            await self._apply_chain_head(loop)
        if self._chain_members:
            self.flight.record(
                "scheduler.burst_drain", chained=True, bursts=bursts,
                rows=len(self._chain_members),
            )
            for er in self._chain_members:
                er.chain_fp = False
                if er.chain_bias_reset:
                    er.chain_bias_reset = False
                    if er.finish is None and er.guided is not None:
                        self._reinstall_guided_mask(er)
                if er.finish is None and er.pipeline_span_open:
                    er.ctx.add_stage("decode_pipeline")
                    er.pipeline_span_open = False
        if bursts:
            self._last_chain_len = bursts
        self._chain_members = []
        self._chain_carry = None
        self._chain_dispatched = 0
        self._chain_pos0 = {}
        self._chain_kind = None
        self._chain_fp = False

    # ---------- cluster KV fabric: prefix pull (kv/fabric.py) ----------

    def _try_submit_pull(self, er: EngineRequest) -> bool:
        """Start a prefix pull for this waiting request?

        Engages when the fabric's ownership view (peer KV events, cold
        tier index) holds a longer prefix run than every local tier.
        The request reserves its FULL prompt allocation now (exactly
        like a remote-prefill submit), pins the pull targets, and waits
        in ``pending_pull`` while the transfer streams — the scheduler
        keeps serving everyone else. One attempt per request: any
        failure falls back to plain local prefill, byte-identically.
        """
        if (er.pull_attempted or er.resume_tokens
                or (er.want_prompt_lps and not er.prompt_lps_emitted)):
            # resumed streams re-prefill prompt+resume (no pullable
            # chain for the generated tail); prompt-logprob requests
            # must run every position through the model anyway
            return False
        if time.monotonic() < er.pull_backoff_until:
            return False
        probe = self.allocator.probe_prefix(er.prompt)
        hashes, local_blocks, host_hashes = probe
        n_local = len(local_blocks) + len(host_hashes)
        plan = self.fabric.plan(hashes, n_local, len(er.prompt))
        if plan is None:
            # nothing worth pulling right now: don't re-hash the whole
            # prompt on every loop pass while the request queues
            er.pull_backoff_until = time.monotonic() + 0.25
            return False
        planned = set(plan.hashes)
        for other in self.pending_pull:
            if (other.pull is not None
                    and not planned.isdisjoint(other.pull.plan.hashes)):
                # a pull already in flight fetches (part of) this run —
                # its commit registers the prefix for everyone, so HOLD
                # this request out of local admission until the pull
                # resolves instead of transferring (or recomputing) the
                # same blocks N× (the shared-system-prompt burst on a
                # cold worker). Commit/fallback clear the hold early;
                # the pull's own deadline bounds it.
                er.pull_backoff_until = time.monotonic() + 0.05
                er.pull_hold_until = other.pull.deadline
                return False
        try:
            er.block_ids, er.num_cached = self.allocator.allocate_prompt(
                er.prompt, probe=probe
            )
        except MemoryError:
            # transient — the pull stays worth trying once memory frees
            # (only an actual transfer attempt burns the one shot)
            er.pull_backoff_until = time.monotonic() + 0.25
            return False
        bs = self.config.kv_block_size
        if er.num_cached // bs != plan.start_block:
            # the local hit shrank inside allocate_prompt (host-tier
            # capacity eviction raced the probe): the planned run no
            # longer abuts the cached prefix — abandon the pull (a
            # re-plan against the new local state may still pull)
            self.allocator.free_blocks(er.block_ids)
            er.block_ids = []
            er.num_cached = 0
            er.pull_backoff_until = time.monotonic() + 0.25
            return False
        er.pull_attempted = True
        targets = er.block_ids[
            plan.start_block:plan.start_block + plan.blocks
        ]
        self.allocator.pin_blocks(targets)
        task = asyncio.get_running_loop().create_task(
            self.fabric.pull(
                plan, targets, request_id=er.request_id,
                trace_id=er.ctx.trace_id,
            ),
            name=f"kv-pull-{er.request_id[:8]}",
        )
        task.add_done_callback(lambda _f: self.wake.set())
        er.pull = _PendingPull(
            plan=plan, task=task, targets=targets, hashes=hashes,
            deadline=time.monotonic() + self.fabric.pull_timeout_s,
        )
        self.flight.record(
            "scheduler.pull_submit", request_id=er.request_id,
            trace_id=er.ctx.trace_id, source=plan.source,
            worker=plan.worker_id, blocks=plan.blocks,
        )
        self.pending_pull.append(er)
        return True

    def _reap_pulls(self) -> bool:
        """Commit finished pulls, fall back on failures and deadlines."""
        progressed = False
        now = time.monotonic()
        for er in list(self.pending_pull):
            pp: _PendingPull = er.pull
            if er.ctx.is_stopped:
                self.pending_pull.remove(er)
                self._release_pull(er)
                self._finish(er, FinishReason.CANCELLED)
                # requests held on THIS pull must not wait out its
                # stale deadline after a client disconnect
                self._clear_pull_holds()
                progressed = True
            elif pp.task.done():
                self.pending_pull.remove(er)
                served, reason = 0, "empty"
                if not pp.task.cancelled():
                    try:
                        served = pp.task.result()
                    except Exception as e:
                        reason = "error"
                        logger.warning(
                            "prefix pull failed for %s (%s); local "
                            "recompute fallback", er.request_id, e,
                        )
                if served > 0:
                    self._commit_pull(er, served)
                else:
                    self._fallback_pull(er, reason)
                progressed = True
            elif now > pp.deadline:
                # a dead/stalled source must never hold the request:
                # cancel the transfer and recompute locally
                pp.task.cancel()
                self.pending_pull.remove(er)
                self._fallback_pull(er, "timeout")
                progressed = True
        return progressed

    def _release_pull(self, er: EngineRequest) -> None:
        """Unwind a pull's reservation state (task + pins). Blocks stay
        with the request — commit registers them, fallback/finish frees
        them."""
        pp: _PendingPull = er.pull
        er.pull = None
        if not pp.task.done():
            pp.task.cancel()
        self.allocator.unpin_blocks(pp.targets)

    def _commit_pull(self, er: EngineRequest, served: int) -> None:
        """A pull landed ``served`` blocks: register the content-
        addressed prefix (matchable + KV events, exactly as if this
        engine had computed it) and re-queue for the tail prefill."""
        pp: _PendingPull = er.pull
        self._release_pull(er)
        bs = self.config.kv_block_size
        for i in range(served):
            idx = pp.plan.start_block + i
            parent = pp.hashes[idx - 1] if idx > 0 else None
            self.allocator.register_complete(
                pp.targets[i], pp.hashes[idx], parent
            )
        er.num_cached += served * bs
        er.pull_ready = True
        # closing-mark semantics: the wait-and-transfer span since the
        # queued mark is the fabric's — the tail prefill's own span
        # follows under "prefill"
        er.ctx.add_stage("kv_fabric")
        self.flight.record(
            "scheduler.pull_commit", request_id=er.request_id,
            trace_id=er.ctx.trace_id, source=pp.plan.source,
            blocks=served, cached_tokens=er.num_cached,
        )
        self.waiting.appendleft(er)
        self._clear_pull_holds()
        self.wake.set()

    def _clear_pull_holds(self) -> None:
        """A pull resolved (commit or fallback): release every waiting
        request held for it — their next pass re-probes against the
        new local state (commit → the prefix is now a local hit)."""
        for w in self.waiting:
            w.pull_hold_until = 0.0
            w.pull_backoff_until = 0.0

    def _fallback_pull(self, er: EngineRequest, reason: str) -> None:
        """Pull failed/expired/served nothing: release everything and
        recompute locally. The stream is byte-identical to the
        no-fabric run — nothing was registered, so the allocator state
        matches a fresh admission exactly."""
        self._release_pull(er)
        self.allocator.free_blocks(er.block_ids)
        er.block_ids = []
        er.num_cached = 0
        # marker span (the "preempted"/"remote_fallback" idiom): the
        # pull wait is attributable, and the second "queued" epoch in
        # the trace is a fallback re-admission, not a bug
        er.ctx.add_stage("pull_fallback")
        self.flight.record(
            "kv_fabric.local_fallback", request_id=er.request_id,
            trace_id=er.ctx.trace_id, reason=reason,
        )
        self.waiting.appendleft(er)
        self._clear_pull_holds()
        self.wake.set()

    # ---------- disaggregated prefill (decode side) ----------

    async def _try_submit_remote(self, er: EngineRequest) -> bool:
        """Conditional disagg: enqueue this prompt for remote prefill?

        Mirrors the decode worker's decision point (reference:
        examples/llm/components/worker.py:180-195 — disagg router verdict
        from prompt length, prefix-hit length, and prefill queue depth).
        """
        if er.remote_attempted:
            return False  # already tried remote once — prefill locally
        if er.pull_ready:
            # a committed prefix pull pre-allocated this request's
            # blocks; the (now small) tail prefills locally
            return False
        if time.monotonic() < er.remote_backoff_until:
            return False
        if er.resume_tokens:
            # preempted stream: only the local path knows to re-prefill
            # prompt + resume_tokens; the remote path would restart the
            # stream from the prompt alone
            return False
        if er.want_prompt_lps:
            # prompt logprobs need every position's logits on THIS engine
            # (the remote protocol ships KV + one sampled token, not a
            # [S, V] logits sweep) — prefill locally
            return False
        if (er.req.sampling_options.guided_choice_token_ids
                or er.req.sampling_options.guided_json
                or er.guided is not None):
            # the remote prefill samples the FIRST token without this
            # engine's guided mask — constrained requests (choice trie
            # OR json grammar) prefill locally
            return False
        # the long-prefill admission class (docs/long_context.md): in
        # disagg mode, prompts past the threshold PREFER the prefill
        # pool regardless of the router's length/queue heuristics — the
        # pool's workers run the SP chunk ladder, and a 128k prompt on
        # this engine's dense ladder would head-of-line-block decode far
        # longer than any queue wait (the in-flight cap in _run still
        # bounds the submit count). Engines with their own SP mesh keep
        # the router's verdict: the local ladder is just as parallel.
        force_long = (
            self.config.long_prefill_threshold_tokens > 0
            and not getattr(self.runner, "sp_ready", False)
            and len(er.prompt) >= self.config.long_prefill_threshold_tokens
        )
        # cheap pre-check before the (hash-the-whole-prompt) prefix probe:
        # a larger prefix hit can only make the uncached suffix smaller,
        # so a prompt that doesn't qualify with hit=0 never qualifies —
        # and this loop runs for EVERY waiting request EVERY pass
        if not force_long and not self.disagg.decide(len(er.prompt), 0):
            return False
        probe = self.allocator.probe_prefix(er.prompt)
        # host-tier blocks count as hit: restoring them locally is far
        # cheaper than a remote prefill round-trip
        prefix_hit = self.allocator.cached_tokens(probe)
        # a big local prefix hit can shrink the suffix back under the
        # threshold — then the class no longer applies
        if force_long and len(er.prompt) - prefix_hit < \
                self.config.long_prefill_threshold_tokens:
            force_long = False
        if not force_long and not self.disagg.decide(len(er.prompt),
                                                     prefix_hit):
            # rejected on the hit term. NOT permanent: cached prefixes can
            # be evicted and the router threshold is live-tunable — back
            # off instead, so the (whole-prompt) probe doesn't re-run on
            # every scheduler pass while conditions are unchanged
            er.remote_backoff_until = time.monotonic() + 0.25
            return False
        er.remote_attempted = True
        try:
            er.block_ids, er.num_cached = self.allocator.allocate_prompt(
                er.prompt, probe=probe
            )
        except MemoryError:
            return False
        try:
            er.remote_future = await self.disagg.submit(
                er.request_id, er.prompt, er.block_ids, er.num_cached,
                temperature=er.temperature, top_k=er.top_k, top_p=er.top_p,
                min_p=er.min_p, presence_penalty=er.presence_penalty,
                frequency_penalty=er.frequency_penalty,
                repetition_penalty=er.repetition_penalty,
                seed=er.req.sampling_options.seed,
                want_logprobs=er.want_logprobs,
                logprobs_n=er.logprobs_n,
                logit_bias=er.req.sampling_options.logit_bias,
                trace_id=er.ctx.trace_id,
                ctx=er.ctx,  # kv_transfer stage mark stamped at commit
            )
        except Exception:
            # queue unreachable — release and let the local path take it
            logger.exception("remote prefill submit failed for %s; going local",
                             er.request_id)
            self.allocator.free_blocks(er.block_ids)
            er.block_ids = []
            er.num_cached = 0
            return False
        self.prefix_hit_tokens += er.num_cached
        self.prefix_total_tokens += len(er.prompt)
        er.ctx.add_stage("admission")
        self.flight.record(
            "scheduler.remote_submit", request_id=er.request_id,
            trace_id=er.ctx.trace_id, prompt_tokens=len(er.prompt),
            cached=er.num_cached,
        )
        er.remote_deadline = time.monotonic() + self.disagg.prefill_timeout_s
        er.remote_future.add_done_callback(lambda _f: self.wake.set())
        self.pending_remote.append(er)
        return True

    def _reap_remote(self) -> bool:
        """Install completed remote prefills; handle cancels and timeouts."""
        progressed = False
        now = time.monotonic()
        for er in list(self.pending_remote):
            if er.ctx.is_stopped:
                self.pending_remote.remove(er)
                self.disagg.cancel(er.request_id)
                self._finish(er, FinishReason.CANCELLED)
                progressed = True
                continue
            fut = er.remote_future
            if fut.done() and not fut.cancelled():
                slot = self._free_slot()
                if slot is None:
                    break  # keep completion ordering; wait for a slot
                self.pending_remote.remove(er)
                self._install_remote(er, slot)
                progressed = True
            elif now > er.remote_deadline:
                # prefill worker lost / queue starved — fall back to local
                logger.warning("remote prefill timeout for %s; local fallback",
                               er.request_id)
                self.pending_remote.remove(er)
                self.disagg.cancel(er.request_id, reason="timeout")
                self.flight.record(
                    "disagg.local_fallback", request_id=er.request_id,
                    trace_id=er.ctx.trace_id, reason="timeout",
                )
                self.allocator.free_blocks(er.block_ids)
                er.block_ids = []
                er.num_cached = 0
                er.remote_future = None
                # marker span (same idiom as "preempted"): the second
                # "admission" in the trace is a fallback re-admission,
                # not a bug — and the remote wait is attributable to it
                er.ctx.add_stage("remote_fallback")
                self.waiting.appendleft(er)
                progressed = True
        return progressed

    def _install_remote(self, er: EngineRequest, slot: int) -> None:
        """A remote prefill committed — enter the decode loop.

        The prefill worker already wrote the KV blocks into our cache and
        sampled the first token (max_tokens=1 semantics, reference:
        examples/llm/components/prefill_worker.py:148-178)."""
        token, lp, top = er.remote_future.result()
        er.ctx.add_stage("remote_prefill")
        er.remote_future = None
        er.slot = slot
        self.slots[slot] = er
        self._host.install(er)
        er.context_len = len(er.prompt)
        er.pending_token = token
        er.generated = 1
        # penalty/PRNG state for the decode steps this slot is entering
        self.runner.set_sample_row(
            slot, er.prompt, [token],
            logit_bias=er.req.sampling_options.logit_bias,
        )
        er.seq = TokenSequence(er.prompt, block_size=self.config.kv_block_size)
        self._register_completed_blocks(er)
        er.ring_tail.clear()
        er.ring_tail.extend(er.prompt[-SUFFIX_RING_W:])
        er.ring_tail.append(token)
        er.finish = self._check_finish(er, token)
        if top and er.logprobs_n > 0:
            top = dict(list(top.items())[: er.logprobs_n])
        else:
            top = None
        self._emit(er, token, lp if er.want_logprobs else None, top)
        if er.finish is not None:
            self._finish(er, er.finish, emit=False)

    def _start_prefill(self, er: EngineRequest) -> None:
        """Claim a slot + KV blocks and enter the chunked-prefill state.

        A preempted request resumes here: ``prompt + resume_tokens`` is
        re-prefilled so the emitted stream *continues* from where it left
        off instead of restarting (vLLM recompute-preemption semantics)."""
        slot = self._free_slot()
        assert slot is not None
        er.ctx.add_stage("admission")
        self.flight.record(
            "scheduler.admission", request_id=er.request_id,
            trace_id=er.ctx.trace_id, slot=slot,
            prompt_tokens=len(er.prompt), resumed=bool(er.resume_tokens),
        )
        tokens_all = er.prompt + er.resume_tokens
        # ring tail mirrors the emitted history (a resumed request's
        # replayed tail included) so stop-seq checks and chain fills
        # continue exactly where the stream left off
        er.ring_tail.clear()
        er.ring_tail.extend(tokens_all[-SUFFIX_RING_W:])
        if er.pull_ready and er.block_ids:
            # a committed prefix pull already allocated the blocks,
            # scattered the pulled run, and registered it (num_cached
            # covers local + pulled) — only the tail prefills below
            er.pull_ready = False
        elif er.want_prompt_lps and not er.prompt_lps_emitted:
            # every prompt position must run through the model — a prefix
            # cache hit would skip its logits. Blank the probe's hits so
            # allocation proceeds with zero cached tokens. (A resumed
            # request that already emitted them uses the cache normally.)
            probe = self.allocator.probe_prefix(tokens_all)
            er.block_ids, er.num_cached = self.allocator.allocate_prompt(
                tokens_all, probe=(probe[0], [], [])
            )
        else:
            er.block_ids, er.num_cached = self.allocator.allocate_prompt(tokens_all)
        if not er.remote_attempted:  # remote fallback already counted itself
            self.prefix_hit_tokens += er.num_cached
            self.prefix_total_tokens += len(tokens_all)
        er.prefill_tokens = tokens_all
        er.prefill_pos = er.num_cached
        er.context_len = er.num_cached
        er.slot = slot
        self.slots[slot] = er
        self._host.install(er)
        er.seq = TokenSequence(tokens_all, block_size=self.config.kv_block_size)
        er.registered_blocks = 0
        # guided decoding: (re)build the constraint and walk it past any
        # already-emitted tokens (a resumed request continues mid-stream)
        gids = er.req.sampling_options.guided_choice_token_ids
        if gids:
            er.guided = TrieConstraint(gids)
        elif er.guided is not None:
            er.guided.reset()  # json constraint attached by serving
        if er.guided is not None:
            for t in er.resume_tokens:
                if er.guided.advance(int(t)) != "ok":
                    # derailed resume (tokens that never followed the
                    # mask — unreachable in normal operation): an
                    # all-banned mask would still emit one unconstrained
                    # token (an additive constant constrains nothing),
                    # so finish the stream here instead
                    self._finish(er, FinishReason.STOP)
                    return
            if not self._guided_allowed_ids(er):
                # dead state: the vocab cannot express any legal
                # continuation (serving validates expressibility at
                # grammar build, so this is a defensive backstop)
                self._finish(er, FinishReason.STOP)
                return
        # penalty state for the slot: prompt presence + (on resume) counts
        # of the already-generated tokens (+ the guided mask for the
        # FIRST sampled token — the prefill's final chunk samples it)
        self.runner.set_sample_row(
            slot, er.prompt, er.resume_tokens,
            logit_bias=er.req.sampling_options.logit_bias,
            guided_mask=(
                self._guided_mask(er) if er.guided is not None else None
            ),
        )
        if self._sp_eligible(er):
            # long-context admission class: the whole mesh prefills this
            # one prompt, a sequence-sharded chunk per pass
            self.sp_queue.append(er)
        else:
            self.prefilling.append(er)

    # ---------- sequence-parallel long-context prefill ----------

    def _sp_eligible(self, er: EngineRequest) -> bool:
        """Route this admission to the sequence-parallel ladder?

        The SP program exists (sp_size > 1, supported trunk), the
        uncached suffix crosses the admission threshold, and nothing in
        the request needs the dense ladder's full-S head (prompt
        logprobs) or a mirrored draft cache (the draft has no SP
        program — its chunk replay would go stale)."""
        cfg = self.config
        if not (getattr(self.runner, "sp_ready", False)
                and cfg.long_prefill_threshold_tokens > 0):
            return False
        suffix = len(er.prefill_tokens) - er.num_cached
        if suffix < cfg.long_prefill_threshold_tokens:
            return False
        if er.want_prompt_lps and not er.prompt_lps_emitted:
            return False
        return self.draft is None

    def _is_sp(self, er: EngineRequest) -> bool:
        return (self.sp_active is not None and self.sp_active.er is er) \
            or er in self.sp_queue

    def _sp_kernel_route(self) -> bool:
        """Did the SP ladder's chunk attention take the paged-DMA
        kernel route (parallel/sequence.sp_chunk_attention)? Drives the
        device-time byte model: the kernel streams the committed prefix
        once; the XLA gather pays a materialize write + re-read."""
        from ..ops.attention import resolve_attention_impl

        return resolve_attention_impl(
            self.config.model.attention_impl) == "pallas"

    def _sp_drop(self, er: EngineRequest) -> None:
        """Remove a cancelled/finished request from the SP ladder. Any
        already-dispatched chunk work is pure over-compute into the
        request's own blocks — freed with the request, nothing leaks."""
        if self.sp_active is not None and self.sp_active.er is er:
            self.sp_active = None
        if er in self.sp_queue:
            self.sp_queue.remove(er)

    async def _sp_advance(self, loop) -> bool:
        """One pass of the SP ladder: dispatch the active request's next
        mesh-wide chunk (dispatch-only — the device runs ahead while the
        loop serves decode), register the previously completed chunk's
        blocks into the prefix cache, and on the final chunk run the
        early decode handoff + drain."""
        st = self.sp_active
        while st is None and self.sp_queue:
            er = self.sp_queue.pop(0)
            if er.finish is not None or er.ctx.is_stopped:
                continue
            st = self.sp_active = _SpPrefill(er=er, t0=time.monotonic())
        if st is None:
            return False
        er = st.er
        if er.finish is not None or er.ctx.is_stopped:
            self.sp_active = None
            if er.finish is None:
                self._finish(er, FinishReason.CANCELLED)
            return True
        total = len(er.prefill_tokens)
        start = er.prefill_pos
        end = min(start + self.runner.sp_chunk_tokens, total)
        final = end >= total
        t_disp = time.monotonic()
        outs = self.runner.sp_prefill_chunk(
            er.prefill_tokens[:end], start, er.block_ids,
            temperature=er.temperature, top_k=er.top_k, top_p=er.top_p,
            min_p=er.min_p, presence_penalty=er.presence_penalty,
            frequency_penalty=er.frequency_penalty,
            repetition_penalty=er.repetition_penalty,
            seed_keys=er.base_key, counters=er.generated,
            sample_slot=er.slot, commit=final,
            want_top=final and er.logprobs_n > 0,
        )
        self.steps += 1
        st.chunks += 1
        self._sp_chunks_c.inc()
        self._sp_tokens_c.inc(end - start)
        er.prefill_pos = end
        er.context_len = end
        # chunk-commit seam: the chunk's blocks become matchable (and KV
        # events publish, feeding fabric ownership) as soon as the write
        # is SCHEDULED — device dispatch order guarantees it lands
        # before any later program reads it, the same contract the dense
        # ladder and the disagg streamed transfer rely on
        self._register_completed_blocks(er)
        self.flight.record(
            "scheduler.sp_chunk", request_id=er.request_id,
            trace_id=er.ctx.trace_id, start=start, end=end, final=final,
            chunk=st.chunks,
        )
        if not final:
            return True
        st.final_dispatch_t = t_disp
        try:
            await self._sp_finish(loop, st, outs)
        finally:
            self.sp_active = None
        return True

    async def _sp_finish(self, loop, st: _SpPrefill, outs) -> None:
        """Early decode handoff + drain for a finished SP ladder.

        The final chunk's sampled token is still device-resident; when
        the request can take a plain decode burst, dispatch one
        IMMEDIATELY with that token composed into the batch row on
        device — the first decode burst is then executing before any
        host sync of the prefill outputs happens (the overlap the tests
        pin). One executor sync drains both; emission runs the exact
        dense-path discipline (tokens past a finish are discarded with
        the request's own blocks)."""
        er = st.er
        cfg = self.config
        next_tokens, lps, top_vals, top_ids = outs
        hs = self._host
        b = cfg.max_batch_size
        bs = cfg.kv_block_size
        ctx0 = er.context_len  # the first sampled token's position
        k_steps = cfg.multi_step_decode
        burst = None
        can_burst = (
            self.runner._burst is not None
            and er.guided is None
            and er.max_new > 1
            and ctx0 + k_steps + 1 <= cfg.max_model_len
            and all(self._ensure_block_for(er, ctx0 + j)
                    for j in range(k_steps))
        )
        # allocator contract (same as every dense dispatch site): any
        # host-offload gathers the block growth above deferred must
        # materialize BEFORE the burst overwrites the evicted slots
        self.allocator.flush_offload()
        if can_burst:
            hs.sync_blocks(er)
            w = cfg.kv_width_bucket(len(er.block_ids))
            btab = hs.btab[:, :w].copy()
            import jax.numpy as jnp
            tok0 = jnp.zeros(b, jnp.int32).at[er.slot].set(next_tokens[0])
            pos0 = np.zeros(b, np.int32)
            pos0[er.slot] = ctx0
            ctrs = np.zeros(b, np.int32)
            ctrs[er.slot] = er.generated + 1  # after the prefill token
            commit = np.zeros(b, bool)
            commit[er.slot] = True
            t_burst = time.monotonic()
            burst = self.runner.decode_burst(
                tok0, pos0, btab, hs.temp, hs.top_k, hs.top_p,
                min_p=hs.min_p, presence_penalty=hs.pres,
                frequency_penalty=hs.freq, repetition_penalty=hs.rep,
                seed_keys=hs.keys, counters=ctrs, commit=commit,
                want_top=er.logprobs_n > 0,
            )
            self.steps += 1
            self._sp_exposed_h.observe(t_burst - st.final_dispatch_t)
            self.flight.record(
                "scheduler.sp_handoff", request_id=er.request_id,
                trace_id=er.ctx.trace_id, k_steps=k_steps,
            )

        def _sync():
            out = [np.asarray(next_tokens), np.asarray(lps),
                   np.asarray(top_vals), np.asarray(top_ids)]
            if burst is not None:
                out.extend(np.asarray(x) for x in burst)
            return out

        t_sync = time.monotonic()
        synced = await loop.run_in_executor(None, _sync)
        t_done = time.monotonic()
        self._observe_host_sync(t_done - t_sync)
        if burst is None:
            self._sp_exposed_h.observe(t_done - st.final_dispatch_t)
        if self.device_time is not None:
            self.device_time.observe(
                "prefill_sp", "prefill", st.final_dispatch_t, t_done,
                read_bytes=self.device_time.sp_prefill_read_bytes(
                    st.chunks, er.context_len,
                    kernel=self._sp_kernel_route(),
                ),
            )
            if burst is not None:
                self.device_time.observe(
                    "decode_burst", "decode", t_burst, t_done,
                    read_bytes=self.device_time.decode_read_bytes(
                        k_steps, er.context_len,
                    ),
                    tokens=k_steps,
                )
        self.flight.record(
            "scheduler.sp_drain", request_id=er.request_id,
            trace_id=er.ctx.trace_id, chunks=st.chunks,
            handoff=burst is not None,
        )
        toks_pf, lps_pf, tv_pf, ti_pf = synced[:4]
        er.ctx.add_stage("prefill")
        token = int(toks_pf[0])
        er.pending_token = token
        er.generated += 1
        er.ring_tail.append(token)
        er.finish = self._check_finish(er, token)
        self._guided_after_token(er)
        self._emit(
            er, token,
            float(lps_pf[0]) if er.want_logprobs else None,
            self._top_row(er, tv_pf, ti_pf, 0),
        )
        if er.finish is not None:
            # trailing burst tokens (if any) are pure over-decode into
            # the request's own blocks — freed with the request
            self._finish(er, er.finish, emit=False)
            return
        if burst is None:
            return
        toks_b, lps_b, tv_b, ti_b = synced[4:]
        for j in range(k_steps):
            if er.finish is not None or er.ctx.is_stopped:
                break
            tok_j = int(toks_b[j, er.slot])
            self._advance_row(er, tok_j)
            self._guided_after_token(er)
            self._emit(
                er, tok_j,
                float(lps_b[j, er.slot]) if er.want_logprobs else None,
                self._top_row(er, tv_b[j], ti_b[j], er.slot),
            )
            if er.finish is not None:
                self._finish(er, er.finish, emit=False)

    async def _prefill_chunk(self, loop, ers: List[EngineRequest]) -> None:
        """ONE batched prefill step: every prefilling request advances a
        chunk as a row of the same program (rows padded to the power-of-
        two ladder, lengths to the common bucket); rows that finish their
        prompt sample/emit. The token budget splits across rows."""
        cfg = self.config
        rows = cfg.prefill_row_bucket(len(ers))
        # the ITL bound is on COMPUTED positions = padded rows x padded
        # bucket, so cap the bucket at the largest that keeps
        # rows * bucket within budget (padding included), not just the
        # per-row take (prefill_bucket_cap — shared with the disagg
        # prefill worker's streamed chunking)
        cap = prefill_bucket_cap(cfg, rows)
        # a full batch can exceed the budget even at the smallest
        # bucket — admit fewer rows this step instead of overrunning
        # (the tail of `ers` stays in self.prefilling for next pass)
        while cap is None and rows > cfg.PREFILL_ROW_BUCKETS[0]:
            rows = max(r for r in cfg.PREFILL_ROW_BUCKETS if r < rows)
            ers = ers[:rows]
            cap = prefill_bucket_cap(cfg, rows)
        # budget < one row at the smallest bucket: best-effort floor
        # (a single row must still advance or prefill livelocks)
        bucket_cap = cap if cap is not None else cfg.prefill_buckets[0]
        plan = []  # (er, start, end, take, final)
        for er in ers:
            total = len(er.prefill_tokens)
            take = min(total - er.prefill_pos, bucket_cap)
            end = er.prefill_pos + take
            plan.append((er, er.prefill_pos, end, take, end >= total))
        bucket = cfg.bucket_for(max(p[3] for p in plan))  # <= bucket_cap

        tokens = np.zeros((rows, bucket), np.int32)
        positions = np.zeros((rows, bucket), np.int32)
        btab = np.zeros((rows, cfg.blocks_per_seq), np.int32)
        slot_map = np.full((rows, bucket), -1, np.int32)
        ctx_lens = np.ones(rows, np.int32)
        last_idx = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.ones(rows, np.float32)
        min_p = np.zeros(rows, np.float32)
        pres = np.zeros(rows, np.float32)
        freq = np.zeros(rows, np.float32)
        rep = np.ones(rows, np.float32)
        keys = np.zeros((rows, 2), np.uint32)
        ctrs = np.zeros(rows, np.int32)
        sample_slots = np.zeros(rows, np.int32)
        commit = np.zeros(rows, bool)
        targets = np.zeros((rows, bucket), np.int32)
        n_tgts = [0] * len(plan)
        want_prompt = False

        for i, (er, start, end, take, final) in enumerate(plan):
            t, p, bt, sm, cl, li = build_prefill_arrays(
                cfg, er.prefill_tokens[:end], start, er.block_ids,
                bucket=bucket,
            )
            tokens[i], positions[i] = t[0], p[0]
            btab[i], slot_map[i] = bt[0], sm[0]
            ctx_lens[i], last_idx[i] = cl[0], li[0]
            (temp[i], top_k[i], top_p[i], min_p[i], pres[i], freq[i],
             rep[i]) = (er.temperature, er.top_k, er.top_p, er.min_p,
                        er.presence_penalty, er.frequency_penalty,
                        er.repetition_penalty)
            keys[i] = er.base_key
            ctrs[i] = er.generated
            sample_slots[i] = er.slot
            commit[i] = final
            if er.want_prompt_lps and not er.prompt_lps_emitted:
                # target at bucket index j (absolute position start+j) is
                # the NEXT prompt token; only prompt positions count (a
                # resumed request's generation tokens are not prompt)
                want_prompt = True
                nxt = er.prefill_tokens[start + 1 : end + 1]
                targets[i, : len(nxt)] = nxt
                n_tgts[i] = max(0, min(take, len(er.prompt) - 1 - start))

        t0 = time.monotonic()
        next_tokens, lps, top_vals, top_ids, plps, _ = self.runner.step(
            tokens, positions, btab, slot_map, ctx_lens, last_idx,
            temp, top_k, top_p,
            min_p=min_p, presence_penalty=pres, frequency_penalty=freq,
            repetition_penalty=rep, seed_keys=keys, counters=ctrs,
            sample_slots=sample_slots, commit=commit,
            want_top=any(er.logprobs_n > 0 for er, *_ in plan),
            targets=targets, want_prompt=want_prompt,
        )
        self.steps += 1
        if self.draft is not None:
            # mirror the chunk on the draft model: same tokens, same
            # slots, same (shared) block ids — so the draft cache holds
            # the full context every speculative round assumes. Sampling
            # is inert (commit all-False; nothing reads the outputs).
            dtemp, dtop_k, dtop_p, dkw = self._inert_sampling(rows)
            self.draft.step(
                tokens, positions, btab, slot_map, ctx_lens, last_idx,
                dtemp, dtop_k, dtop_p,
                sample_slots=sample_slots,
                commit=np.zeros(rows, bool), want_top=False, **dkw,
            )

        finals = []
        for i, (er, start, end, take, final) in enumerate(plan):
            if n_tgts[i] > 0:
                # keep the DEVICE row; one host conversion at the end
                er.prompt_lp_parts.append((plps[i : i + 1], n_tgts[i]))
            er.prefill_pos = end
            er.context_len = end
            # prefix blocks become matchable (and KV events publish) as
            # soon as each chunk's KV is scheduled — device ordering
            # guarantees the write lands before any later step reads it
            self._register_completed_blocks(er)
            logger.debug("prefill chunk %s [%d:%d)/%d %.1fms",
                         er.request_id, start, end,
                         len(er.prefill_tokens),
                         1e3 * (time.monotonic() - t0))
            if final:
                finals.append(i)
        if not finals:
            return

        def _to_host():
            # every device→host transfer off the event loop: final-row
            # outputs plus any accumulated prompt-logprob rows (an
            # echo+logprobs prompt may hold many chunk rows)
            plists = {
                i: [
                    float(x)
                    for row, cnt in plan[i][0].prompt_lp_parts
                    for x in np.asarray(row)[0, :cnt]
                ]
                for i in finals
                if plan[i][0].prompt_lp_parts
            }
            return (np.asarray(next_tokens), np.asarray(lps),
                    np.asarray(top_vals), np.asarray(top_ids), plists)

        t_sync = time.monotonic()
        toks, lpn, tv, ti, plists = await loop.run_in_executor(None, _to_host)
        self._observe_host_sync(time.monotonic() - t_sync)
        if self.device_time is not None:
            # non-final chunks never sync; their device time folds into
            # this observation via the serialized-interval estimator
            self.device_time.observe(
                "prefill", "prefill", t0, time.monotonic(),
            )
        for i in finals:
            er = plan[i][0]
            self.prefilling.remove(er)
            er.ctx.add_stage("prefill")
            prompt_lps = None
            if er.want_prompt_lps and not er.prompt_lps_emitted:
                # OpenAI/vLLM convention: the first prompt token has no
                # conditioning prefix — its entry is None
                prompt_lps = [None] + plists.get(i, [])
                er.prompt_lps_emitted = True
            er.prompt_lp_parts = []
            if er.max_new == 0:
                # prompt-scoring request (echo + logprobs + max_tokens=0):
                # the prefill ran for its logits; no token is emitted
                er.finish = FinishReason.LENGTH
                er.out_queue.put_nowait(EngineOutput(
                    token_ids=[], finish_reason=er.finish,
                    prompt_logprobs=prompt_lps,
                ))
                self._finish(er, er.finish, emit=False)
                continue
            token = int(toks[i])
            er.pending_token = token
            er.generated += 1  # += not =: resumed requests keep their count
            er.ring_tail.append(token)
            er.finish = self._check_finish(er, token)
            self._guided_after_token(er)
            self._emit(er, token, float(lpn[i]) if er.want_logprobs else None,
                       self._top_row(er, tv, ti, i), prompt_lps=prompt_lps)
            if er.finish is not None:
                self._finish(er, er.finish, emit=False)

    def _spec_eligible(self, er: EngineRequest) -> bool:
        """Speculative verify preserves the exact stream only for greedy,
        penalty-free, bias-free requests that want no logprobs: the
        verify step's raw argmax must equal what sequential sampling
        would pick, and per-position logprobs are not computed. Guided
        rows are excluded too — their mask changes every step."""
        return (er.temperature == 0.0
                and er.presence_penalty == 0.0
                and er.frequency_penalty == 0.0
                and er.repetition_penalty == 1.0
                and not er.want_logprobs and er.logprobs_n == 0
                and not er.req.sampling_options.logit_bias
                and er.guided is None)

    def _guided_allowed_ids(self, er: EngineRequest) -> List[int]:
        """Token ids the constraint permits next, plus the eos ids
        wherever the constrained output may legally end (a terminal trie
        node; a complete top-level JSON value)."""
        v = self.config.model.vocab_size
        ids, at_end = er.guided.allowed()
        allowed = [t for t in ids if 0 <= t < v]
        if at_end:
            allowed.extend(
                int(e) for e in er.req.eos_token_ids or []
                if 0 <= int(e) < v
            )
        return allowed

    def _guided_mask(self, er: EngineRequest) -> np.ndarray:
        """Dense [V] additive mask for the NEXT sampled token: 0 for the
        allowed ids, a large negative everywhere else. Used at admission
        (set_sample_row); per-step updates edit sparsely instead."""
        v = self.config.model.vocab_size
        mask = np.full(v, -1e9, np.float32)
        er.guided_allowed = self._guided_allowed_ids(er)
        mask[er.guided_allowed] = 0.0
        return mask

    def _guided_after_token(self, er: EngineRequest,
                            edit: bool = True) -> None:
        """Advance the constraint past the just-sampled token; install
        the next mask, or finish when the constraint completes. Runs
        between _check_finish and _emit so the completing token still
        streams.

        ``edit=False`` (the chained drain): advance the cursor and judge
        verdicts only — the device computed this token's mask from the
        transition table, and the barrier reinstalls the host mask if
        the row ever returns to the sync path."""
        if er.guided is None or er.finish is not None:
            return
        key_before = er.guided.state_key()
        verdict = er.guided.advance(er.pending_token)
        if verdict != "ok":
            # "done": constraint complete (closing brace / final choice
            # token). "derail": eos at a legal end point (eos is never
            # in the constraint's own alphabet) or a defensive fallback.
            er.finish = FinishReason.STOP
            return
        if not edit:
            return
        if er.guided.state_key() == key_before:
            # same machine state → identical allowed set (e.g. JSON
            # string-body tokens): the installed mask is already right
            return
        # sparse edit: only the old node's and new node's neighborhoods
        # change — O(branching), not O(vocab), per token
        user_bias = er.req.sampling_options.logit_bias or {}
        new_allowed = self._guided_allowed_ids(er)
        if not new_allowed:
            # dead state mid-stream (vocab cannot continue the grammar
            # and no legal end here): stop at the valid prefix instead
            # of emitting an unconstrained token through an all-banned
            # mask
            er.finish = FinishReason.STOP
            return
        new_set = set(new_allowed)
        changed = list(new_set | set(er.guided_allowed))
        vals = [
            (0.0 if t in new_set else -1e9) + float(user_bias.get(t, 0.0))
            for t in changed
        ]
        if not self.runner.edit_bias_entries(er.slot, changed, vals):
            # neighborhood wider than the largest edit bucket: rebuild
            mask = self._guided_mask(er)
            for tid, b in user_bias.items():
                tid = int(tid)
                if 0 <= tid < len(mask):
                    mask[tid] += float(b)
            self.runner.set_bias_row(er.slot, mask)
        er.guided_allowed = new_allowed

    @staticmethod
    def _inert_sampling(n: int):
        """Greedy, penalty-free sampling arrays for draft-mirror runs
        (nothing reads the sampled outputs): positional (temperature,
        top_k, top_p) plus the keyword tail as one dict."""
        zf = np.zeros(n, np.float32)
        zi = np.zeros(n, np.int32)
        return zf, zi, np.ones(n, np.float32), dict(
            min_p=zf, presence_penalty=zf, frequency_penalty=zf,
            repetition_penalty=np.ones(n, np.float32),
            seed_keys=np.zeros((n, 2), np.uint32), counters=zi,
        )

    async def _draft_propose(self, loop, active: List[EngineRequest],
                             K: int) -> dict:
        """K greedy proposals per row from the draft model's fused burst.

        ONE extra dispatch per round: the draft's ``multi_step_decode``
        is K+1, so the burst also writes the K-th proposal's KV into the
        mirror cache (the (K+1)th sampled token is discarded — it exists
        only to drive that final KV write). Inactive rows run inert.
        """
        cfg = self.config
        b = cfg.max_batch_size
        w = cfg.kv_width_bucket(max(len(er.block_ids) for er in active))
        tokens0 = np.zeros(b, np.int32)
        positions0 = np.zeros(b, np.int32)
        btab = np.zeros((b, w), np.int32)
        commit = np.zeros(b, bool)
        for er in active:
            i = er.slot
            tokens0[i] = er.pending_token
            positions0[i] = er.context_len
            btab[i, : len(er.block_ids)] = er.block_ids
            commit[i] = True
        temp, top_k, top_p, kw = self._inert_sampling(b)
        toksK, *_ = self.draft.decode_burst(
            tokens0, positions0, btab, temp, top_k, top_p,
            commit=commit, want_top=False, **kw,
        )
        tk = await loop.run_in_executor(None, lambda: np.asarray(toksK))
        self.steps += 1
        return {
            er.slot: [int(t) for t in tk[:K, er.slot]] for er in active
        }

    async def _decode_spec(self, loop, active: List[EngineRequest]) -> None:
        """One speculative decode pass: propose up to K tokens per row —
        from the row's own history (ngram) or from the draft model's
        fused K-step burst — verify all K+1 positions in ONE target
        forward (decode is bandwidth-bound — the weights stream once
        either way), and emit the accepted prefix plus the correction
        token.

        KV discipline matches the burst path: every proposed position's
        KV is written during the verify (and, for draft proposals, into
        the draft's mirror cache during the burst); rejected positions'
        slots are simply rewritten when decoding reaches them again, and
        block registration only ever covers positions below the host
        context_len, which advances by accepted tokens only.
        """
        cfg = self.config
        b = cfg.max_batch_size
        bs = cfg.kv_block_size
        # verify-step dispatches are not decode bursts; stop the clock
        self._last_burst_done_t = None
        K = cfg.spec_draft_tokens if self.draft is not None \
            else cfg.spec_ngram_tokens
        S = K + 1
        if any(er.context_len + S + 1 > cfg.max_model_len for er in active):
            # a row is within K of the horizon; it finishes momentarily
            return await self._decode(loop, active, 1)

        props: dict = {}
        if self.draft is None:
            # ngram proposals first: when nothing matches anywhere
            # (non-repetitive output), the K+1-wide verify would be pure
            # per-step overhead — run the normal decode (incl. its fused
            # burst) instead
            for er in active:
                history = list(er.seq.token_ids) + [er.pending_token]
                props[er.slot] = ngram_propose(
                    history, cfg.spec_ngram_match, K
                )
            if not any(props.values()):
                return await self._decode(loop, active, cfg.multi_step_decode)

        for er in list(active):
            ok = all(
                self._ensure_block_for(er, er.context_len + j)
                for j in range(S)
            )
            if not ok:
                logger.warning("KV OOM: preempting %s", er.request_id)
                self._preempt(er)
                active.remove(er)
        self.allocator.flush_offload()
        if not active:
            return

        if self.draft is not None:
            # draft proposals: ONE K-step greedy burst of the small model
            # (blocks are allocated above, so the burst's KV writes into
            # the mirror cache land in valid slots)
            props = await self._draft_propose(loop, active, K)

        w = cfg.kv_width_bucket(max(len(er.block_ids) for er in active))
        tokens = np.zeros((b, S), np.int32)
        positions = np.zeros((b, S), np.int32)
        slot_map = np.full((b, S), -1, np.int32)
        btab = np.zeros((b, w), np.int32)
        ctx_lens = np.ones(b, np.int32)
        last_idx = np.zeros(b, np.int32)

        for er in active:
            i = er.slot
            pos0 = er.context_len
            prop = props[i]
            row = [er.pending_token] + prop
            tokens[i, : len(row)] = row
            positions[i] = pos0 + np.arange(S)
            for j in range(S):
                pj = pos0 + j
                slot_map[i, j] = er.block_ids[pj // bs] * bs + pj % bs
            btab[i, : len(er.block_ids)] = er.block_ids
            # causal masking is by absolute position, so padding rows'
            # junk keys (past their proposal) are invisible to every
            # valid query at an earlier position
            ctx_lens[i] = pos0 + S
            last_idx[i] = len(row) - 1

        zf, zi = np.zeros(b, np.float32), np.zeros(b, np.int32)
        t_dispatch = time.monotonic()
        *_, greedy_all = self.runner.step(
            tokens, positions, btab, slot_map, ctx_lens, last_idx,
            zf, zi, np.ones(b, np.float32),
            min_p=zf, presence_penalty=zf, frequency_penalty=zf,
            repetition_penalty=np.ones(b, np.float32),
            seed_keys=np.zeros((b, 2), np.uint32), counters=zi,
            sample_slots=np.arange(b, dtype=np.int32),
            commit=np.zeros(b, bool),  # greedy chain: counts never consulted
            want_top=False, want_greedy=True,
        )
        t_sync = time.monotonic()
        ga = await loop.run_in_executor(None, lambda: np.asarray(greedy_all))
        self._observe_host_sync(time.monotonic() - t_sync)
        if self.device_time is not None:
            # the verify forward is one decode-shaped step over S
            # positions: weights once + each row's (ctx + S) KV
            self.device_time.observe(
                "spec_verify", "decode", t_dispatch, time.monotonic(),
                read_bytes=self.device_time.decode_read_bytes(
                    1, sum(er.context_len + S for er in active),
                ),
                tokens=len(active),
            )
        self.steps += 1

        for er in active:
            if er.finish is not None:
                continue
            i = er.slot
            prop = props[i]
            a = 0
            while a < len(prop) and int(ga[i, a]) == prop[a]:
                a += 1
            self.spec_proposed += len(prop)
            self.spec_accepted += a
            self._spec_proposed_ctr.inc(len(prop))
            self._spec_accepted_ctr.inc(a)
            # emit accepted prefix + the correction token, with the same
            # pending-token discipline as every other decode path
            for j in range(a + 1):
                if er.finish is not None:
                    break
                token = int(ga[i, j])
                self._advance_row(er, token)
                self._emit(er, token, None, None)
                if er.finish is not None:
                    self._finish(er, er.finish, emit=False)

    async def _decode(self, loop, active: List[EngineRequest],
                      k_steps: int = 1) -> None:
        cfg = self.config
        b = cfg.max_batch_size
        bs = cfg.kv_block_size

        # a K-step burst writes K tokens of KV per row before the host
        # sees any of them, so every row needs blocks for all K positions
        # up front, and no row may run past the block-table/model-len
        # horizon mid-burst (such rows finish within one burst anyway —
        # fall back to per-token stepping for everyone this pass)
        if k_steps > 1 and any(
            er.context_len + k_steps + 1 > cfg.max_model_len for er in active
        ):
            k_steps = 1
        if self.draft is not None:
            # plain decode must keep the draft's mirror cache current
            # (the next speculative round assumes draft KV for every
            # position < context); the mirror runs per-token, so pin the
            # target to per-token too — with a draft configured, the
            # fused burst's role is played by speculation itself
            k_steps = 1
        if any(er.guided is not None for er in active):
            # guided rows rewrite their mask between tokens on the host;
            # a fused burst would sample K tokens against one stale mask.
            # NOTE this pins the WHOLE batch (all rows share one
            # dispatch), so concurrent unguided requests also lose the
            # burst while any guided request is active — documented in
            # docs/models.md. Splitting guided rows into their own
            # dispatch would pay two program launches per step, worse
            # than the amortization it saves at serving batch sizes.
            k_steps = 1

        # make sure each active sequence has blocks for its next position
        # (all k_steps of them under a burst)
        for er in list(active):
            ok = all(
                self._ensure_block_for(er, er.context_len + j)
                for j in range(k_steps)
            )
            if not ok:
                # out of memory: evict the youngest request back to waiting
                # (simple preemption — recompute later)
                logger.warning("KV OOM: preempting %s", er.request_id)
                self._preempt(er)
                active.remove(er)
        # one batched host-offload gather for every eviction this step,
        # before the step below overwrites the evicted slots
        self.allocator.flush_offload()
        if not active:
            return

        # KV-width bucketing: the block table (and so the gather/page walk
        # behind attention) is sized to the LIVE context, rounded up a
        # power-of-two ladder — short-context decode doesn't pay the
        # max_model_len table width (one compiled program per bucket)
        w = cfg.kv_width_bucket(max(len(er.block_ids) for er in active))

        # sampling params and the block table come from the persistent
        # host state (mutated only on membership / block growth); only
        # the genuinely per-pass scalars are rebuilt here
        hs = self._host
        tokens = np.zeros((b, 1), np.int32)
        positions = np.zeros((b, 1), np.int32)
        slot_map = np.full((b, 1), -1, np.int32)
        ctx_lens = np.ones(b, np.int32)
        last_idx = np.zeros(b, np.int32)
        ctrs = np.zeros(b, np.int32)
        commit = np.zeros(b, bool)

        for er in active:
            i = er.slot
            pos = er.context_len
            hs.sync_blocks(er)
            tokens[i, 0] = er.pending_token
            positions[i, 0] = pos
            slot_map[i, 0] = er.block_ids[pos // bs] * bs + pos % bs
            ctx_lens[i] = pos + 1
            ctrs[i] = er.generated
            commit[i] = True
        # .copy(), not a view: the persistent table mutates across passes
        # while a dispatched program's host→device transfer may still be
        # in flight — the step must capture a stable snapshot
        btab = hs.btab[:, :w].copy()

        # the [B, V] top-k sort only runs when some active request
        # asked for alternatives (ADVICE r2: fixed decode-path cost)
        want_top = any(er.logprobs_n > 0 for er in active)

        # synchronous path: the device has been idle since the previous
        # burst's host sync completed — that gap IS the bubble the
        # dispatch-ahead pipeline exists to close
        if self._last_burst_done_t is not None:
            self._bubble_hist.observe(
                time.monotonic() - self._last_burst_done_t
            )
            self._last_burst_done_t = None

        self.flight.record(
            "scheduler.burst_dispatch", k_steps=k_steps, rows=len(active),
            pipelined=False,
            requests=[er.request_id for er in active[:8]],
        )
        t_dispatch = time.monotonic()
        if k_steps > 1:
            next_tokens, lps, top_vals, top_ids = self.runner.decode_burst(
                tokens[:, 0], positions[:, 0], btab,
                hs.temp, hs.top_k, hs.top_p,
                min_p=hs.min_p, presence_penalty=hs.pres,
                frequency_penalty=hs.freq,
                repetition_penalty=hs.rep, seed_keys=hs.keys, counters=ctrs,
                commit=commit, want_top=want_top,
            )
        else:
            next_tokens, lps, top_vals, top_ids, *_ = self.runner.step(
                tokens, positions, btab, slot_map, ctx_lens, last_idx,
                hs.temp, hs.top_k, hs.top_p,
                min_p=hs.min_p, presence_penalty=hs.pres,
                frequency_penalty=hs.freq,
                repetition_penalty=hs.rep, seed_keys=hs.keys, counters=ctrs,
                sample_slots=np.arange(b, dtype=np.int32), commit=commit,
                want_top=want_top,
            )
            if self.draft is not None:
                # mirror the step on the draft (inert sampling): the
                # speculative rounds assume the draft cache covers every
                # position the target has decoded
                dtemp, dtop_k, dtop_p, dkw = self._inert_sampling(b)
                self.draft.step(
                    tokens, positions, btab, slot_map, ctx_lens, last_idx,
                    dtemp, dtop_k, dtop_p,
                    sample_slots=np.arange(b, dtype=np.int32),
                    commit=np.zeros(b, bool), want_top=False, **dkw,
                )
        t_sync = time.monotonic()

        def _sync_step():
            faults.maybe_hang("decode_burst_hang")  # chaos site (see above)
            return (np.asarray(next_tokens), np.asarray(lps),
                    np.asarray(top_vals), np.asarray(top_ids))

        toks, lpn, tv, ti = await loop.run_in_executor(None, _sync_step)
        self._observe_host_sync(time.monotonic() - t_sync)
        self._last_burst_done_t = time.monotonic()
        if self.device_time is not None:
            self.device_time.observe(
                "decode_burst" if k_steps > 1 else "decode", "decode",
                t_dispatch, self._last_burst_done_t,
                read_bytes=self.device_time.decode_read_bytes(
                    k_steps, sum(er.context_len for er in active),
                ),
                tokens=k_steps * len(active),
            )
        self.steps += 1
        if k_steps == 1:
            # [B] → [1, B] so the emit loop below is one shape
            toks, lpn = toks[None], lpn[None]
            tv, ti = tv[None], ti[None]

        # emit in step order; a request that finishes at step j has its
        # trailing burst tokens (sampled ahead on device) discarded —
        # their KV went into this request's own still-unregistered or
        # over-allocated blocks, which are freed with the request, so
        # nothing another sequence can observe was touched
        for j in range(k_steps):
            for er in active:
                if er.finish is not None:
                    continue
                token = int(toks[j, er.slot])
                self._advance_row(er, token)
                self._guided_after_token(er)
                self._emit(
                    er, token,
                    float(lpn[j, er.slot]) if er.want_logprobs else None,
                    self._top_row(er, tv[j], ti[j], er.slot),
                )
                if er.finish is not None:
                    self._finish(er, er.finish, emit=False)

    def _preempt(self, er: EngineRequest) -> None:
        """Return a request to the waiting queue, releasing its blocks.

        Tokens already emitted to the client are PRESERVED: on re-admission
        the request re-prefills ``prompt + resume_tokens`` and the stream
        continues where it stopped (never restarts or diverges)."""
        self._preemptions.inc()
        self.flight.record(
            "scheduler.preemption", request_id=er.request_id,
            trace_id=er.ctx.trace_id, generated=er.generated,
            blocks_freed=len(er.block_ids),
        )
        er.ctx.add_stage("preempted")
        if er.slot >= 0:
            self.slots[er.slot] = None
            er.slot = -1
        self.allocator.free_blocks(er.block_ids)
        er.block_ids = []
        # seq mirrors tokens whose KV was written; everything past the
        # original prompt is generated output, plus the not-yet-written
        # pending token — all already emitted to the client
        gen = er.seq.token_ids[len(er.prompt):] if er.seq is not None else []
        if er.pending_token >= 0:
            gen = gen + [er.pending_token]
        er.resume_tokens = list(gen)
        er.context_len = 0
        er.num_cached = 0
        er.pending_token = -1
        er.seq = None
        er.registered_blocks = 0
        er.prefill_tokens = []
        er.prefill_pos = 0
        # re-prefill recomputes prompt logprobs from scratch
        er.prompt_lp_parts = []
        # er.generated keeps its value: max_tokens accounting + PRNG
        # fold-in counters continue, not restart
        self.waiting.appendleft(er)

    def _check_finish(self, er: EngineRequest, token: int) -> Optional[FinishReason]:
        """Per-token finish verdict off the admission-time classification
        (EngineRequest.classify_finish): set membership against the
        precomputed frozensets instead of re-deriving eos/stop lists
        from the request every token — this runs for EVERY emitted token
        of every request (incl. the async drain's hot path). Must stay
        the exact host mirror of sampling.device_finish_mask (+ the
        suffix-hash stop approximation: the exact token-suffix compare
        below is what the device's hash candidate approximates, and it
        runs on BOTH paths so chain and sync streams stay identical)."""
        if er.generated >= er.fin_min_new:
            # eos/stops suppressed below min_tokens; ignore_eos already
            # emptied fin_eos at classification
            if token in er.fin_eos:
                return FinishReason.EOS
            if token in er.fin_stop:
                return FinishReason.STOP
            if er.fin_stop_seqs:
                # canonical-tokenization stop strings: the ring tail
                # ends with this token (callers note it first); only
                # generated output may match (gen >= L). Non-canonical
                # tokenizations remain the backend jail's concern.
                tail = tuple(er.ring_tail)
                for seq in er.fin_stop_seqs:
                    length = len(seq)
                    if (er.generated >= length
                            and len(tail) >= length
                            and tail[-length:] == seq):
                        return FinishReason.STOP
        if er.generated >= er.fin_max_new:
            return FinishReason.LENGTH
        if er.context_len + 1 >= self.config.max_model_len:
            return FinishReason.LENGTH
        return None
