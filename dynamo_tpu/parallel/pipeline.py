"""Pipeline parallelism: layers sharded by stage, microbatches in flight.

The last parallelism axis from SURVEY.md §2.12 (reference analog: the
vllm0_7 engine's Ray-based pipeline_parallel_size pass-through,
lib/engines/vllm0_7/src/{ray.rs,vllm_inc.py:38} — the reference never
implements PP itself, it forwards a flag to vLLM).

TPU-first formulation — a *collective* GPipe schedule inside one SPMD
program (no per-stage processes, no RPC):

- the mesh's ``pp`` axis holds P stages; the stacked layer params
  [L, ...] reshape to [P, L/P, ...] and shard on the leading axis, so
  under ``shard_map`` each device owns its stage's layer block and the
  per-layer ``lax.scan`` runs over just L/P layers;
- the paged KV cache [L, N, bs, KVH, D] shards the same way — each
  stage reads/writes only its own layer slab, in place;
- the batch splits into M microbatches; for T = M + P - 1 ticks every
  device runs the same step: compute its layer block on the microbatch
  it currently holds, then ``lax.ppermute`` the activations one stage
  down the ring. Stage 0 injects (embedding) and the last stage
  collects; warm-up/drain ticks carry garbage that is masked out — KV
  writes use the scatter drop sentinel so invalid ticks touch nothing.

Embedding/logits stay replicated (cheap relative to the trunk); combine
``pp`` with ``tp``/``dp`` axes by nesting specs — this module only owns
the pp dimension.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ..engine.config import ModelConfig
from ..models import llama
from ..ops.compat import shard_map

KVCache = Tuple[jax.Array, jax.Array]


def stage_params(params, num_stages: int):
    """Reshape stacked layer params [L, ...] → [P, L/P, ...] for pp sharding.

    The pipeline stages exactly ONE homogeneous layer group. A non-MoE
    MLA model (models/deepseek.py, num_experts=0) stacks its trunk under
    "dense_layers" instead of "layers"; it is renamed here — the staged
    tree is consumed only by pipeline_forward, which addresses the trunk
    as "layers". Mixed dense+MoE trunks (first_k_dense_replace > 0) keep
    their dense prefix UNstaged under "dense_layers": XLA's homogeneous
    stage scan cannot hold two differently-shaped layer pytrees, so the
    (short) prefix replicates to every stage and runs at injection while
    only the MoE trunk shards over pp.
    """
    key = "layers" if "layers" in params else "dense_layers"
    l = jax.tree.leaves(params[key])[0].shape[0]
    if l % num_stages:
        raise ValueError(f"{l} layers not divisible by {num_stages} pp stages")
    staged = dict(params)
    if key == "layers" and "dense_layers" in params:
        # mixed dense+MoE trunk (DeepSeek first_k_dense_replace > 0):
        # the stage scan cannot stack two differently-shaped layer
        # pytrees, so the (short) dense prefix stays UNstaged — it is
        # kept under "dense_layers", replicated to every stage, and
        # computed redundantly at injection (pipeline_forward); only
        # the homogeneous MoE trunk shards over pp.
        pass
    else:
        staged.pop("dense_layers", None)
    staged["layers"] = jax.tree.map(
        lambda x: x.reshape(num_stages, l // num_stages, *x.shape[1:]),
        params[key],
    )
    return staged


def stage_cache(kv_cache: KVCache, num_stages: int,
                prefix_layers: int = 0) -> KVCache:
    """[L, N, bs, KVH, D] → [P, L/P, N, bs, KVH, D] (stage-local slabs).

    ``prefix_layers`` > 0 (mixed dense+MoE MLA trunks): the first k
    layers belong to the replicated dense prefix — each side becomes
    ``{"pre": [k, ...] replicated, "stg": [P, (L-k)/P, ...] staged}``.
    """
    def split(c):
        l = c.shape[0]
        if l % num_stages:
            raise ValueError(
                f"{l} cache layers not divisible by {num_stages} pp stages"
            )
        return c.reshape(num_stages, l // num_stages, *c.shape[1:])

    if prefix_layers:
        return tuple(
            {"pre": c[:prefix_layers], "stg": split(c[prefix_layers:])}
            for c in kv_cache
        )
    return tuple(split(c) for c in kv_cache)


def unstage_cache(kv_cache: KVCache) -> KVCache:
    """Inverse of stage_cache: back to the wire layout [L, ...] with
    prefix layers (if any) leading."""
    def flat(c):
        if isinstance(c, dict):
            stg = c["stg"].reshape(-1, *c["stg"].shape[2:])
            return jnp.concatenate([c["pre"], stg], axis=0)
        return c.reshape(-1, *c.shape[2:])

    return tuple(flat(c) for c in kv_cache)


def param_specs(params, tp: bool = False, arch=None) -> dict:
    """Placement specs for staged params: layer stacks shard over pp on
    the stage axis. With ``tp`` the inner dims also shard Megatron-style —
    each spec is the family's per-layer tp spec with "pp" prepended for
    the stage axis (wq/wk/wv/w_gate/w_up column-parallel, wo/w_down
    row-parallel; MoE experts additionally over "ep"); lm_head stays
    vocab-sharded over tp at the outer (GSPMD) level."""
    arch = arch or llama
    specs = {"embed": P(), "final_norm": P()}
    if "lm_head" in params:
        specs["lm_head"] = P(None, "tp") if tp else P()
    # always start from the family's specs so non-tp axes (MoE "ep" on
    # the expert stacks) survive even when tp is off — only the "tp"
    # names are stripped at tp=1. Families whose staged trunk may be a
    # renamed group (deepseek's dense_layers) provide pp_trunk_specs.
    trunk_specs = getattr(arch, "pp_trunk_specs", None)
    if trunk_specs is not None:
        layer_specs = trunk_specs(params["layers"])
    else:
        layer_specs = arch.param_specs({"layers": params["layers"]})["layers"]

    def axis(a):
        return None if (a == "tp" and not tp) else a

    specs["layers"] = {
        k: P("pp", *(axis(a) for a in s)) for k, s in layer_specs.items()
    }
    if "dense_layers" in params:
        # replicated dense prefix (mixed MLA trunk): every stage holds
        # and computes it; its tp axes strip (MLA pp requires tp=1)
        prefix_specs = (trunk_specs(params["dense_layers"])
                        if trunk_specs is not None
                        else arch.param_specs(
                            {"dense_layers": params["dense_layers"]}
                        )["dense_layers"])
        specs["dense_layers"] = {
            k: P(*(axis(a) for a in s)) for k, s in prefix_specs.items()
        }
    # int8 serving: QuantizedWeight leaves need mirrored spec NODES (the
    # scale is one rank lower than q) — both for device_put and for the
    # shard_map in_specs below
    from ..models import quant

    return quant.mirror_specs(params, specs)


CACHE_SPEC = P("pp")  # [P, L/P, N, bs, KVH, D]
# with tp: KV heads shard over tp inside each stage's slab
CACHE_SPEC_TP = P("pp", None, None, None, "tp", None)


def pipeline_forward(
    params,                   # staged params (stage_params output)
    cfg: ModelConfig,
    tokens: jax.Array,        # [B, S]
    positions: jax.Array,     # [B, S]
    kv_cache: KVCache,        # staged cache (stage_cache output)
    block_tables: jax.Array,  # [B, W]
    slot_mapping: jax.Array,  # [B, S]
    context_lens: jax.Array,  # [B]
    mesh,
    num_microbatches: Optional[int] = None,
    return_hidden: bool = False,
    arch=None,                # family module (llama default; mixtral = MoE)
) -> Tuple[jax.Array, KVCache]:
    """GQA-family forward with the trunk pipelined over the pp axis.

    Returns (logits [B, S, V], updated staged cache) — same contract as
    the family's forward modulo the staged cache layout. M defaults to P
    (the minimum that fills the pipeline; raise it to shrink the bubble).

    The shard_map is fully manual (dp/ep included) with explicit
    collectives — a partial-manual formulation (dp/ep left to GSPMD)
    crashes XLA's bf16 AllReducePromotion pass on this toolchain, because
    shardy inserts a sharding_constraint inside the psum reducer region:

    - dp: microbatch rows shard over "dp" when divisible; the KV cache is
      replicated across dp, so each member all-gathers every member's new
      K/V + slots before the cache scatter (make_gqa_attn_fn's
      kv_gather_axis) and attends its local rows only. A batch too small
      to split (B=1 prefill) is computed replicated — the non-pp path's
      behavior.
    - ep (MoE): expert stacks shard over "ep"; routing runs replicated
      over the global expert set, each member computes its local experts,
      and ONE psum over (tp, ep) finishes both the Megatron row-parallel
      contraction and the expert combine (moe_mlp's ep_axis). Known
      semantics delta vs the unstaged engine: expert capacity is sized
      per MICROBATCH (mb*s tokens), not per full batch — a microbatch
      whose tokens concentrate on one expert can drop tokens the
      unstaged engine would keep. moe_capacity_factor (default 2.0)
      absorbs this in practice; raise it if pp-MoE quality drifts.

    Families plug in through module hooks with llama defaults:
    ``embed_tokens`` / ``make_attn_fn`` / ``run_layers`` / ``mlp_fn``
    (Gemma-2 overrides all four for its scaled embeddings, softcap +
    alternating-window attention, and sandwich-norm layer step; the
    window alternation follows the GLOBAL layer index via
    make_attn_fn's layer_offset).
    """
    import dataclasses as _dc
    import math as _math

    arch = arch or llama
    embed_fn = getattr(arch, "embed_tokens", llama.embed_tokens)
    make_attn = getattr(arch, "make_attn_fn", llama.make_gqa_attn_fn)
    run_layers_fn = getattr(arch, "run_layers", llama.run_layers)
    family_mlp = getattr(arch, "mlp_fn", llama._swiglu_mlp)
    # routed-MoE families expose a per-tick mlp factory taking the
    # manual ep axis (mixtral.make_moe_mlp_fn; gptoss.make_mlp_fn)
    moe_maker = None
    if getattr(cfg, "num_experts", 0):
        moe_maker = (
            getattr(arch, "make_moe_mlp_fn", None)
            or getattr(arch, "make_mlp_fn", None)
        )
    moe = moe_maker is not None
    num_stages = mesh.shape["pp"]
    tp = mesh.shape.get("tp", 1)
    dp = mesh.shape.get("dp", 1)
    ep = mesh.shape.get("ep", 1) if moe else 1
    b, s = tokens.shape
    # auto microbatching: M = P fills the pipeline, but the batch must
    # split evenly — prefill runs at B=1, so fall back to the largest
    # divisor (m=1 degrades to stage-serial execution, still correct)
    m = num_microbatches or (
        num_stages if b % num_stages == 0 else _math.gcd(b, num_stages)
    )
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    mb = b // m
    # shard microbatch rows over dp when they split evenly; otherwise
    # every dp member computes the full rows redundantly (exactly the
    # non-pp engine's prefill-at-B=1 behavior)
    shard_dp = dp > 1 and mb % dp == 0
    mb_local = mb // dp if shard_dp else mb
    batch_spec = P(None, "dp") if shard_dp else P()

    def split_mb(x):
        return x.reshape(m, mb, *x.shape[1:])

    tokens_mb = split_mb(tokens)
    positions_mb = split_mb(positions)
    tables_mb = split_mb(block_tables)
    slots_mb = split_mb(slot_mapping)
    ctx_mb = split_mb(context_lens)

    cache_spec = CACHE_SPEC_TP if tp > 1 else CACHE_SPEC
    # each stage computes attention/MLP on its tp-local head/column shard
    # (activations replicated over tp, Megatron-style: one psum after the
    # attention output projection, one after w_down)
    local_cfg = (
        _dc.replace(
            cfg,
            num_heads=cfg.num_heads // tp,
            num_kv_heads=cfg.num_kv_heads // tp,
        )
        if tp > 1 else cfg
    )
    # reduce only over axes the mesh actually has (library callers may
    # build pp-only or pp x ep meshes; ep > 1 implies an ep axis exists)
    attn_axes = ("tp",) if "tp" in mesh.axis_names else ()
    mlp_axes = attn_axes + (("ep",) if ep > 1 else ())

    # mixed dense+MoE MLA trunk: a replicated dense prefix rides beside
    # the staged trunk — its params/cache replicate to every stage and
    # the prefix compute runs redundantly at injection (the prefix is a
    # few layers of sixty-plus; redundancy beats heterogeneous staging)
    has_prefix = "dense_layers" in params
    side_spec = (
        {"pre": P(), "stg": cache_spec} if has_prefix else cache_spec
    )

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            param_specs(params, tp=tp > 1, arch=arch),
            (side_spec, side_spec),
            batch_spec, batch_spec, batch_spec, batch_spec, batch_spec,
        ),
        out_specs=(batch_spec, (side_spec, side_spec)),
        check_vma=False,
    )
    def run(params, kv_cache, tokens_mb, positions_mb, tables_mb, slots_mb, ctx_mb):
        stage = lax.axis_index("pp")
        is_first = stage == 0
        is_last = stage == num_stages - 1
        # shard_map gives the local block with a leading singleton stage dim
        local_layers = jax.tree.map(lambda x: x[0], params["layers"])
        layers_per_stage = jax.tree.leaves(local_layers)[0].shape[0]
        if has_prefix:
            k_pre, v_pre = kv_cache[0]["pre"], kv_cache[1]["pre"]
            k_local, v_local = kv_cache[0]["stg"][0], kv_cache[1]["stg"][0]
        else:
            k_pre = v_pre = None
            k_local, v_local = kv_cache[0][0], kv_cache[1][0]

        d_model = cfg.hidden_size
        ticks = m + num_stages - 1

        def tick(t, carry):
            x_state, k_local, v_local, k_pre, v_pre, outputs = carry
            # which microbatch does THIS stage hold at tick t?
            mb_idx = jnp.clip(t - stage, 0, m - 1)
            valid = jnp.logical_and(t - stage >= 0, t - stage < m)

            tok = lax.dynamic_index_in_dim(tokens_mb, mb_idx, 0, keepdims=False)
            pos = lax.dynamic_index_in_dim(positions_mb, mb_idx, 0, keepdims=False)
            tab = lax.dynamic_index_in_dim(tables_mb, mb_idx, 0, keepdims=False)
            slots = lax.dynamic_index_in_dim(slots_mb, mb_idx, 0, keepdims=False)
            ctx = lax.dynamic_index_in_dim(ctx_mb, mb_idx, 0, keepdims=False)

            # invalid (warm-up/drain) ticks must not write KV: the drop
            # sentinel routes their scatter out of range
            slots = jnp.where(valid, slots, -1)

            # stage 0 injects the embedded microbatch; others use the
            # activations ppermuted in at the end of the previous tick.
            # With a dense prefix, injection = embed + the replicated
            # prefix layers: every stage computes its current
            # microbatch's prefix identically (writes land on disjoint
            # slots, so the replicated caches converge regardless of
            # tick order) and discards the result unless it is stage 0.
            injected = embed_fn(params, tok)
            if has_prefix:
                pre_attn = make_attn(
                    local_cfg, mb_local, s, pos, slots, tab, ctx,
                    mesh=None,
                    kv_gather_axis="dp" if shard_dp else None,
                    layer_offset=0, tp_axis=None,
                )
                injected, (k_pre, v_pre), _ = run_layers_fn(
                    injected, (k_pre, v_pre), params["dense_layers"],
                    cfg, pre_attn, llama._swiglu_mlp,
                )
            x_in = jnp.where(is_first, injected, x_state)

            # layer_offset and tp_axis are part of the factory contract:
            # the stage's first GLOBAL layer index (gemma2/gptoss window
            # alternation) and the manual tp axis (families with
            # replicated additive terms — gptoss's bo/b_down — scale
            # them so the Megatron psum restores each exactly once)
            # a size-1 tp axis still rides the psum (identity) but is
            # NOT a manual tp shard — factories that reject or rescale
            # under manual tp (MLA; gptoss's replicated biases) must
            # only see a real one
            tp_ax = "tp" if (attn_axes and tp > 1) else None
            base_attn = make_attn(
                local_cfg, mb_local, s, pos, slots, tab, ctx, mesh=None,
                kv_gather_axis="dp" if shard_dp else None,
                layer_offset=stage * layers_per_stage,
                tp_axis=tp_ax,
            )
            base_mlp = (
                moe_maker(
                    cfg, mb_local, s, slots,
                    ep_axis="ep" if ep > 1 else None,
                    tp_axis=tp_ax,
                ) if moe
                else family_mlp
            )
            if mlp_axes:
                def attn_fn(x, lp, k, v, li):
                    delta, k, v = base_attn(x, lp, k, v, li)
                    return (
                        lax.psum(delta, attn_axes) if attn_axes else delta
                    ), k, v

                def mlp_fn(x, lp):
                    # ONE reduction finishes both the Megatron
                    # row-parallel contraction (tp) and, for MoE, the
                    # local-expert combine (ep)
                    return lax.psum(base_mlp(x, lp), mlp_axes)
            else:
                attn_fn, mlp_fn = base_attn, base_mlp
            hidden, (k_local, v_local), _ = run_layers_fn(
                x_in, (k_local, v_local), local_layers, cfg, attn_fn,
                mlp_fn,
            )

            # last stage collects its finished microbatch
            out_idx = jnp.clip(t - (num_stages - 1), 0, m - 1)
            take = jnp.logical_and(is_last, valid)
            current = lax.dynamic_index_in_dim(outputs, out_idx, 0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(take, hidden, current), out_idx, 0
            )

            # rotate activations one stage down the ring
            x_state = lax.ppermute(
                hidden, "pp",
                [(i, (i + 1) % num_stages) for i in range(num_stages)],
            )
            return x_state, k_local, v_local, k_pre, v_pre, outputs

        x0 = jnp.zeros((mb_local, s, d_model), params["embed"].dtype)
        out0 = jnp.zeros((m, mb_local, s, d_model), params["embed"].dtype)
        x_state, k_local, v_local, k_pre, v_pre, outputs = lax.fori_loop(
            0, ticks, tick, (x0, k_local, v_local, k_pre, v_pre, out0)
        )

        # only the last stage holds real outputs; psum broadcasts them
        outputs = lax.psum(
            jnp.where(is_last, outputs, jnp.zeros_like(outputs)), "pp"
        )
        if has_prefix:
            return outputs, ({"pre": k_pre, "stg": k_local[None]},
                             {"pre": v_pre, "stg": v_local[None]})
        return outputs, (k_local[None], v_local[None])

    outputs, kv_cache = run(
        params, kv_cache, tokens_mb, positions_mb, tables_mb, slots_mb, ctx_mb
    )
    hidden = outputs.reshape(b, s, -1)
    if return_hidden:
        return hidden, kv_cache
    return arch.logits_from_hidden(hidden, params, cfg), kv_cache
