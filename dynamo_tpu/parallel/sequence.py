"""Engine-facing sequence-parallel prefill attention.

``sp_prefill_attention`` is the drop-in long-context replacement for
ops/attention.py::prefill_attention: same [B, S, ...] interface, but the
sequence dim is sharded over the mesh's ``sp`` axis so a prompt far larger
than one chip's attention memory prefills across the slice. Handles
padding to the axis size and strategy selection (ring for very long S,
all-to-all when heads divide nicely).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from .ring_attention import ring_attention, ulysses_attention


def choose_strategy(seq_len: int, num_kv_heads: int, sp: int) -> str:
    """ring: communication scales with S and works for any head count;
    ulysses: lower latency at moderate S but needs KVH % sp == 0."""
    if num_kv_heads % sp == 0 and seq_len <= 32768:
        return "ulysses"
    return "ring"


def sp_prefill_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, KVH, D]
    v: jax.Array,
    valid_lens: jax.Array,  # [B]
    mesh: Mesh,
    axis: str = "sp",
    strategy: str = "auto",
    scale: Optional[float] = None,
) -> jax.Array:
    """Causal self-attention over the full prompt, sequence-sharded.

    Pads S up to a multiple of the sp axis size (padded positions are
    masked via position id -1) and strips the padding from the output.
    """
    sp = mesh.shape[axis]
    b, s, _h, _d = q.shape
    pad = (-s) % sp
    if pad:
        zeros_q = jnp.zeros((b, pad) + q.shape[2:], q.dtype)
        zeros_kv = jnp.zeros((b, pad) + k.shape[2:], k.dtype)
        q = jnp.concatenate([q, zeros_q], axis=1)
        k = jnp.concatenate([k, zeros_kv], axis=1)
        v = jnp.concatenate([v, zeros_kv], axis=1)
    s_padded = s + pad
    # global positions; everything at/after a row's valid_len is padding
    pos = jnp.arange(s_padded, dtype=jnp.int32)[None, :].repeat(b, axis=0)
    pos = jnp.where(pos < valid_lens[:, None], pos, -1)

    if strategy == "auto":
        strategy = choose_strategy(s_padded, k.shape[2], sp)
    if strategy == "ring":
        out = ring_attention(q, k, v, pos, pos, mesh, axis=axis, scale=scale)
    elif strategy == "ulysses":
        out = ulysses_attention(q, k, v, pos, pos, mesh, axis=axis, scale=scale)
    else:
        raise ValueError(f"unknown sp strategy {strategy!r}; use auto|ring|ulysses")
    return out[:, :s]
