"""Engine-facing sequence-parallel prefill attention.

``sp_prefill_attention`` is the drop-in long-context replacement for
ops/attention.py::prefill_attention: same [B, S, ...] interface, but the
sequence dim is sharded over the mesh's ``sp`` axis so a prompt far larger
than one chip's attention memory prefills across the slice. Handles
padding to the axis size and strategy selection (ring for very long S,
all-to-all when heads divide nicely).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import ring_attention, ulysses_attention


def choose_strategy(seq_len: int, num_kv_heads: int, sp: int) -> str:
    """ring: communication scales with S and works for any head count;
    ulysses: lower latency at moderate S but needs KVH % sp == 0."""
    if num_kv_heads % sp == 0 and seq_len <= 32768:
        return "ulysses"
    return "ring"


def sp_prefill_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, KVH, D]
    v: jax.Array,
    valid_lens: jax.Array,  # [B]
    mesh: Mesh,
    axis: str = "sp",
    strategy: str = "auto",
    scale: Optional[float] = None,
) -> jax.Array:
    """Causal self-attention over the full prompt, sequence-sharded.

    Pads S up to a multiple of the sp axis size (padded positions are
    masked via position id -1) and strips the padding from the output.
    """
    sp = mesh.shape[axis]
    b, s, _h, _d = q.shape
    pad = (-s) % sp
    if pad:
        zeros_q = jnp.zeros((b, pad) + q.shape[2:], q.dtype)
        zeros_kv = jnp.zeros((b, pad) + k.shape[2:], k.dtype)
        q = jnp.concatenate([q, zeros_q], axis=1)
        k = jnp.concatenate([k, zeros_kv], axis=1)
        v = jnp.concatenate([v, zeros_kv], axis=1)
    s_padded = s + pad
    # global positions; everything at/after a row's valid_len is padding
    pos = jnp.arange(s_padded, dtype=jnp.int32)[None, :].repeat(b, axis=0)
    pos = jnp.where(pos < valid_lens[:, None], pos, -1)

    if strategy == "auto":
        strategy = choose_strategy(s_padded, k.shape[2], sp)
    if strategy == "ring":
        out = ring_attention(q, k, v, pos, pos, mesh, axis=axis, scale=scale)
    elif strategy == "ulysses":
        out = ulysses_attention(q, k, v, pos, pos, mesh, axis=axis, scale=scale)
    else:
        raise ValueError(f"unknown sp strategy {strategy!r}; use auto|ring|ulysses")
    return out[:, :s]


def sp_chunk_attention(
    q: jax.Array,            # [1, S, H, D] post-RoPE chunk queries
    k: jax.Array,            # [1, S, KVH, D] the chunk's fresh keys
    v: jax.Array,            # [1, S, KVH, D]
    k_cache: jax.Array,      # [L, N, bs, KVH, Dpad] stacked paged cache
    v_cache: jax.Array,
    block_tables: jax.Array,  # [1, W] this sequence's block ids
    chunk_start,             # traced scalar: first absolute position
    context_len,             # traced scalar: chunk end (valid tokens incl.)
    layer_idx,               # traced scalar: layer into the stacked cache
    mesh: Mesh,
    axis: str = "sp",
    head_axis: Optional[str] = None,
    scale: Optional[float] = None,
) -> jax.Array:
    """Attention for ONE sequence-sharded prefill chunk of a long prompt.

    The serving half of sequence parallelism (engine/model_runner.py
    ``prefill_sp``): the chunk's queries and fresh K/V are sharded over
    the mesh's ``axis``; earlier chunks' KV already live in the paged
    cache. Both sources fold into ONE ring pass — the committed prefix
    is gathered from the cache for this layer, sharded over the same
    axis (per-device key memory stays O((S + W·bs)/sp)), concatenated
    behind the chunk's K/V, and rotated around the ring with global
    position ids doing all masking:

    - chunk keys carry their global positions (causal intra-chunk),
    - prefix keys carry positions ``< chunk_start`` (everything the
      chunk may attend), later cache slots masked to -1 — so the
      chunk's own just-scattered slots are never double-counted, and a
      prefix-cache hit's reused blocks are covered for free.

    Ring (not Ulysses) deliberately: arbitrary head counts, and the
    rotation overlaps the interconnect with compute at exactly the long
    sequence lengths this path exists for.
    """
    b, s, _h, d = q.shape
    l, n_blocks = k_cache.shape[:2]
    # layer indexing through the gather (ops/attention.py idiom): block
    # n of layer li is flat row li*N + n — no full-layer copy
    kc = k_cache.reshape((l * n_blocks,) + k_cache.shape[2:])
    vc = v_cache.reshape((l * n_blocks,) + v_cache.shape[2:])
    rows = block_tables + layer_idx * n_blocks               # [1, W]
    w = block_tables.shape[1]
    bs_sz = k_cache.shape[2]
    pk = kc[rows].reshape(b, w * bs_sz, kc.shape[-2], kc.shape[-1])
    pv = vc[rows].reshape(b, w * bs_sz, vc.shape[-2], vc.shape[-1])
    # slice lane padding away and upcast fp8 storage to the compute dtype
    pk = pk[..., :d].astype(q.dtype)
    pv = pv[..., :d].astype(q.dtype)
    # distribute the gathered prefix over the sequence axis BEFORE the
    # ring, so no device ever holds the whole context
    kv_spec = NamedSharding(mesh, P(None, axis, head_axis, None))
    pk = jax.lax.with_sharding_constraint(pk, kv_spec)
    pv = jax.lax.with_sharding_constraint(pv, kv_spec)

    idx = jnp.arange(s, dtype=jnp.int32)[None, :]
    take = context_len - chunk_start
    qpos = jnp.where(idx < take, chunk_start + idx, -1)      # [1, S]
    cpos = jnp.arange(w * bs_sz, dtype=jnp.int32)[None, :]
    # prefix keys: strictly before the chunk (committed KV only); the
    # chunk's own slots and any pad/garbage blocks mask to -1
    ppos = jnp.where(cpos < chunk_start, cpos, -1)

    kk = jnp.concatenate([k, pk], axis=1)
    vv = jnp.concatenate([v, pv], axis=1)
    kpos = jnp.concatenate([qpos, ppos], axis=1)
    sp = mesh.shape[axis]
    if (s % sp) or (kk.shape[1] % sp):
        raise ValueError(
            f"sp chunk shapes must divide the {axis!r} axis: "
            f"S={s}, S+W*bs={kk.shape[1]}, sp={sp}"
        )
    return ring_attention(
        q, kk, vv, qpos, kpos, mesh, axis=axis, scale=scale,
        head_axis=head_axis,
    )
