"""Engine-facing sequence-parallel prefill attention.

``sp_prefill_attention`` is the drop-in long-context replacement for
ops/attention.py::prefill_attention: same [B, S, ...] interface, but the
sequence dim is sharded over the mesh's ``sp`` axis so a prompt far larger
than one chip's attention memory prefills across the slice. Handles
padding to the axis size and strategy selection (ring for very long S,
all-to-all when heads divide nicely).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.compat import shard_map
from .ring_attention import _NEG, _ring_partials, ring_attention, ulysses_attention


def choose_strategy(seq_len: int, num_kv_heads: int, sp: int) -> str:
    """ring: communication scales with S and works for any head count;
    ulysses: lower latency at moderate S but needs KVH % sp == 0."""
    if num_kv_heads % sp == 0 and seq_len <= 32768:
        return "ulysses"
    return "ring"


def sp_prefill_attention(
    q: jax.Array,  # [B, S, H, D]
    k: jax.Array,  # [B, S, KVH, D]
    v: jax.Array,
    valid_lens: jax.Array,  # [B]
    mesh: Mesh,
    axis: str = "sp",
    strategy: str = "auto",
    scale: Optional[float] = None,
) -> jax.Array:
    """Causal self-attention over the full prompt, sequence-sharded.

    Pads S up to a multiple of the sp axis size (padded positions are
    masked via position id -1) and strips the padding from the output.
    """
    sp = mesh.shape[axis]
    b, s, _h, _d = q.shape
    pad = (-s) % sp
    if pad:
        zeros_q = jnp.zeros((b, pad) + q.shape[2:], q.dtype)
        zeros_kv = jnp.zeros((b, pad) + k.shape[2:], k.dtype)
        q = jnp.concatenate([q, zeros_q], axis=1)
        k = jnp.concatenate([k, zeros_kv], axis=1)
        v = jnp.concatenate([v, zeros_kv], axis=1)
    s_padded = s + pad
    # global positions; everything at/after a row's valid_len is padding
    pos = jnp.arange(s_padded, dtype=jnp.int32)[None, :].repeat(b, axis=0)
    pos = jnp.where(pos < valid_lens[:, None], pos, -1)

    if strategy == "auto":
        strategy = choose_strategy(s_padded, k.shape[2], sp)
    if strategy == "ring":
        out = ring_attention(q, k, v, pos, pos, mesh, axis=axis, scale=scale)
    elif strategy == "ulysses":
        out = ulysses_attention(q, k, v, pos, pos, mesh, axis=axis, scale=scale)
    else:
        raise ValueError(f"unknown sp strategy {strategy!r}; use auto|ring|ulysses")
    return out[:, :s]


def sp_chunk_attention(
    q: jax.Array,            # [1, S, H, D] post-RoPE chunk queries
    k: jax.Array,            # [1, S, KVH, D] the chunk's fresh keys
    v: jax.Array,            # [1, S, KVH, D]
    k_cache: jax.Array,      # [L, N, bs, KVH, Dpad] stacked paged cache
    v_cache: jax.Array,
    block_tables: jax.Array,  # [1, W] this sequence's block ids
    chunk_start,             # traced scalar: first absolute position
    context_len,             # traced scalar: chunk end (valid tokens incl.)
    layer_idx,               # traced scalar: layer into the stacked cache
    mesh: Mesh,
    axis: str = "sp",
    head_axis: Optional[str] = None,
    scale: Optional[float] = None,
    impl: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """Attention for ONE sequence-sharded prefill chunk of a long prompt.

    The serving half of sequence parallelism (engine/model_runner.py
    ``prefill_sp``): the chunk's queries and fresh K/V are sharded over
    the mesh's ``axis``; earlier chunks' KV already live in the paged
    cache. Both sources fold into ONE online softmax. Two routes:

    - **Pallas kernel route** (``impl`` resolves to pallas): one ring
      pass over the chunk's fresh K/V only
      (ring_attention._ring_partials), while each device reads the
      committed prefix straight out of its local paged cache with the
      double-buffered page-DMA kernel
      (ops/pallas_sp.paged_prefix_attention_partials) — the cache is
      replicated over ``axis`` (only ``head_axis`` shards it), so no
      gather, no concat, and per-device prefix memory is O(pages in
      flight). The two partial sets merge exp-weighted and normalize
      once, bit-compatible row-for-row with one joint softmax.

    - **XLA gather route** (fallback): the committed prefix is gathered
      from the cache for this layer, sharded over the same axis,
      concatenated behind the chunk's K/V, and rotated around the ring
      with global position ids doing all masking — per-device key
      memory O((S + W·bs)/sp), but the gather itself materializes the
      full [1, W·bs, KVH, D] prefix before the sharding constraint can
      split it.

    Both routes: chunk keys carry their global positions (causal
    intra-chunk); prefix keys are exactly the cache slots
    ``< chunk_start`` (committed KV only — the chunk's own
    just-scattered slots are never double-counted, and a prefix-cache
    hit's reused blocks are covered for free).

    Ring (not Ulysses) deliberately: arbitrary head counts, and the
    rotation overlaps the interconnect with compute at exactly the long
    sequence lengths this path exists for.
    """
    from ..ops.attention import record_route, resolve_attention_impl

    b, s, _h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    sp = mesh.shape[axis]
    interpret = interpret or bool(os.environ.get("DYN_PALLAS_INTERPRET"))
    if resolve_attention_impl(impl) == "pallas":
        if s % sp:
            raise ValueError(
                f"sp chunk S must divide the {axis!r} axis: S={s}, sp={sp}"
            )
        record_route("sp_ring_kernel")
        return _sp_chunk_kernel_route(
            q, k, v, k_cache, v_cache,
            block_tables.astype(jnp.int32),
            jnp.asarray(chunk_start, jnp.int32).reshape(1),
            jnp.asarray(context_len, jnp.int32).reshape(1),
            jnp.asarray(layer_idx, jnp.int32).reshape(1),
            mesh=mesh, axis=axis, head_axis=head_axis, scale=scale,
            interpret=interpret,
        )
    record_route("sp_ring_gather")
    l, n_blocks = k_cache.shape[:2]
    # layer indexing through the gather (ops/attention.py idiom): block
    # n of layer li is flat row li*N + n — no full-layer copy
    kc = k_cache.reshape((l * n_blocks,) + k_cache.shape[2:])
    vc = v_cache.reshape((l * n_blocks,) + v_cache.shape[2:])
    rows = block_tables + layer_idx * n_blocks               # [1, W]
    w = block_tables.shape[1]
    bs_sz = k_cache.shape[2]
    pk = kc[rows].reshape(b, w * bs_sz, kc.shape[-2], kc.shape[-1])
    pv = vc[rows].reshape(b, w * bs_sz, vc.shape[-2], vc.shape[-1])
    # slice lane padding away and upcast fp8 storage to the compute dtype
    pk = pk[..., :d].astype(q.dtype)
    pv = pv[..., :d].astype(q.dtype)
    # distribute the gathered prefix over the sequence axis BEFORE the
    # ring, so no device ever holds the whole context
    kv_spec = NamedSharding(mesh, P(None, axis, head_axis, None))
    pk = jax.lax.with_sharding_constraint(pk, kv_spec)
    pv = jax.lax.with_sharding_constraint(pv, kv_spec)

    idx = jnp.arange(s, dtype=jnp.int32)[None, :]
    take = context_len - chunk_start
    qpos = jnp.where(idx < take, chunk_start + idx, -1)      # [1, S]
    cpos = jnp.arange(w * bs_sz, dtype=jnp.int32)[None, :]
    # prefix keys: strictly before the chunk (committed KV only); the
    # chunk's own slots and any pad/garbage blocks mask to -1
    ppos = jnp.where(cpos < chunk_start, cpos, -1)

    kk = jnp.concatenate([k, pk], axis=1)
    vv = jnp.concatenate([v, pv], axis=1)
    kpos = jnp.concatenate([qpos, ppos], axis=1)
    if (s % sp) or (kk.shape[1] % sp):
        raise ValueError(
            f"sp chunk shapes must divide the {axis!r} axis: "
            f"S={s}, S+W*bs={kk.shape[1]}, sp={sp}"
        )
    return ring_attention(
        q, kk, vv, qpos, kpos, mesh, axis=axis, scale=scale,
        head_axis=head_axis,
    )


def _sp_chunk_kernel_route(
    q, k, v, k_cache, v_cache, block_tables, chunk_start, context_len,
    layer_idx, *, mesh, axis, head_axis, scale, interpret,
):
    """Kernelized chunk attention: ring partials over the fresh chunk K/V
    merged with paged-prefix partials read in place from the cache.

    One shard_map: queries/chunk-KV sharded [None, axis, head_axis,
    None]; the cache enters sharded ONLY over ``head_axis`` (replicated
    across ``axis`` — exactly the engine's CACHE_SPEC), so each sp
    device walks its local pages for its own query shard and the full
    [W·bs] prefix is never materialized anywhere.

    The merge is the standard two-source online-softmax combine: with
    per-row (m_r, l_r, o_r) from the ring and (m_p, l_p, acc_p) from
    the prefix kernel, ``m = max(m_r, m_p)``, each side scales by
    ``exp(m_x − m)``, sums add, and one divide normalizes. Pad query
    rows (position -1) have empty ring partials already; their prefix
    partials are masked to empty here so the row stays exactly 0.
    """
    b, s, h, d = q.shape
    kernel = functools.partial(
        _sp_chunk_body, axis=axis, scale=scale, interpret=interpret,
    )
    seq = P(None, axis, head_axis, None)
    pos = P(None, axis)
    cache = P(None, None, None, head_axis, None)
    return shard_map(
        kernel, mesh=mesh,
        in_specs=(seq, seq, seq, pos, P(None, None), cache, cache,
                  P(None), P(None)),
        out_specs=seq,
        check_vma=False,
    )(
        q, k, v,
        _chunk_qpos(s, chunk_start, context_len),
        block_tables, k_cache, v_cache, chunk_start, layer_idx,
    )


def _chunk_qpos(s, chunk_start, context_len):
    """Global query positions for one chunk; rows past the valid tail
    (the last chunk's padding) get -1 and mask out everywhere."""
    idx = jnp.arange(s, dtype=jnp.int32)[None, :]
    return jnp.where(idx < context_len - chunk_start,
                     chunk_start + idx, -1)


def _sp_chunk_body(q, k, v, qpos, btab, kc, vc, pfx, li, *,
                   axis, scale, interpret):
    from ..ops.pallas_sp import paged_prefix_attention_partials

    b, sq, h, d = q.shape
    # ring over the chunk's fresh K/V only: kpos == qpos (the chunk IS
    # the newest keys; causality intra-chunk via global positions)
    o_r, m_r, l_r = _ring_partials(
        q, k, v, qpos, qpos, axis=axis, scale=scale
    )                                            # [B,KVH,G,Sq(,D)] f32
    acc_p, m_p, l_p = paged_prefix_attention_partials(
        q, kc, vc, btab, pfx[0], li[0],
        scale=scale, interpret=interpret,
    )                                            # [B,Sq,KVH,G(,D)] f32
    acc_p = acc_p.transpose(0, 2, 3, 1, 4)
    m_p = m_p.transpose(0, 2, 3, 1)
    l_p = l_p.transpose(0, 2, 3, 1)
    # pad query rows attended the whole prefix inside the kernel (it has
    # no notion of query validity); empty their partials so the merged
    # row is exactly 0 like the gather route's
    padded = (qpos < 0)[:, None, None, :]
    m_p = jnp.where(padded, _NEG, m_p)
    l_p = jnp.where(padded, 0.0, l_p)
    acc_p = jnp.where(padded[..., None], 0.0, acc_p)

    m = jnp.maximum(m_r, m_p)
    a_r = jnp.exp(m_r - m)
    a_p = jnp.exp(m_p - m)
    l_tot = a_r * l_r + a_p * l_p
    o = (o_r * a_r[..., None] + acc_p * a_p[..., None]) / jnp.where(
        l_tot == 0.0, 1.0, l_tot
    )[..., None]
    return o.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, d).astype(q.dtype)
