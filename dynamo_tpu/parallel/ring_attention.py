"""Sequence-parallel attention for long-context prefill.

Two interchangeable strategies over an ``sp`` mesh axis (neither exists in
the reference, which caps context by config and offloads long prefills —
SURVEY.md §2.12; this is the TPU-native long-context answer):

- **Ring attention** (`ring_attention`): Q stays put; K/V (and their
  position ids) rotate around the ring via ``ppermute`` while each device
  accumulates flash-style online-softmax partials (running max ``m``, sum
  ``l``, weighted accumulator ``o``). sp devices hold S/sp of the sequence
  each, so per-device attention memory is O((S/sp)^2) and the K/V rotation
  overlaps with compute on the ICI ring. Communication-optimal for
  S >> heads.

- **Ulysses / all-to-all** (`ulysses_attention`): two ``all_to_all``s
  reshard [seq/sp, H] -> [seq, H/sp], run plain local attention over the
  full sequence with H/sp heads per device, then reshard back. Cheaper at
  moderate S when H is divisible by sp; requires KVH % sp == 0.

Both handle GQA (H query heads grouped over KVH KV heads) and causal
masking by *global* position ids, so ragged/padded batches work: pad
positions with -1 and they are masked out everywhere.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.compat import shard_map

_NEG = -0.5 * jnp.finfo(jnp.float32).max


def _gqa_scores(q5, k, scale):
    """q5: [B,Sq,KVH,G,D] f32; k: [B,Sk,KVH,D] -> [B,KVH,G,Sq,Sk]."""
    return jnp.einsum("bqhgd,bkhd->bhgqk", q5, k.astype(jnp.float32)) * scale


def _causal_mask(q_pos, k_pos):
    """[B,Sq],[B,Sk] global positions -> bool [B,1,1,Sq,Sk]; -1 pads drop."""
    valid = (k_pos >= 0)[:, None, None, None, :] & (q_pos >= 0)[:, None, None, :, None]
    causal = k_pos[:, None, None, None, :] <= q_pos[:, None, None, :, None]
    return valid & causal


def _ring_partials(q, k, v, q_pos, k_pos, *, axis: str, scale: float):
    """Per-device online-softmax partials under shard_map: the full ring
    rotation WITHOUT the final normalization. Returns the unnormalized
    accumulator ``o`` [B,KVH,G,Sq,D] f32 plus the running max ``m`` and
    sum ``l`` [B,KVH,G,Sq] f32 — so callers can merge further key
    sources (the paged-prefix kernel in parallel/sequence.py) before
    dividing. Fully-masked rows keep m == _NEG and l == 0."""
    n = lax.psum(1, axis)
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    g = h // kvh
    q5 = q.reshape(b, sq, kvh, g, d).astype(jnp.float32)

    o = jnp.zeros((b, kvh, g, sq, d), jnp.float32)
    m = jnp.full((b, kvh, g, sq), _NEG, jnp.float32)
    l = jnp.zeros((b, kvh, g, sq), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(_, carry):
        o, m, l, k, v, k_pos = carry
        s = _gqa_scores(q5, k, scale)                        # [B,KVH,G,Sq,Sk]
        s = jnp.where(_causal_mask(q_pos, k_pos), s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        # rows with no valid key anywhere keep m_new == _NEG; zero their
        # probabilities so the final output is 0, not mean(V)
        p = jnp.where(
            (m_new > _NEG / 2)[..., None], jnp.exp(s - m_new[..., None]), 0.0
        )
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        o = o * alpha[..., None] + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v.astype(jnp.float32)
        )
        k, v, k_pos = (lax.ppermute(x, axis, perm) for x in (k, v, k_pos))
        return o, m_new, l, k, v, k_pos

    o, m, l, _, _, _ = lax.fori_loop(0, n, body, (o, m, l, k, v, k_pos))
    return o, m, l


def _ring_kernel(q, k, v, q_pos, k_pos, *, axis: str, scale: float):
    """Per-device body under shard_map: seq dim sharded over ``axis``."""
    b, sq, h, d = q.shape
    o, _m, l = _ring_partials(q, k, v, q_pos, k_pos, axis=axis, scale=scale)
    out = o / jnp.maximum(l, 1e-30)[..., None]               # fully-masked rows -> 0
    return out.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, d).astype(q.dtype)


def ring_attention(
    q: jax.Array,       # [B, Sq, H, D]
    k: jax.Array,       # [B, Sk, KVH, D]
    v: jax.Array,       # [B, Sk, KVH, D]
    q_positions: jax.Array,   # [B, Sq] global positions (-1 = pad)
    kv_positions: jax.Array,  # [B, Sk]
    mesh: Mesh,
    axis: str = "sp",
    scale: Optional[float] = None,
    head_axis: Optional[str] = None,
) -> jax.Array:
    """Causal GQA attention with the sequence dim sharded over ``axis``.

    Sq and Sk must each be divisible by the axis size (they need not be
    equal: the serving chunk path concatenates the chunk's fresh K/V
    with the gathered committed prefix, so Sk > Sq). ``head_axis``
    optionally shards the head dim too (tensor parallelism composes:
    heads over tp, sequence over sp — the ring rotates within each tp
    shard's heads). Returns [B, Sq, H, D] sharded the same way as q.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    seq = P(None, axis, head_axis, None)
    pos = P(None, axis)
    kernel = functools.partial(_ring_kernel, axis=axis, scale=scale)
    return shard_map(
        kernel, mesh=mesh,
        in_specs=(seq, seq, seq, pos, pos),
        out_specs=seq,
        check_vma=False,
    )(q, k, v, q_positions, kv_positions)


def _ulysses_kernel(q, k, v, q_pos, k_pos, *, axis: str, scale: float):
    b, _s_loc, _h, d = q.shape  # [B, S/n, H, D] per device

    def to_seq_major(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]: split heads, gather sequence
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def to_head_major(x):
        # inverse: [B, S, H/n, D] -> [B, S/n, H, D]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    q_f = to_seq_major(q)
    k_f = to_seq_major(k)
    v_f = to_seq_major(v)
    qp = lax.all_gather(q_pos, axis, axis=1, tiled=True)   # [B, S]
    kp = lax.all_gather(k_pos, axis, axis=1, tiled=True)

    kvh_loc = k_f.shape[2]
    g = q_f.shape[2] // kvh_loc
    q5 = q_f.reshape(b, q_f.shape[1], kvh_loc, g, d).astype(jnp.float32)
    s = _gqa_scores(q5, k_f, scale)
    s = jnp.where(_causal_mask(qp, kp), s, _NEG)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.where(m > _NEG / 2, jnp.exp(s - m), 0.0)  # fully-masked rows -> 0
    o = jnp.einsum("bhgqk,bkhd->bhgqd", p, v_f.astype(jnp.float32))
    o = o / jnp.maximum(p.sum(-1), 1e-30)[..., None]
    o = o.transpose(0, 3, 1, 2, 4).reshape(b, q_f.shape[1], q_f.shape[2], d)
    return to_head_major(o).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_positions: jax.Array,
    kv_positions: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all sequence parallelism: reshard seq->heads, attend, reshard
    back. Requires KVH % axis_size == 0 (heads divide over the axis)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = mesh.shape[axis]
    if k.shape[2] % n != 0:
        raise ValueError(f"ulysses needs num_kv_heads % sp == 0, got {k.shape[2]} % {n}")
    seq = P(None, axis, None, None)
    pos = P(None, axis)
    kernel = functools.partial(_ulysses_kernel, axis=axis, scale=scale)
    return shard_map(
        kernel, mesh=mesh,
        in_specs=(seq, seq, seq, pos, pos),
        out_specs=seq,
        check_vma=False,
    )(q, k, v, q_positions, kv_positions)


def dense_reference(q, k, v, q_positions, kv_positions, scale=None):
    """Unsharded causal GQA attention — the correctness oracle for tests."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    b, sq, h, d = q.shape
    kvh = k.shape[2]
    q5 = q.reshape(b, sq, kvh, h // kvh, d).astype(jnp.float32)
    s = _gqa_scores(q5, k, scale)
    s = jnp.where(_causal_mask(q_positions, kv_positions), s, _NEG)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.where(m > _NEG / 2, jnp.exp(s - m), 0.0)  # fully-masked rows -> 0
    o = jnp.einsum("bhgqk,bkhd->bhgqd", p, v.astype(jnp.float32))
    o = o / jnp.maximum(p.sum(-1), 1e-30)[..., None]
    return o.transpose(0, 3, 1, 2, 4).reshape(b, sq, h, d).astype(q.dtype)
