"""Parallelism toolkit: meshes, multi-host bring-up, sequence parallelism.

See mesh.py for the axis vocabulary (dp/tp/sp/ep/pp) and
ring_attention.py / sequence.py for long-context attention.
"""

from .mesh import AXES, MultiHostConfig, initialize_multihost, make_mesh, mesh_shape
from .ring_attention import dense_reference, ring_attention, ulysses_attention
from .sequence import choose_strategy, sp_prefill_attention

__all__ = [
    "AXES",
    "MultiHostConfig",
    "initialize_multihost",
    "make_mesh",
    "mesh_shape",
    "dense_reference",
    "ring_attention",
    "ulysses_attention",
    "choose_strategy",
    "sp_prefill_attention",
]
