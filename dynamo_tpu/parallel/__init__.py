"""Parallelism toolkit: meshes, multi-host bring-up, sequence + pipeline
parallelism.

See mesh.py for the axis vocabulary (dp/tp/sp/ep/pp),
ring_attention.py / sequence.py for long-context attention, and
pipeline.py for the collective GPipe schedule over the pp axis.
"""

from .mesh import AXES, MultiHostConfig, initialize_multihost, make_mesh, mesh_shape
from .pipeline import pipeline_forward, stage_cache, stage_params, unstage_cache
from .ring_attention import dense_reference, ring_attention, ulysses_attention
from .sequence import choose_strategy, sp_chunk_attention, sp_prefill_attention

__all__ = [
    "pipeline_forward",
    "stage_cache",
    "stage_params",
    "unstage_cache",
    "AXES",
    "MultiHostConfig",
    "initialize_multihost",
    "make_mesh",
    "mesh_shape",
    "dense_reference",
    "ring_attention",
    "ulysses_attention",
    "choose_strategy",
    "sp_chunk_attention",
    "sp_prefill_attention",
]
