"""Device-mesh construction for every parallelism axis the framework uses.

One mesh, named axes, shardings annotated per-array — XLA inserts the
collectives (scaling-book recipe). Axes:

- ``dp``: data parallel / replica scaling (reference analog: worker
  replica sets, lib/runtime/src/component/client.rs:220-293)
- ``tp``: tensor parallel (reference: --tensor-parallel-size pass-through,
  launch/dynamo-run/src/flags.rs:62 — here native Megatron sharding)
- ``sp``: sequence/context parallel for long-context prefill (ring or
  all-to-all attention; absent in the reference — SURVEY.md §2.12)
- ``ep``: expert parallel for MoE (reference: TRT-LLM
  moe_expert_parallel_size pass-through only)
- ``pp``: pipeline stages (reference: vllm0_7 Ray-based PP)

Multi-host bring-up mirrors the reference's MultiNodeConfig
{num_nodes, node_rank, leader_addr} (reference: lib/llm/src/engines.rs:39-57,
Ray leader/follower in lib/engines/vllm0_7/src/ray.rs:66-230): JAX's
coordinator plays the leader, ICI carries intra-slice traffic, DCN
cross-slice.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Mapping, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

AXES = ("dp", "pp", "sp", "ep", "tp")  # canonical order, tp innermost (ICI)


def make_mesh(
    axes: Mapping[str, int], devices: Optional[Sequence] = None
) -> Mesh:
    """Mesh over ``axes`` ({name: size}); tp placed innermost so its
    collectives ride the fastest ICI links. Axes of size 1 are kept (specs
    may name them; XLA drops trivial collectives)."""
    import jax

    devices = list(devices) if devices is not None else jax.devices()
    names = tuple(a for a in AXES if a in axes)
    extra = set(axes) - set(names)
    if extra:
        raise ValueError(f"unknown mesh axes {sorted(extra)}; valid: {AXES}")
    sizes = tuple(int(axes[a]) for a in names)
    total = int(np.prod(sizes)) if sizes else 1
    if total > len(devices):
        raise ValueError(f"mesh {dict(axes)} needs {total} devices, have {len(devices)}")
    arr = np.asarray(devices[:total]).reshape(sizes or (1,))
    return Mesh(arr, names or ("dp",))


@dataclasses.dataclass
class MultiHostConfig:
    """Analog of the reference's MultiNodeConfig (engines.rs:39-57)."""

    leader_addr: str = ""     # "host:port" of the coordinator (node 0)
    num_nodes: int = 1
    node_rank: int = 0
    local_device_ids: Optional[Sequence[int]] = None


def initialize_multihost(cfg: MultiHostConfig) -> None:
    """Join this process to the multi-host JAX runtime.

    After this, ``jax.devices()`` is global across hosts and a mesh built
    from it spans slices (ICI within a slice, DCN across). No-op for a
    single node.
    """
    if cfg.num_nodes <= 1:
        return
    import jax

    if not cfg.leader_addr:
        raise ValueError("multi-host run needs leader_addr (coordinator host:port)")
    logger.info(
        "joining multihost runtime: leader=%s rank=%d/%d",
        cfg.leader_addr, cfg.node_rank, cfg.num_nodes,
    )
    jax.distributed.initialize(
        coordinator_address=cfg.leader_addr,
        num_processes=cfg.num_nodes,
        process_id=cfg.node_rank,
        local_device_ids=cfg.local_device_ids,
    )


def mesh_shape(mesh: Mesh) -> Tuple[Tuple[str, int], ...]:
    return tuple((name, size) for name, size in mesh.shape.items())
