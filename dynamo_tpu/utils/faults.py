"""First-class fault injection: make an engine wedge on demand.

The self-healing stack (recovery/) is only trustworthy if its failure
modes can be produced deterministically — a chaos test that waits for a
real Mosaic hang is not a test. ``DYN_FAULT`` names injection *sites*
compiled into the hot paths they sabotage::

    DYN_FAULT=decode_burst_hang:once            # wedge the next decode sync
    DYN_FAULT=transfer_conn_drop:0.1            # drop 10% of KV transfer conns
    DYN_FAULT=child_exit:once,decode_burst_hang:0.01

Spec grammar: ``site:once`` fires exactly once, ``site:<float>`` fires
with that probability per evaluation, ``site:off`` disarms. Tests arm
sites programmatically with ``arm()`` (no env mutation) and release
hung sites with ``release()``.

Sites currently wired (each documented in docs/self_healing.md):

- ``decode_burst_hang`` — the scheduler's decode host-sync blocks (in
  its executor thread) until ``release()``: the exact shape of a hung
  Mosaic compile or a dead device, and the wedge the stall watchdog's
  ``decode_stall`` trip exists to catch.
- ``transfer_conn_drop`` — a KV transfer / migration client connection
  dies mid-stream (and, for the KV fabric, the pull-SERVING side dies
  mid-serve), exercising the receiver's poison-the-commit path and the
  puller's local-recompute fallback.
- ``prefix_pull_stall`` — a cluster-KV-fabric prefix pull
  (kv/fabric.py) stalls mid-flight instead of dying: the scheduler's
  pull deadline must cancel it, fall back to local recompute with a
  byte-identical stream, and leak zero blocks on either side.
- ``child_exit`` — a supervised engine child (subprocess_host) exits
  hard mid-serve, exercising the respawn ladder.

Every fire is recorded in the flight ring (``fault.injected``) so a
chaos run's artifact shows exactly which failures were synthetic.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from typing import Dict, Optional

logger = logging.getLogger(__name__)

FAULT_ENV = "DYN_FAULT"

_lock = threading.Lock()
# site → spec: "once" (not yet fired) | float probability. Absent = off.
_armed: Dict[str, object] = {}
_env_loaded = False
# sites that hung and await release; created lazily per site
_hang_events: Dict[str, threading.Event] = {}
fired_total: Dict[str, int] = {}


def _load_env_locked() -> None:
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    raw = os.environ.get(FAULT_ENV, "")
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        site, _, spec = part.partition(":")
        try:
            _arm_locked(site.strip(), spec.strip() or "once")
        except ValueError as e:
            # a typo'd fault spec must never take the server down — the
            # operator is injecting faults on purpose, loudly
            logger.error("ignoring malformed %s entry %r: %s",
                         FAULT_ENV, part, e)


def _arm_locked(site: str, spec: str) -> None:
    if not site:
        raise ValueError("empty fault site")
    if spec == "off":
        _armed.pop(site, None)
        return
    if spec == "once":
        _armed[site] = "once"
        return
    p = float(spec)  # raises ValueError on garbage
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"probability {p} outside [0, 1]")
    _armed[site] = p


def arm(site: str, spec: str = "once") -> None:
    """Programmatically arm a site (tests; same grammar as DYN_FAULT)."""
    with _lock:
        _load_env_locked()
        _arm_locked(site, spec)


def reset() -> None:
    """Disarm everything and forget the env parse (tests)."""
    global _env_loaded
    with _lock:
        _armed.clear()
        fired_total.clear()
        _env_loaded = False
        for ev in _hang_events.values():
            ev.set()
        _hang_events.clear()


def fire(site: str) -> bool:
    """Should this evaluation of ``site`` fail? Consumes ``once`` arms.

    Thread-safe and cheap when nothing is armed (one dict lookup under
    a lock) — safe to call from executor threads and hot loops alike.
    """
    with _lock:
        _load_env_locked()
        spec = _armed.get(site)
        if spec is None:
            return False
        if spec == "once":
            del _armed[site]
        elif random.random() >= spec:
            return False
        fired_total[site] = fired_total.get(site, 0) + 1
    try:
        from ..telemetry.flight import flight_recorder

        flight_recorder().record("fault.injected", site=site)
    # dynlint: allow(silent-except) - the injection (and its WARNING below) must land even if the flight ring import fails mid-teardown
    except Exception:
        pass
    logger.warning("FAULT INJECTED [%s]", site)
    return True


def maybe_hang(site: str, timeout_s: float = 600.0) -> bool:
    """If ``site`` fires, BLOCK the calling thread until ``release()``
    (or the safety timeout). Call from the thread being sabotaged — for
    ``decode_burst_hang`` that is the scheduler's executor sync thread,
    never the event loop. Returns whether it hung."""
    if not fire(site):
        return False
    with _lock:
        ev = _hang_events.setdefault(site, threading.Event())
    ev.wait(timeout_s)
    return True


def release(site: Optional[str] = None) -> None:
    """Un-wedge hung sites (all of them when ``site`` is None)."""
    with _lock:
        events = (
            [e for s, e in _hang_events.items() if site in (None, s)]
        )
    for ev in events:
        ev.set()
