"""Device profiling hooks: jax.profiler capture, on demand.

The reference measures performance externally (genai-perf, perf.sh —
SURVEY.md §5 notes no in-repo profiler integration); on TPU the
first-class tool is the XLA profiler, so this framework wires it in as
part of the serving surface:

- ``enable_profiler_server(port)`` starts jax's profiler gRPC server —
  TensorBoard (or ``jax.profiler.trace_remote``) can then capture traces
  from a live worker, the standard remote-capture workflow.
- ``capture_trace(out_dir, seconds)`` records a trace window in-process
  (device activity + HLO annotations) — the engine's HTTP service
  exposes it at ``GET /debug/profile`` when ``--profile-dir`` is set, so
  an operator can grab a trace of live traffic with one curl.

Both are thin wrappers so non-serving code (bench.py, tests) can reuse
the same entry points.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import threading
import time
import uuid

logger = logging.getLogger(__name__)

_server_started = False

# jax.profiler.trace is NOT reentrant: a second trace starting while one
# is active crashes mid-capture (and can corrupt the first capture's
# output). Every capture path — GET /debug/profile, an incident bundle's
# --incident-profile-s window, bench harnesses — funnels through this
# process-wide lock; a loser gets CaptureBusyError (→ a clean 409 /
# "skipped" note) instead of a crash.
_capture_lock = threading.Lock()


class CaptureBusyError(RuntimeError):
    """Another profiler capture is already in flight in this process."""

# per-process capture counter: two captures in the same SECOND used to
# collide (strftime has second resolution) and exist_ok=True silently
# merged their trace files into one unreadable directory
_capture_seq = itertools.count()


def trace_dir_name() -> str:
    """Unique-per-capture directory name: timestamp (human ordering) +
    process-local counter (same-second captures in one process) + pid +
    random suffix (same-second captures across processes sharing the
    profile dir)."""
    return (
        time.strftime("trace-%Y%m%d-%H%M%S")
        + f"-{os.getpid()}-{next(_capture_seq):04d}-{uuid.uuid4().hex[:6]}"
    )


def enable_profiler_server(port: int) -> None:
    """Start the jax profiler gRPC server (idempotent; once per process)."""
    global _server_started
    if _server_started:
        return
    import jax

    jax.profiler.start_server(port)
    _server_started = True
    logger.info("jax profiler server on port %d (TensorBoard-capturable)", port)


def capture_trace(out_dir: str, seconds: float) -> str:
    """Record a profiler trace window; returns the trace directory.

    Blocking — run it in an executor from async code. Each capture lands
    in a timestamped subdirectory so consecutive captures never collide.
    Raises :class:`CaptureBusyError` when another capture holds the
    process-wide profiler lock (jax allows ONE active trace per process).
    """
    import jax

    if not _capture_lock.acquire(blocking=False):
        raise CaptureBusyError(
            "a profiler capture is already in flight in this process")
    try:
        trace_dir = os.path.join(out_dir, trace_dir_name())
        # exist_ok=False on purpose: a collision must fail loudly instead
        # of silently merging two captures into one directory
        os.makedirs(trace_dir)
        with jax.profiler.trace(trace_dir):
            time.sleep(seconds)
        return trace_dir
    finally:
        _capture_lock.release()


async def capture_trace_async(out_dir: str, seconds: float) -> str:
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, capture_trace, out_dir, seconds)
