"""Layered runtime configuration: defaults → TOML files → DYN_* env.

Reference analog: lib/runtime/src/config.rs:26-176 — Figment layering of
``Serialized::defaults`` / ``/opt/dynamo/{defaults,etc}/runtime.toml`` /
``Env::prefixed("DYN_RUNTIME_")`` with empty-env filtering. Same
precedence here (env on top), dataclass-typed, stdlib ``tomllib``.

Usage:
    @dataclasses.dataclass
    class MyConfig:
        num_workers: int = 16

    cfg = from_settings(MyConfig, "DYN_RUNTIME_")
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import List, Optional, Sequence, Type, TypeVar

try:
    import tomllib  # py3.11+ stdlib
except ModuleNotFoundError:  # py3.10: same parser, pre-stdlib package name
    try:
        import tomli as tomllib
    except ModuleNotFoundError:
        tomllib = None  # no TOML parser: file layers skipped, env still applies

logger = logging.getLogger(__name__)

T = TypeVar("T")

# same search order as the reference's figment(): defaults file then the
# site file; later layers win
DEFAULT_CONFIG_FILES = (
    "/opt/dynamo/defaults/runtime.toml",
    "/opt/dynamo/etc/runtime.toml",
)
CONFIG_PATH_ENV = "DYN_CONFIG_PATH"  # extra TOML, highest file layer


def _coerce(raw: str, field_type) -> object:
    """Env strings → the dataclass field's type. ``field_type`` may be a
    string (PEP 563 postponed annotations) or an actual type."""
    t = field_type if isinstance(field_type, str) else str(field_type)
    if "bool" in t:
        return raw.strip().lower() in ("1", "true", "yes", "on")
    if "int" in t:
        return int(raw)
    if "float" in t:
        return float(raw)
    if "ist[" in t or t == "list":  # List[...] / list[...]
        return json.loads(raw)
    return raw


def from_settings(
    cls: Type[T],
    env_prefix: str,
    config_files: Sequence[str] = DEFAULT_CONFIG_FILES,
    section: Optional[str] = None,
) -> T:
    """Build ``cls`` from defaults, TOML layers, then ``{env_prefix}FIELD``
    env vars (empty env values are ignored, like the reference's
    filter_map). Unknown TOML keys are ignored with a debug log; bad env
    values raise — misconfiguration should fail at startup, loudly."""
    values = {}
    fields = {f.name: f for f in dataclasses.fields(cls)}

    paths = list(config_files)
    extra = os.environ.get(CONFIG_PATH_ENV)
    if extra:
        paths.append(extra)
    for path in paths:
        if not os.path.exists(path):
            continue
        if tomllib is None:
            logger.warning(
                "tomllib unavailable (python < 3.11); ignoring config "
                "file %s — set %sFIELD env vars instead", path, env_prefix,
            )
            continue
        with open(path, "rb") as f:
            data = tomllib.load(f)
        if section is not None:
            data = data.get(section, {})
        for key, value in data.items():
            if key in fields:
                values[key] = value
            else:
                logger.debug("ignoring unknown config key %s in %s", key, path)

    for name, field in fields.items():
        raw = os.environ.get(f"{env_prefix}{name.upper()}")
        if raw:  # empty env vars are treated as unset (reference semantics)
            values[name] = _coerce(raw, field.type)
    return cls(**values)


@dataclasses.dataclass
class RuntimeSettings:
    """Worker-process runtime knobs (reference RuntimeConfig/WorkerConfig).

    ``DYN_RUNTIME_NUM_WORKER_THREADS`` sizes the blocking-work executor
    (the asyncio analog of the reference's tokio worker threads);
    ``DYN_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT`` bounds HTTP drain on SIGTERM.
    """

    num_worker_threads: int = 16
    graceful_shutdown_timeout: float = 30.0

    @classmethod
    def from_settings(cls) -> "RuntimeSettings":
        base = from_settings(cls, "DYN_RUNTIME_", section="runtime")
        # the reference reads the shutdown timeout under DYN_WORKER_
        raw = os.environ.get("DYN_WORKER_GRACEFUL_SHUTDOWN_TIMEOUT")
        if raw:
            base.graceful_shutdown_timeout = float(raw)
        return base
