"""JAX platform override helper.

Some environments import jax at interpreter startup via a site hook
pinned to the real TPU (platform "axon"), snapshotting jax's config
before per-process env vars can influence it — `JAX_PLATFORMS=cpu
python ...` is silently ignored. Re-applying the env var to the live
config after import restores the expected contract. Shared by the CLI,
bench harness, and any launcher that spawns workers with a forced
platform (tests/conftest.py applies the same pattern inline because it
must run before this package is importable).
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)


def apply_jax_platform_override() -> None:
    """Make ``JAX_PLATFORMS`` authoritative even after an early jax import.

    No-op when the env var is unset. A failure to apply is loud: the
    caller asked for a specific platform (usually to stay OFF a shared
    TPU), and silently proceeding on the wrong one queues compiles
    through the shared relay — the exact outage mode this guard exists
    to prevent.
    """
    requested = os.environ.get("JAX_PLATFORMS")
    if not requested:
        return
    try:
        import jax

        jax.config.update("jax_platforms", requested)
    except Exception as e:  # noqa: BLE001 - diagnosed, not swallowed
        logger.warning(
            "could not re-apply JAX_PLATFORMS=%s to jax config (%s: %s); "
            "jax may run on the platform selected at interpreter startup",
            requested, type(e).__name__, e,
        )
