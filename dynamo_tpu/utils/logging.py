"""Structured logging: env-filtered levels, JSONL option, request stages.

Reference analog: lib/runtime/src/logging.rs:94-180 —
- ``DYN_LOG``             env-filter spec: ``info`` or
                          ``warn,dynamo_tpu.engine=debug,aiohttp=error``
- ``DYN_LOGGING_JSONL=1`` one JSON object per line (machine-shippable)
- ``DYN_LOG_USE_LOCAL_TZ=1`` local timestamps instead of UTC

Per-request stage tracking mirrors the reference Context's stage list
(lib/runtime/src/pipeline/context.rs:125): operators call
``Context.add_stage(name)``; each entry records a monotonic timestamp so
the frontend can log a per-request latency breakdown at completion.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
from datetime import datetime, timezone
from typing import Optional

FILTER_ENV = "DYN_LOG"
JSONL_ENV = "DYN_LOGGING_JSONL"
LOCAL_TZ_ENV = "DYN_LOG_USE_LOCAL_TZ"

_LEVELS = {
    "trace": logging.DEBUG,  # python has no TRACE; map down
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


class JsonlFormatter(logging.Formatter):
    """One JSON object per record: time, level, target, message, extras."""

    def __init__(self, local_tz: bool = False):
        super().__init__()
        self.local_tz = local_tz

    def format(self, record: logging.LogRecord) -> str:
        if self.local_tz:
            ts = datetime.fromtimestamp(record.created).astimezone()
        else:
            ts = datetime.fromtimestamp(record.created, tz=timezone.utc)
        out = {
            "time": ts.isoformat(timespec="microseconds"),
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        for key in ("request_id", "stage", "stages"):
            value = getattr(record, key, None)
            if value is not None:
                out[key] = value
        if record.exc_info and record.exc_info[0] is not None:
            out["exception"] = self.formatException(record.exc_info)
        return json.dumps(out, ensure_ascii=False)


def parse_filter(spec: str, default_level: int = logging.INFO) -> tuple:
    """``"warn,foo=debug,bar.baz=error"`` → (root_level, {logger: level}).
    A spec with only per-logger directives keeps the caller's default root."""
    root = default_level
    per_logger = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            name, _, level = part.partition("=")
            per_logger[name.strip()] = _LEVELS.get(level.strip().lower(), logging.INFO)
        else:
            root = _LEVELS.get(part.lower(), logging.INFO)
    return root, per_logger


def setup_logging(default_level: int = logging.INFO, stream=None) -> None:
    """Install the process logging config from the DYN_* environment.

    Replaces ``logging.basicConfig`` at every binary entrypoint so one
    env surface controls format and filtering across frontend, workers,
    router, and broker — the reference's shared-format guarantee."""
    root_level, per_logger = (
        parse_filter(os.environ[FILTER_ENV], default_level)
        if os.environ.get(FILTER_ENV)
        else (default_level, {})
    )
    handler = logging.StreamHandler(stream or sys.stderr)
    if os.environ.get(JSONL_ENV, "").strip() in ("1", "true"):
        handler.setFormatter(
            JsonlFormatter(local_tz=os.environ.get(LOCAL_TZ_ENV) == "1")
        )
    else:
        handler.setFormatter(logging.Formatter(
            "%(levelname)s %(asctime)s %(name)s: %(message)s"
        ))
    root = logging.getLogger()
    root.handlers[:] = [handler]
    root.setLevel(root_level)
    for name, level in per_logger.items():
        logging.getLogger(name).setLevel(level)


def stage_summary(stages) -> str:
    """[(name, t_monotonic)] → "preprocess=1.2ms backend=0.3ms ..." deltas.

    ``name=<delta>`` is the time from the PREVIOUS mark to ``name`` —
    marks are stamped at phase completion, so the delta lands under the
    phase that actually spent it (same attribution as
    telemetry.tracing.span_breakdown). The tail from the last mark to
    now is ``egress``.
    """
    if not stages:
        return ""
    parts = []
    closed = list(stages) + [("egress", time.monotonic())]
    for (_, t), (name_next, t_next) in zip(closed, closed[1:]):
        parts.append(f"{name_next}={(t_next - t) * 1e3:.1f}ms")
    return " ".join(parts)
