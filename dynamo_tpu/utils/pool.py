"""Generic fixed-capacity object pool with RAII-style returns.

Reference analog: lib/runtime/src/utils/pool.rs:23-241 — a pool of
pre-created values handed out as unique items whose drop returns them,
convertible to shared (refcounted) items where the last clone returns.
Re-designed on asyncio: ``acquire`` awaits availability instead of
spinning, items are async-context-managers (the idiomatic Python RAII),
and a ``weakref.finalize`` safety net returns leaked items so a dropped
reference can never shrink the pool.

    pool = Pool([conn1, conn2], on_return=lambda c: c.reset())
    async with await pool.acquire() as conn:
        await conn.send(...)
    # returned (and reset) here — or at GC if the item leaks

Shared items (reference SharedPoolItem) let several readers hold one
value; the value returns when the last share is released:

    item = await pool.acquire()
    a, b = item.share(), item.share()
    a.release(); b.release()   # second release returns the value
"""

from __future__ import annotations

import asyncio
import weakref
from collections import deque
from typing import Any, Awaitable, Callable, Deque, Generic, List, Optional, TypeVar

T = TypeVar("T")


class PoolExhausted(Exception):
    """try_acquire on an empty pool / acquire past its deadline."""


class Pool(Generic[T]):
    def __init__(
        self,
        items: List[T],
        on_return: Optional[Callable[[T], None]] = None,
    ):
        self._items: Deque[T] = deque(items)
        self.capacity = len(items)
        self.on_return = on_return
        self._waiters: Deque[asyncio.Future] = deque()

    @classmethod
    async def create(
        cls,
        factory: Callable[[], Awaitable[T]],
        n: int,
        on_return: Optional[Callable[[T], None]] = None,
    ) -> "Pool[T]":
        return cls([await factory() for _ in range(n)], on_return=on_return)

    @property
    def available(self) -> int:
        return len(self._items)

    def try_acquire(self) -> "PoolItem[T]":
        if not self._items:
            raise PoolExhausted(f"pool empty ({self.capacity} items out)")
        return PoolItem(self, self._items.popleft())

    async def acquire(self, timeout: Optional[float] = None) -> "PoolItem[T]":
        if self._items:
            return PoolItem(self, self._items.popleft())
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            value = await (
                asyncio.wait_for(fut, timeout) if timeout is not None else fut
            )
        except (asyncio.TimeoutError, asyncio.CancelledError) as e:
            # the race that silently drains pools: _return may have
            # already handed the value to this future in the same tick
            # the timeout/cancel fired — recover it or it is lost forever
            if fut.done() and not fut.cancelled():
                self._return(fut.result())
            if isinstance(e, asyncio.CancelledError):
                raise  # cancellation must propagate, not become Exhausted
            raise PoolExhausted(
                f"no item available within {timeout}s"
            ) from None
        finally:
            if fut in self._waiters:  # timed out / cancelled before handoff
                self._waiters.remove(fut)
        return PoolItem(self, value)

    def _return(self, value: T) -> None:
        if self.on_return is not None:
            self.on_return(value)
        # direct hand-off to the oldest live waiter, else back to the deque
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(value)
                return
        self._items.append(value)


class PoolItem(Generic[T]):
    """Unique handle: exactly one return, on release/exit/GC."""

    def __init__(self, pool: Pool[T], value: T):
        self._pool = pool
        self._value: Optional[T] = value
        self._returned = False
        # the RAII safety net: a leaked (garbage-collected) item must not
        # shrink the pool. Deliberately does NOT hold a ref to self.
        self._finalizer = weakref.finalize(self, _return_once, pool, [value])

    @property
    def value(self) -> T:
        if self._returned:
            raise RuntimeError("pool item already returned")
        return self._value  # type: ignore[return-value]

    def release(self) -> None:
        if not self._returned:
            self._returned = True
            self._finalizer.detach()
            value, self._value = self._value, None
            self._pool._return(value)  # type: ignore[arg-type]

    def share(self) -> "SharedPoolItem[T]":
        """Convert to a refcounted shared handle (consumes this item)."""
        if self._returned:
            raise RuntimeError("pool item already returned")
        self._finalizer.detach()
        self._returned = True
        value, self._value = self._value, None
        state = _SharedState(self._pool, value)  # type: ignore[arg-type]
        return SharedPoolItem(state)

    async def __aenter__(self) -> T:
        return self.value

    async def __aexit__(self, *exc) -> None:
        self.release()

    def __enter__(self) -> T:
        return self.value

    def __exit__(self, *exc) -> None:
        self.release()


def _return_once(pool: Pool, box: list) -> None:
    if box:
        pool._return(box.pop())


class _SharedState(Generic[T]):
    def __init__(self, pool: Pool[T], value: T):
        self.pool = pool
        self.value = value
        self.count = 0
        self.returned = False
        # same GC safety net as PoolItem: once every SharedPoolItem handle
        # is dropped (released or leaked), this state is unreachable and
        # the finalizer returns the value if no explicit release did
        self._finalizer = weakref.finalize(
            self, _return_shared_once, pool, [value]
        )

    def drop(self) -> None:
        self.count -= 1
        if self.count == 0 and not self.returned:
            self.returned = True
            self._finalizer.detach()
            self.pool._return(self.value)


def _return_shared_once(pool: Pool, box: list) -> None:
    if box:
        pool._return(box.pop())


class SharedPoolItem(Generic[T]):
    """Cloneable handle; the LAST release returns the value."""

    def __init__(self, state: _SharedState[T]):
        self._state = state
        self._released = False
        state.count += 1

    @property
    def value(self) -> T:
        if self._released:
            raise RuntimeError("shared pool item already released")
        return self._state.value

    @property
    def strong_count(self) -> int:
        return self._state.count

    def share(self) -> "SharedPoolItem[T]":
        if self._released:
            raise RuntimeError("shared pool item already released")
        return SharedPoolItem(self._state)

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._state.drop()
