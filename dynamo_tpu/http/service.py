"""OpenAI-compatible HTTP frontend (aiohttp).

Routes: POST /v1/chat/completions, POST /v1/completions, GET /v1/models,
GET /metrics, GET /health. SSE streaming with a client-disconnect monitor
that stops generation; non-streaming requests aggregate the chunk stream.

Reference analog: lib/llm/src/http/service/openai.rs:132-539 (axum routes +
disconnect monitor), service.rs ModelManager, service_v2 builder, and the
model discovery watcher (http/service/discovery.rs:37-171) that hot-adds
remote models registered in the discovery plane.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any, AsyncIterator, Dict, Optional

import msgpack
from aiohttp import web

from ..protocols import sse
from ..protocols.annotated import Annotated
from ..utils.logging import stage_summary
from ..protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    CompletionRequest,
    CompletionResponse,
    ModelInfo,
    ModelList,
    aggregate_chat_stream,
    aggregate_completion_stream,
)
from ..runtime.client import Client, NoInstancesError, RouterMode
from ..runtime.component import DistributedRuntime
from ..runtime.discovery import WatchEventType
from ..runtime.engine import (
    AsyncEngine,
    AsyncEngineContext,
    Context,
    EngineDrainingError,
    EngineError,
)
from ..runtime.network import ResponseStreamError
from ..telemetry.tracing import TraceRecorder
from .metrics import ServiceMetrics

logger = logging.getLogger(__name__)

MODEL_REGISTRY_PREFIX = "models/"  # under the http namespace


class ModelManager:
    """name → engine maps for chat and completion models, as a live view
    over the model registry (registry/registry.py): served aliases and
    tenant visibility resolve through the registered cards; engines
    without cards (local single-model serving, BYO) stay public under
    their exact name."""

    def __init__(self, registry=None) -> None:
        from ..registry.registry import ModelRegistry

        self.chat_engines: Dict[str, AsyncEngine] = {}
        self.completion_engines: Dict[str, AsyncEngine] = {}
        self.metadata: Dict[str, dict] = {}  # name → /v1/models extras
        self.registry = registry or ModelRegistry()

    def set_metadata(self, name: str, **meta) -> None:
        self.metadata.setdefault(name, {}).update(
            {k: v for k, v in meta.items() if v is not None}
        )

    def set_card(self, card) -> None:
        self.registry.put(card)

    def add_chat_model(self, name: str, engine: AsyncEngine) -> None:
        self.chat_engines[name] = engine

    def add_completion_model(self, name: str, engine: AsyncEngine) -> None:
        self.completion_engines[name] = engine

    def remove_model(self, name: str) -> None:
        self.chat_engines.pop(name, None)
        self.completion_engines.pop(name, None)
        self.metadata.pop(name, None)  # a re-registration starts clean
        self.registry.remove(name)

    def resolve(self, model: str, tenant: Optional[str] = None
                ) -> Optional[str]:
        """Requested name/alias → canonical pool name, or None (unknown
        OR invisible to the tenant — the same answer, so tenants cannot
        probe each other's catalogs). Card-less engine names resolve to
        themselves and are public."""
        if self.registry.lookup(model) is not None:
            return self.registry.resolve(model, tenant)
        if model in self.chat_engines or model in self.completion_engines:
            return model
        return None

    def served_names(self) -> list:
        """Every model with an engine, visibility-blind — the operator
        surface (/health), never a tenant-facing catalog."""
        return sorted(set(self.chat_engines) | set(self.completion_engines))

    def model_names(self, tenant: Optional[str] = None) -> list:
        names = set(self.chat_engines) | set(self.completion_engines)
        if not self.registry.cards:
            return sorted(names)
        visible = []
        for name in names:
            card = self.registry.card(name)
            if card is None or card.visible_to(tenant):
                visible.append(name)
        return sorted(visible)


class HttpService:
    def __init__(
        self,
        manager: Optional[ModelManager] = None,
        host: str = "0.0.0.0",
        port: int = 8080,
        metrics_prefix: str = "dynamo",
        profile_dir: Optional[str] = None,
        admission=None,  # planner.admission.AdmissionController
        slo=None,        # telemetry.slo.SloTracker
        trace_ttl_s: Optional[float] = None,
        trace_capacity: Optional[int] = None,
        hub=None,        # telemetry.hub.FleetHub
        incidents=None,  # telemetry.incidents.IncidentRecorder
        quotas=None,     # registry.tenants.TenantQuotas
        pools=None,      # registry.pools.PoolManager
    ):
        self.manager = manager or ModelManager()
        self.host = host
        self.port = port
        self.metrics = ServiceMetrics(metrics_prefix)
        # optional HTTP-edge admission control (priority classes, bounded
        # queues, load shedding) — the actuated end of the SLA planner
        self.admission = admission
        if admission is not None:
            self.metrics.attach_registry(admission.registry)
        # multi-tenant quota layer (registry/tenants.py): X-Tenant →
        # per-tenant token buckets, checked BEFORE the priority queues
        # so one tenant's spike sheds that tenant at the door
        self.quotas = quotas
        if quotas is not None:
            self.metrics.attach_registry(quotas.registry)
        # per-model pool manager (registry/pools.py): cold-start gate +
        # scale-to-zero loop; None = models must be warm to serve
        self.pools = None
        if pools is not None:
            self.attach_pools(pools)
        self.metrics.attach_registry(self.manager.registry.registry)
        # optional SLO attainment + goodput accounting: per-request
        # TTFT / worst-ITL verdicts at the edge (telemetry/slo.py)
        if slo is not None:
            self.metrics.slo = slo
            self.metrics.attach_registry(slo.registry)
        # completed request traces: ingress-assigned trace ids (honoring
        # X-Request-Id) → span breakdowns at GET /debug/requests/{id},
        # cluster-stitched timelines at GET /debug/trace/{id}. Bounded
        # by max-entries LRU AND TTL (evictions counted on
        # dynamo_trace_evicted_total) so traffic can't grow trace memory
        self.traces = TraceRecorder(
            capacity=trace_capacity, ttl_s=trace_ttl_s,
            registry=self.metrics.registry,
        )
        self.profile_dir = profile_dir
        self.app = web.Application()
        self.app.router.add_post("/v1/chat/completions", self.handle_chat)
        self.app.router.add_post("/v1/completions", self.handle_completions)
        self.app.router.add_post("/v1/embeddings", self.handle_embeddings)
        self.app.router.add_get("/v1/models", self.handle_models)
        self.app.router.add_get("/metrics", self.handle_metrics)
        self.app.router.add_get("/health", self.handle_health)
        self.app.router.add_get("/debug/requests", self.handle_debug_requests)
        self.app.router.add_get("/debug/requests/{rid}", self.handle_debug_request)
        self.app.router.add_get("/debug/trace/{rid}", self.handle_debug_trace)
        self.app.router.add_get("/debug/flight", self.handle_flight)
        # zero-downtime rolling updates: drain + live-migrate in-flight
        # requests to peers (recovery/controller.py). Wired by the CLI
        # when --self-heal builds a RecoveryController; 501 otherwise.
        self.drainer = None  # async (mode, respawn) -> summary dict
        self.app.router.add_post("/admin/drain", self.handle_admin_drain)
        # dynamic model management (registry/registry.py RegistryAdmin,
        # wired by the CLI when a discovery plane exists; 501 otherwise)
        # — the llmctl/dynamoctl surface over HTTP
        self.registry_admin = None
        self.app.router.add_get("/admin/models", self.handle_admin_models)
        self.app.router.add_post("/admin/models",
                                 self.handle_admin_model_add)
        self.app.router.add_delete("/admin/models/{name}",
                                   self.handle_admin_model_remove)
        self.app.router.add_get("/admin/pools", self.handle_admin_pools)
        # fleet telemetry hub + incident recorder (telemetry/hub.py,
        # telemetry/incidents.py): wired by the CLI (--hub /
        # DYN_INCIDENT_DIR); the routes answer 501 when the subsystem is
        # off so an operator learns the flag instead of guessing at 404s
        self.hub = hub
        self.incidents = incidents
        if hub is not None:
            self.metrics.attach_registry(hub.registry)
        if incidents is not None:
            self.metrics.attach_registry(incidents.registry)
        self.app.router.add_get("/fleet/metrics", self.handle_fleet_metrics)
        self.app.router.add_get("/fleet/workers", self.handle_fleet_workers)
        self.app.router.add_get("/debug/incidents", self.handle_incidents)
        if profile_dir:
            # opt-in only: trace capture costs device time and writes disk
            self.app.router.add_get("/debug/profile", self.handle_profile)
            self._profile_lock = asyncio.Lock()
        self._runner: Optional[web.AppRunner] = None
        self._site: Optional[web.TCPSite] = None

    def attach_pools(self, pools) -> None:
        """Attach a PoolManager after construction (the CLI builds it
        once the model watcher exists) — gates requests AND merges its
        instruments into this service's exposition."""
        self.pools = pools
        self.metrics.attach_registry(pools.registry)

    # ---------- lifecycle ----------

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, self.host, self.port)
        await self._site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        logger.info("http service on %s:%d", self.host, self.port)

    async def stop_accepting(self) -> None:
        """Close the listening socket but keep in-flight connections alive
        (the first phase of graceful shutdown: drain without accepting)."""
        if self._site is not None:
            await self._site.stop()
            self._site = None

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        # close() joins the trace writer thread — off-loop, so a hung
        # JSONL filesystem can't stall the rest of shutdown
        await asyncio.get_running_loop().run_in_executor(
            None, self.traces.close)

    # ---------- helpers ----------

    @staticmethod
    def _error(status: int, message: str, err_type: str = "invalid_request_error"):
        return web.json_response(
            {"error": {"message": message, "type": err_type, "code": status}},
            status=status,
        )

    @staticmethod
    def _model_not_found(model: str):
        """The OpenAI 404 body — also the answer for a model another
        tenant CAN see (existence must not leak across tenants)."""
        return web.json_response(
            {"error": {
                "message": f"The model '{model}' does not exist or you "
                           "do not have access to it.",
                "type": "invalid_request_error",
                "param": "model",
                "code": "model_not_found",
            }},
            status=404,
        )

    def _resolve_tenant(self, request: web.Request) -> str:
        """X-Tenant → tenant id (absent/garbage degrades to default —
        the X-Priority parsing contract). Tenant IDENTITY always parses
        — card visibility must work on a quota-less frontend too; the
        quota gate additionally counts garbage headers."""
        from ..registry.tenants import TENANT_HEADER, parse_tenant

        header = request.headers.get(TENANT_HEADER)
        if self.quotas is not None:
            return self.quotas.resolve(header)
        return parse_tenant(header)

    async def _handle_inference(
        self, request: web.Request, request_cls, engines: Dict[str, AsyncEngine],
        chunk_cls, aggregate, kind: str = "chat",
    ) -> web.StreamResponse:
        try:
            body = await request.json()
            api_req = request_cls.model_validate(body)
        except (json.JSONDecodeError, ValueError) as e:
            return self._error(400, f"invalid request: {e}")

        tenant = self._resolve_tenant(request)
        # registry resolution: alias → canonical pool name, tenant
        # visibility enforced (unknown and invisible answer identically)
        name = self.manager.resolve(api_req.model, tenant)
        if name is None:
            return self._model_not_found(api_req.model)
        card = self.manager.registry.card(name)
        if card is not None and card.model_type not in (kind, "both"):
            # registered for the OTHER endpoint kind: for this API the
            # model does not exist — a 404, never a forever-retry 503
            return self._model_not_found(api_req.model)
        if name != api_req.model:
            # canonicalize the OUTBOUND model: downstream hops (the
            # processor's pool partition, worker metadata, per-model
            # metrics) key on the canonical pool name — an alias must
            # not leak past the edge (responses echo the resolved
            # model, the OpenAI alias convention)
            api_req.model = name
        rid = (request.headers.get("X-Request-Id") or "").strip()[:128]
        if self.quotas is not None:
            # tenant token buckets BEFORE the priority queues: a tenant
            # over its requests/s or tokens/s budget is shed at the door
            # (429 + Retry-After), other tenants untouched
            from ..planner.admission import AdmissionRejected

            try:
                self.quotas.admit(tenant, request_id=rid)
            except AdmissionRejected as e:
                return web.json_response(
                    {"error": {"message": str(e), "type": "overloaded",
                               "code": 429}},
                    status=429,
                    headers={"Retry-After": e.retry_after_header},
                )
        if self.pools is not None:
            self.pools.note_request(name)
            if card is not None:
                # cold-start gate: a warm pool passes through in one
                # dict lookup; a registered-but-cold model (scale-to-
                # zero drained its pool, or the record exists with no
                # client yet) kicks a spawn with the model's card and
                # holds the request, bounded — past the deadline it
                # sheds with 503 + Retry-After
                from ..registry.pools import ColdStartTimeout

                try:
                    await self.pools.await_capacity(name)
                except ColdStartTimeout as e:
                    return web.json_response(
                        {"error": {"message": str(e),
                                   "type": "service_unavailable",
                                   "code": 503}},
                        status=503,
                        headers={"Retry-After":
                                 str(max(1, int(e.retry_after_s)))},
                    )
        engine = engines.get(name)
        if engine is None:
            if card is not None:
                # the card exists but no worker serves the pool and no
                # cold-start path is configured: transient, retryable
                return web.json_response(
                    {"error": {"message": f"model '{api_req.model}' has "
                               "no live workers",
                               "type": "service_unavailable", "code": 503}},
                    status=503, headers={"Retry-After": "5"},
                )
            return self._model_not_found(api_req.model)

        admitted = False
        if self.admission is not None:
            # priority-class admission control (planner/admission.py):
            # shed/deadline rejections answer 429 + Retry-After BEFORE the
            # request counts as inflight — shed traffic is accounted on
            # the dynamo_planner_* instruments, not the service timers
            from ..planner.admission import AdmissionRejected, parse_priority

            priority = parse_priority(request.headers.get("X-Priority"))
            try:
                await self.admission.acquire(priority, request_id=rid)
                admitted = True
            except AdmissionRejected as e:
                return web.json_response(
                    {"error": {"message": str(e), "type": "overloaded",
                               "code": 429}},
                    status=429,
                    headers={"Retry-After": e.retry_after_header},
                )

        # per-model accounting keys on the CANONICAL pool name, so an
        # alias's traffic lands on its model's series
        timer = self.metrics.track(name)
        status = "error"
        # token-bucket accounting by ACTUAL streamed tokens — the charge
        # rides the same sites the SLO goodput counter does
        if self.quotas is not None:
            quotas, q_tenant = self.quotas, tenant

            def charge(n: int) -> None:
                quotas.charge_tokens(q_tenant, n)
        else:
            charge = None
        # ingress-assigned trace id: honor the client's X-Request-Id so
        # callers can correlate their logs with /debug/requests/{id} and
        # every downstream hop (scheduler spans, remote prefill) by id.
        # It is correlation-only: the engine-side request id stays a fresh
        # UUID (AsyncEngineContext.id), so a reused/duplicate client id
        # cannot collide in scheduler or disagg-coordinator state.
        ctx = Context(api_req, AsyncEngineContext(trace_id=rid or None))
        ctx.add_stage("http")
        try:
            stream = engine.generate(ctx).__aiter__()
            # prime the first chunk BEFORE committing a status line so
            # request-validation errors (raised on first iteration of the
            # pipeline generator) still map to proper HTTP codes
            try:
                first = await stream.__anext__()
            except StopAsyncIteration:
                first = None
            if api_req.stream:
                resp, status = await self._stream_sse(
                    request, ctx, first, stream, timer, charge=charge)
                return resp
            def _check_annotated(chunk):
                """None for data chunks; the envelope for annotations.
                Error envelopes raise — a swallowed error must not look ok."""
                ann = Annotated.maybe_from_wire(chunk)
                if ann is not None and ann.is_error:
                    raise EngineError(
                        ann.comment[0] if ann.comment else "engine error"
                    )
                return ann

            chunks = []
            if first is not None and _check_annotated(first) is None:
                chunks.append(chunk_cls.model_validate(_as_dict(first)))
            async for chunk in stream:
                if _check_annotated(chunk) is not None:
                    continue  # annotations are stream-only side channel
                d = _as_dict(chunk)
                if _has_payload(d):
                    n = _payload_tokens(d)
                    timer.token(n)
                    if charge is not None:
                        charge(n)
                chunks.append(chunk_cls.model_validate(d))
            status = "success"
            return web.json_response(
                aggregate(chunks).model_dump(exclude_none=True),
                headers={"X-Request-Id": ctx.trace_id},
            )
        except EngineDrainingError as e:
            # transient: the worker behind this engine is draining for a
            # recovery or rolling update — clients/LBs should retry
            return web.json_response(
                {"error": {"message": str(e), "type": "service_unavailable",
                           "code": 503}},
                status=503, headers={"Retry-After": "1"},
            )
        except (EngineError, ValueError) as e:
            return self._error(400, str(e))
        except NoInstancesError as e:
            # an empty pool is transient by design (workers churn,
            # scale-to-zero drains) — tell the client when to come back
            return web.json_response(
                {"error": {"message": str(e), "type": "service_unavailable",
                           "code": 503}},
                status=503, headers={"Retry-After": "5"},
            )
        except (ResponseStreamError, asyncio.TimeoutError) as e:
            return self._error(502, str(e), "engine_error")
        except _StreamDisconnect:
            status = "disconnect"
            raise ConnectionResetError("client disconnected")
        except asyncio.CancelledError:
            ctx.context.stop_generating()
            status = "disconnect"
            raise
        finally:
            if admitted:
                self.admission.release()
            ctx.context.stop_generating()
            timer.finish(status)
            self.traces.record(ctx.trace_id, name, status,
                               ctx.stages, ctx=ctx.context)
            if ctx.stages and logger.isEnabledFor(logging.DEBUG):
                logger.debug(
                    "request %s %s: %s",
                    ctx.trace_id, status, stage_summary(ctx.stages),
                    extra={"request_id": ctx.trace_id,
                           "stages": [s for s, _ in ctx.stages]},
                )

    async def _stream_sse(
        self,
        request: web.Request,
        ctx: Context,
        first: Any,
        chunks: AsyncIterator[Any],
        timer,
        charge=None,  # tenant token-bucket accounting (registry/tenants.py)
    ):
        resp = web.StreamResponse(
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-cache",
                "Connection": "keep-alive",
                "X-Request-Id": ctx.trace_id,
            }
        )
        await resp.prepare(request)

        async def _write(chunk) -> bool:
            """Write one stream element; True = stream must terminate."""
            ann = Annotated.maybe_from_wire(chunk)
            if ann is not None:
                if ann.is_error:
                    # match the mid-stream exception convention below:
                    # error payload on a data line, then end the stream
                    await resp.write(sse.encode_event(
                        {"error": {"message": ann.comment[0] if ann.comment
                                   else "engine error"}}
                    ))
                    return True
                # annotation events ride SSE event/comment lines with no
                # data payload (reference annotated.rs wire mapping)
                await resp.write(sse.encode_event(
                    None, event=ann.event,
                    comment=ann.comment[0] if ann.comment else None,
                ))
                return False
            d = _as_dict(chunk)
            if _has_payload(d):
                n = _payload_tokens(d)
                timer.token(n)
                if charge is not None:
                    charge(n)
            await resp.write(sse.encode_event(d))
            return False

        try:
            failed = first is not None and await _write(first)
            if not failed:
                async for chunk in chunks:
                    if await _write(chunk):
                        failed = True
                        break
            await resp.write(sse.encode_done())
            await resp.write_eof()
            if failed:
                ctx.context.stop_generating()
                return resp, "error"
            return resp, "success"
        except (ConnectionResetError, asyncio.CancelledError):
            # client went away — stop generation upstream
            ctx.context.stop_generating()
            raise _StreamDisconnect()
        except (EngineError, ResponseStreamError, NoInstancesError) as e:
            # mid-stream failure: emit an error event, then end the stream
            await resp.write(sse.encode_event({"error": {"message": str(e)}}))
            await resp.write(sse.encode_done())
            await resp.write_eof()
            return resp, "error"

    # ---------- routes ----------

    async def handle_chat(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_inference(
            request, ChatCompletionRequest, self.manager.chat_engines,
            ChatCompletionChunk, aggregate_chat_stream, kind="chat",
        )

    async def handle_completions(self, request: web.Request) -> web.StreamResponse:
        return await self._handle_inference(
            request, CompletionRequest, self.manager.completion_engines,
            CompletionResponse, aggregate_completion_stream,
            kind="completions",
        )

    async def handle_embeddings(self, request: web.Request) -> web.Response:
        """POST /v1/embeddings — the prefill-only workload riding the
        batched-prefill path (llm/embeddings.py): OpenAI-shaped request
        (input: str | [str] | [ids] | [[ids]]) and response (data rows
        + usage counts). Served when the resolved engine carries an
        ``embedder``; engines without one (echo chat, remote pools whose
        frontend sits on the decode tier) answer 501 with a routing
        hint."""
        import base64 as _b64

        from ..llm.embeddings import EmbeddingError

        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return self._error(400, f"invalid request: {e}")
        if not isinstance(body, dict):
            return self._error(400, "request body must be a JSON object")
        model = body.get("model")
        if not isinstance(model, str) or not model:
            return self._error(400, "missing model")
        if "input" not in body:
            return self._error(400, "missing input")
        fmt = body.get("encoding_format", "float")
        if fmt not in ("float", "base64"):
            return self._error(
                400, "encoding_format must be 'float' or 'base64'")
        tenant = self._resolve_tenant(request)
        name = self.manager.resolve(model, tenant)
        if name is None:
            return self._model_not_found(model)
        engine = (self.manager.chat_engines.get(name)
                  or self.manager.completion_engines.get(name))
        embedder = getattr(engine, "embedder", None)
        if embedder is None:
            return self._error(
                501,
                f"model '{model}' does not serve embeddings on this "
                "frontend (embeddings ride the prefill path — route to "
                "a prefill-pool frontend; docs/long_context.md)",
                err_type="not_implemented",
            )
        try:
            vectors, ntok = await embedder.embed(body["input"])
        except EmbeddingError as e:
            return self._error(400, str(e))
        data = []
        for i, vec in enumerate(vectors):
            if fmt == "base64":
                import numpy as _np

                emb = _b64.b64encode(
                    _np.asarray(vec, _np.float32).tobytes()
                ).decode("ascii")
            else:
                emb = vec
            data.append(
                {"object": "embedding", "index": i, "embedding": emb}
            )
        return web.json_response({
            "object": "list",
            "data": data,
            "model": model,
            "usage": {"prompt_tokens": ntok, "total_tokens": ntok},
        })

    async def handle_models(self, request: web.Request) -> web.Response:
        """GET /v1/models — card-enriched (family, context length,
        aliases, owned_by) and filtered by the caller's tenant
        visibility; card-less engines keep their metadata-only rows."""
        tenant = self._resolve_tenant(request)
        data = []
        for name in self.manager.model_names(tenant):
            meta = dict(self.manager.metadata.get(name, {}))
            card = self.manager.registry.card(name)
            if card is not None:
                meta.setdefault("model_type", card.model_type)
                if card.context_length:
                    meta.setdefault("max_model_len", card.context_length)
                data.append(ModelInfo(
                    id=name, owned_by=card.owned_by, family=card.family,
                    aliases=card.aliases or None, **meta,
                ))
            else:
                data.append(ModelInfo(id=name, **meta))
        return web.json_response(
            ModelList(data=data).model_dump(exclude_none=True)
        )

    async def handle_metrics(self, request: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render(), content_type="text/plain")

    async def handle_health(self, request: web.Request) -> web.Response:
        # operator surface: every served model, visibility-blind — a
        # readiness probe must see tenant-scoped models too
        return web.json_response(
            {"status": "ok", "models": self.manager.served_names()})

    async def handle_debug_requests(self, request: web.Request) -> web.Response:
        """GET /debug/requests?limit=N — the most recent completed traces
        (newest last), for finding an id when the client didn't pick one."""
        try:
            limit = int(request.query.get("limit", "20"))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        return web.json_response(
            {"traces": self.traces.recent(max(1, min(limit, 200)))}
        )

    async def handle_debug_request(self, request: web.Request) -> web.Response:
        """GET /debug/requests/{id} — per-request span breakdown (stage
        names, offsets, durations) for a completed request. Issue the
        request with an X-Request-Id header to pick the id yourself."""
        rid = request.match_info["rid"]
        trace = self.traces.get(rid)
        if trace is None:
            return web.json_response(
                {"error": f"no completed trace for request id {rid!r} "
                          "(unknown, evicted, or still in flight)"},
                status=404,
            )
        return web.json_response(trace)

    async def handle_debug_trace(self, request: web.Request) -> web.Response:
        """GET /debug/trace/{id} — the request X-ray: every process's
        spans (frontend, router hop, decode engine, prefill worker,
        migration peer) stitched onto ONE clock-adjusted axis, plus the
        per-hop offset/rtt estimates and the unattributed gaps. The
        cluster answer to "where did this request's 900 ms TTFT go"."""
        from ..telemetry.stitch import stitched_timeline, timeline_gaps

        rid = request.match_info["rid"]
        trace = self.traces.get(rid)
        if trace is None:
            return web.json_response(
                {"error": f"no completed trace for request id {rid!r} "
                          "(unknown, evicted, or still in flight)"},
                status=404,
            )
        stitched = stitched_timeline(trace)
        return web.json_response({
            "request_id": trace["request_id"],
            "model": trace.get("model"),
            "status": trace.get("status"),
            "total_s": trace.get("total_s"),
            "sources": stitched["sources"],
            "timeline": stitched["timeline"],
            "gaps": timeline_gaps(stitched["timeline"],
                                  min_gap_s=0.0005),
        })

    async def handle_flight(self, request: web.Request) -> web.Response:
        """GET /debug/flight[?save=1][&request=<id>] — the flight-recorder
        dump on demand: ring events (optionally filtered to one request
        id), all-thread stacks, every registered engine's liveness probe,
        request table, and metrics snapshot (telemetry/watchdog.py). The
        same artifact the stall watchdog writes on a trip; ``save=1``
        additionally persists it to DYN_FLIGHT_DIR."""
        from ..telemetry.watchdog import build_flight_artifact, write_flight_artifact

        loop = asyncio.get_running_loop()
        # stack walking + metrics rendering off-loop: /debug/flight is
        # exactly the endpoint an operator hits when the loop is ailing
        artifact = await loop.run_in_executor(
            None, lambda: build_flight_artifact(reason="debug_endpoint")
        )
        if request.query.get("save"):
            # persist the COMPLETE dump before any response filtering: an
            # on-disk artifact must never silently be a one-request slice
            artifact["artifact_path"] = await loop.run_in_executor(
                None, lambda: write_flight_artifact(artifact)
            )
        rid = request.query.get("request")
        if rid:
            artifact["events"] = [
                e for e in artifact["events"]
                if e.get("request_id") == rid or e.get("trace_id") == rid
            ]
            artifact["filtered_to_request"] = rid
        return web.json_response(artifact, dumps=lambda o: json.dumps(
            o, default=str))

    async def handle_admin_drain(self, request: web.Request) -> web.Response:
        """POST /admin/drain[?mode=migrate|fail][&respawn=1] — stop
        admission, let committed bursts finish, live-migrate the rest to
        healthy peers, and (optionally) respawn — the rolling-model-
        update runbook in docs/self_healing.md. Returns the drain
        summary (requests finished / migrated / failed, duration)."""
        if self.drainer is None:
            return web.json_response(
                {"error": "no recovery controller attached "
                          "(serve with --self-heal)"},
                status=501,
            )
        mode = request.query.get("mode", "migrate")
        if mode not in ("migrate", "fail"):
            return web.json_response({"error": f"bad mode {mode!r}"},
                                     status=400)
        respawn = request.query.get("respawn") in ("1", "true", "yes")
        summary = await self.drainer(mode=mode, respawn=respawn)
        return web.json_response(summary)

    async def handle_admin_models(self, request: web.Request) -> web.Response:
        """GET /admin/models — every registered card, unfiltered (this
        is the operator surface, not the tenant-scoped /v1/models)."""
        return web.json_response({
            "models": [card.to_wire() for _, card in
                       sorted(self.manager.registry.cards.items())],
        })

    async def handle_admin_model_add(self, request: web.Request
                                     ) -> web.Response:
        """POST /admin/models — register a model card dynamically (the
        ``llmctl http add`` / ``dynamoctl models add`` analogue). The
        frontend's watcher picks the record up and binds the route; no
        restart. Body: a ModelCard wire dict (name + endpoint required)."""
        if self.registry_admin is None:
            return web.json_response(
                {"error": "no registry admin attached (serve with a "
                          "discovery plane: --store-port)"},
                status=501,
            )
        from ..registry.cards import ModelCard

        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object (a card)")
            card = ModelCard.from_wire(body)
            if not card.name or not card.endpoint:
                raise ValueError("name and endpoint are required")
            await self.registry_admin.add(card)
        except (json.JSONDecodeError, ValueError, TypeError) as e:
            return self._error(400, f"invalid model card: {e}")
        return web.json_response({"registered": card.name})

    async def handle_admin_model_remove(self, request: web.Request
                                        ) -> web.Response:
        """DELETE /admin/models/{name} — unregister; routes unbind as
        the watcher sees the delete."""
        if self.registry_admin is None:
            return web.json_response(
                {"error": "no registry admin attached (serve with a "
                          "discovery plane: --store-port)"},
                status=501,
            )
        name = request.match_info["name"]
        card = self.manager.registry.card(name)
        await self.registry_admin.remove(
            name, card.model_type if card is not None else None)
        return web.json_response({"removed": name})

    async def handle_admin_pools(self, request: web.Request) -> web.Response:
        """GET /admin/pools — per-model pool rows: live workers, idle
        age, cold-start state (what the scale-to-zero policy sees)."""
        if self.pools is None:
            return web.json_response(
                {"error": "no pool manager attached (serve with "
                          "--pool-scale-to-zero-idle-s or a cold-start "
                          "backend)"},
                status=501,
            )
        return web.json_response({"pools": self.pools.snapshot()})

    async def handle_fleet_metrics(self, request: web.Request) -> web.Response:
        """GET /fleet/metrics — cluster rollups (sum/max/avg by role,
        counter rates) from the fleet hub's scraped histories."""
        if self.hub is None:
            return web.json_response(
                {"error": "no fleet hub attached (serve with --hub)"},
                status=501,
            )
        return await self.hub.handle_fleet_metrics(request)

    async def handle_fleet_workers(self, request: web.Request) -> web.Response:
        """GET /fleet/workers — per-worker KV/busy/roofline/SLO/drain
        rows; what scripts/dynamotop.py renders live."""
        if self.hub is None:
            return web.json_response(
                {"error": "no fleet hub attached (serve with --hub)"},
                status=501,
            )
        return await self.hub.handle_fleet_workers(request)

    async def handle_incidents(self, request: web.Request) -> web.Response:
        """GET /debug/incidents[?id=] — list / fetch incident bundles."""
        if self.incidents is None:
            return web.json_response(
                {"error": "no incident recorder attached (set "
                          "DYN_INCIDENT_DIR or --incident-dir)"},
                status=501,
            )
        return await self.incidents.handle_debug_incidents(request)

    async def handle_profile(self, request: web.Request) -> web.Response:
        """GET /debug/profile?seconds=N — capture an XLA profiler trace of
        live traffic (enabled only with a configured profile dir)."""
        from ..utils.profiling import CaptureBusyError, capture_trace_async

        try:
            seconds = float(request.query.get("seconds", "2"))
        except ValueError:
            return web.json_response({"error": "bad seconds"}, status=400)
        if seconds != seconds:  # NaN survives min/max clamping
            return web.json_response({"error": "bad seconds"}, status=400)
        seconds = min(max(seconds, 0.1), 60.0)
        # jax allows ONE active trace per process — serialize via a
        # non-blocking lock so a concurrent capture gets a clean 409
        if self._profile_lock.locked():
            return web.json_response(
                {"error": "a capture is already in flight"}, status=409
            )
        async with self._profile_lock:
            try:
                trace_dir = await capture_trace_async(
                    self.profile_dir, seconds)
            except CaptureBusyError as e:
                # the PROCESS-wide profiler lock is held by a capture that
                # didn't come through this endpoint (an incident bundle's
                # profile window) — same clean 409, never a crash
                return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"trace_dir": trace_dir, "seconds": seconds})


class _StreamDisconnect(Exception):
    """Internal: SSE client went away mid-stream."""


def _as_dict(chunk: Any) -> Any:
    if hasattr(chunk, "model_dump"):
        return chunk.model_dump(exclude_none=True)
    return chunk


def _payload_tokens(chunk: Any) -> int:
    """Token count of one payload chunk, for SLO goodput accounting.
    OpenAI chat/completions chunks carry one token per chunk on every
    current engine path (the scheduler emits per token even under
    speculative decode); token-level shapes expose token_ids, so a
    future multi-token chunk still counts fully."""
    if isinstance(chunk, dict) and isinstance(chunk.get("token_ids"), list):
        return len(chunk["token_ids"])
    return 1


def _has_payload(chunk: Any) -> bool:
    """True if the chunk carries generated content (TTFT should fire)."""
    if not isinstance(chunk, dict):
        return True
    for choice in chunk.get("choices", []):
        if (choice.get("delta") or {}).get("content") or choice.get("text"):
            return True
    return False


# ---------- model registry + discovery watcher ----------


def model_registry_key(namespace: str, model_type: str, name: str) -> str:
    return f"{namespace}/{MODEL_REGISTRY_PREFIX}{model_type}/{name}"


async def register_model(
    drt: DistributedRuntime,
    namespace: str,
    name: str,
    endpoint_path: str,
    model_type: str = "chat",
    mdc: Optional[dict] = None,
    lease_scoped: bool = True,
    card=None,  # registry.cards.ModelCard: the fleet card riding along
) -> None:
    """Register a served model in the discovery plane (llmctl analog).

    ``endpoint_path`` is a dyn://ns.comp.ep address whose workers accept
    OpenAI-level requests (preprocessing is worker-side, as in the
    reference's v0.1.1 layout). With ``card`` the record carries the
    full fleet card (family, aliases, tenant visibility, cold-start
    material) the registry-aware frontend serves and pools by.
    """
    entry = {"name": name, "endpoint": endpoint_path, "model_type": model_type}
    if mdc:
        entry["mdc"] = mdc
    if card is not None:
        entry["card"] = card.to_wire()
    lease = await drt.discovery.primary_lease() if lease_scoped else None
    await drt.discovery.kv_put(
        model_registry_key(namespace, model_type, name),
        msgpack.packb(entry, use_bin_type=True),
        lease_id=lease.id if lease else None,
    )


async def unregister_model(
    drt: DistributedRuntime, namespace: str, name: str, model_type: str = "chat"
) -> None:
    await drt.discovery.kv_delete(model_registry_key(namespace, model_type, name))


async def list_models(drt: DistributedRuntime, namespace: str) -> list:
    kvs = await drt.discovery.kv_get_prefix(f"{namespace}/{MODEL_REGISTRY_PREFIX}")
    return [msgpack.unpackb(v, raw=False) for v in kvs.values()]


def parse_endpoint_path(path: str):
    """'dyn://ns.comp.ep' → (ns, comp, ep)."""
    body = path[len("dyn://"):] if path.startswith("dyn://") else path
    parts = body.split(".")
    if len(parts) != 3:
        raise ValueError(f"bad endpoint path {path!r}; want dyn://ns.comp.ep")
    return parts[0], parts[1], parts[2]


class ModelWatcher:
    """Hot-add/remove models from discovery-plane registrations."""

    def __init__(
        self,
        drt: DistributedRuntime,
        manager: ModelManager,
        namespace: str = "public",
        router_mode: RouterMode = RouterMode.ROUND_ROBIN,
    ):
        self.drt = drt
        self.manager = manager
        self.namespace = namespace
        self.router_mode = router_mode
        self._clients: Dict[str, Client] = {}
        self._task: Optional[asyncio.Task] = None
        self._watcher = None
        # strong refs to in-flight client.close() tasks spawned from the
        # sync delete path: a bare ensure_future can be GC'd mid-close
        # and would drop any close() exception on the floor
        self._closing: set = set()

    async def start(self) -> None:
        prefix = f"{self.namespace}/{MODEL_REGISTRY_PREFIX}"
        snapshot, watcher = await self.drt.discovery.watch_prefix(prefix)
        self._watcher = watcher
        for key, value in snapshot.items():
            await self._handle_put(key, value)
        self._task = self.drt.runtime.spawn(self._loop(watcher))

    async def _loop(self, watcher) -> None:
        async for ev in watcher:
            try:
                if ev.type == WatchEventType.PUT:
                    await self._handle_put(ev.key, ev.value)
                else:
                    self._handle_delete(ev.key)
            except Exception:
                logger.exception("model watcher failed on %s", ev.key)

    async def _handle_put(self, key: str, value: bytes) -> None:
        entry = msgpack.unpackb(value, raw=False)
        name = entry["name"]
        ns, comp, ep = parse_endpoint_path(entry["endpoint"])
        endpoint = self.drt.namespace(ns).component(comp).endpoint(ep)
        card = None
        if entry.get("card"):
            from ..registry.cards import ModelCard

            try:
                card = ModelCard.from_wire(entry["card"])
            except (TypeError, ValueError):
                logger.warning("malformed model card for %s ignored "
                               "(serving by entry fields only)", name,
                               exc_info=True)
        # per-model pool: when a card names the pool, the client only
        # routes to endpoint instances whose registration metadata says
        # they serve THIS model (several pools can share one component);
        # card-less registrations keep the whole-endpoint behavior
        client = await Client(
            endpoint, self.router_mode,
            model=card.name if card is not None else None,
        ).start()
        previous = self._clients.pop(name, None)
        if previous is not None:
            # re-registration PUT: release the old client's watch task
            # instead of leaking one per worker churn event
            await previous.close()
        # start clean: a narrowed model_type must not leave the closed
        # client behind in the other engine map, nor stale metadata
        self.manager.remove_model(name)
        self._clients[name] = client
        model_type = entry.get("model_type", "chat")
        self.manager.set_metadata(
            name,
            model_type=model_type,
            max_model_len=(entry.get("mdc") or {}).get("context_length"),
        )
        if card is not None:
            self.manager.set_card(card)
        if model_type in ("chat", "both"):
            self.manager.add_chat_model(name, client)
        if model_type in ("completions", "both"):
            self.manager.add_completion_model(name, client)
        logger.info("model %s → %s registered (%s)", name, entry["endpoint"], model_type)

    def pool_size(self, name: str) -> int:
        """Live workers in one model's pool — what the pool manager's
        cold-start gate and scale-to-zero policy consult."""
        client = self._clients.get(name)
        if client is None:
            return 0
        return len(client.eligible_ids())

    def _handle_delete(self, key: str) -> None:
        name = key.rsplit("/", 1)[-1]
        self.manager.remove_model(name)
        client = self._clients.pop(name, None)
        if client is not None:
            task = asyncio.ensure_future(client.close())
            self._closing.add(task)

            def _done(t: asyncio.Task, model: str = name) -> None:
                self._closing.discard(t)
                if not t.cancelled() and t.exception() is not None:
                    logger.warning("closing client for removed model %s "
                                   "failed: %s", model, t.exception())

            task.add_done_callback(_done)
        logger.info("model %s removed", name)

    async def stop(self) -> None:
        if self._watcher is not None:
            self._watcher.cancel()
        if self._task is not None:
            self._task.cancel()
        # drain close() tasks spawned by deletes racing shutdown, so their
        # exceptions are observed before the loop is torn down under them
        if self._closing:
            await asyncio.gather(*list(self._closing), return_exceptions=True)
        for client in self._clients.values():
            await client.close()
