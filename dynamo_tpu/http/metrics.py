"""Prometheus metrics for the HTTP service (no client lib in env — the
text exposition format is simple enough to emit directly).

Reference analog: lib/llm/src/http/service/metrics.rs:37-130 —
``{prefix}_http_service_requests_total`` / ``_inflight_requests`` /
``_request_duration_seconds`` labelled by model and status.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str):
        self.name = name
        self.help = help_
        self.values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        self.values[key] = self.values.get(key, 0.0) + amount

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for key, val in sorted(self.values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


class Gauge(Counter):
    def set(self, value: float, **labels: str) -> None:
        self.values[tuple(sorted(labels.items()))] = value

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for key, val in sorted(self.values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {val}")
        return lines


class Histogram:
    def __init__(self, name: str, help_: str, buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = buckets
        self.counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self.sums: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self.totals: Dict[Tuple[Tuple[str, str], ...], int] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        if key not in self.counts:
            self.counts[key] = [0] * len(self.buckets)
            self.sums[key] = 0.0
            self.totals[key] = 0
        for i, b in enumerate(self.buckets):
            if value <= b:
                self.counts[key][i] += 1
        self.sums[key] += value
        self.totals[key] += 1

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for key in sorted(self.counts):
            labels = dict(key)
            for i, b in enumerate(self.buckets):
                lines.append(
                    f"{self.name}_bucket{_fmt_labels({**labels, 'le': str(b)})} {self.counts[key][i]}"
                )
            lines.append(
                f"{self.name}_bucket{_fmt_labels({**labels, 'le': '+Inf'})} {self.totals[key]}"
            )
            lines.append(f"{self.name}_sum{_fmt_labels(labels)} {self.sums[key]}")
            lines.append(f"{self.name}_count{_fmt_labels(labels)} {self.totals[key]}")
        return lines


class _CallbackGauges:
    """Gauges whose values come from a callback at render time."""

    def __init__(self, prefix: str, fn):
        self.prefix = prefix
        self.fn = fn

    def render(self) -> List[str]:
        lines: List[str] = []
        try:
            vals = self.fn() or {}
            if not isinstance(vals, dict):
                return []  # BYO engines may return anything
            for k, v in sorted(vals.items()):
                if isinstance(v, bool) or not isinstance(v, (int, float)):
                    continue
                name = f"{self.prefix}_{k}"
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {float(v)}")
        except Exception:
            return []  # a broken engine must not take /metrics down
        return lines


class ServiceMetrics:
    """The HTTP service's metric set + request timing helper."""

    def __init__(self, prefix: str = "dynamo"):
        self.requests_total = Counter(
            f"{prefix}_http_service_requests_total", "Total HTTP requests by model/status"
        )
        self.inflight = Gauge(
            f"{prefix}_http_service_inflight_requests", "In-flight requests by model"
        )
        self.duration = Histogram(
            f"{prefix}_http_service_request_duration_seconds",
            "Request duration by model",
        )
        self.ttft = Histogram(
            f"{prefix}_http_service_time_to_first_token_seconds",
            "Time to first streamed token by model",
        )
        self._extra = []

    def register(self, metric) -> None:
        self._extra.append(metric)

    def register_callback_gauges(self, prefix: str, fn) -> None:
        """Expose a dict-returning callback (e.g. the in-process
        engine's ForwardPassMetrics analog — slot/KV occupancy, prefix
        hit rate, speculation acceptance) as Prometheus gauges, pulled
        fresh at every /metrics render."""
        self._extra.append(_CallbackGauges(prefix, fn))

    def inflight_total(self) -> float:
        """Sum of in-flight requests across models (graceful-drain gate)."""
        return sum(self.inflight.values.values())

    def render(self) -> str:
        lines: List[str] = []
        for m in (self.requests_total, self.inflight, self.duration, self.ttft, *self._extra):
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    class _Timer:
        def __init__(self, metrics: "ServiceMetrics", model: str):
            self.metrics = metrics
            self.model = model
            self.start = time.monotonic()
            self.status = "success"
            self.first_token_seen = False

        def first_token(self) -> None:
            if not self.first_token_seen:
                self.first_token_seen = True
                self.metrics.ttft.observe(time.monotonic() - self.start, model=self.model)

        def finish(self, status: str = "success") -> None:
            self.metrics.inflight.dec(model=self.model)
            self.metrics.requests_total.inc(model=self.model, status=status)
            self.metrics.duration.observe(time.monotonic() - self.start, model=self.model)

    def track(self, model: str) -> "ServiceMetrics._Timer":
        self.inflight.inc(model=model)
        return self._Timer(self, model)
