"""HTTP-edge metrics: the service's instrument set + request timing helper.

The instrument primitives (Counter/Gauge/Histogram and the label
escaping that makes model names with quotes/backslashes/newlines legal
exposition text) live in ``telemetry/registry.py``; this module keeps
the HTTP service's metric set and re-exports the primitives for
back-compat.

Reference analog: lib/llm/src/http/service/metrics.rs:37-130 —
``{prefix}_http_service_requests_total`` / ``_inflight_requests`` /
``_request_duration_seconds`` labelled by model and status.
"""

from __future__ import annotations

import time
from typing import Optional

from ..telemetry.registry import (  # noqa: F401 — re-exported for callers
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    format_labels as _fmt_labels,
)


class ServiceMetrics:
    """The HTTP service's metric set + request timing helper.

    All instruments live in ``self.registry`` — engine/scheduler/router
    registries attach there so a single ``GET /metrics`` scrape exposes
    every layer of the serving process.
    """

    def __init__(self, prefix: str = "dynamo",
                 registry: Optional[MetricsRegistry] = None):
        self.registry = registry or MetricsRegistry()
        # optional SLO tracker (telemetry/slo.py): successful requests
        # report their edge-measured TTFT / worst inter-token gap /
        # token count at finish for attainment + goodput accounting
        self.slo = None
        self.requests_total = self.registry.counter(
            f"{prefix}_http_service_requests_total", "Total HTTP requests by model/status"
        )
        self.inflight = self.registry.gauge(
            f"{prefix}_http_service_inflight_requests", "In-flight requests by model"
        )
        self.duration = self.registry.histogram(
            f"{prefix}_http_service_request_duration_seconds",
            "Request duration by model",
        )
        self.ttft = self.registry.histogram(
            f"{prefix}_http_service_time_to_first_token_seconds",
            "Time to first streamed token by model",
        )

    def register(self, metric) -> None:
        self.registry.register(metric)

    def register_callback_gauges(self, prefix: str, fn) -> None:
        """Expose a dict-returning callback (e.g. a BYO engine's
        ForwardPassMetrics analog — slot/KV occupancy, prefix hit rate,
        speculation acceptance) as Prometheus gauges, pulled fresh at
        every /metrics render."""
        self.registry.register_callback_gauges(prefix, fn)

    def attach_registry(self, registry: MetricsRegistry) -> None:
        """Merge another component's registry into this exposition
        (the in-process engine's scheduler/KV/disagg instruments)."""
        self.registry.attach(registry)

    def inflight_total(self) -> float:
        """Sum of in-flight requests across models (graceful-drain gate)."""
        return sum(self.inflight.values.values())

    def render(self) -> str:
        return self.registry.render()

    class _Timer:
        def __init__(self, metrics: "ServiceMetrics", model: str):
            self.metrics = metrics
            self.model = model
            self.start = time.monotonic()
            self.status = "success"
            self.first_token_seen = False
            # edge-side SLO accounting: TTFT, worst inter-token gap,
            # and token count for the request's attainment verdict
            self.ttft_s: Optional[float] = None
            self.itl_max_s: Optional[float] = None
            self.tokens = 0
            self._last_token_t: Optional[float] = None

        def first_token(self) -> None:
            if not self.first_token_seen:
                self.first_token_seen = True
                self.ttft_s = time.monotonic() - self.start
                self.metrics.ttft.observe(self.ttft_s, model=self.model)

        def token(self, n: int = 1) -> None:
            """One payload chunk left the edge: TTFT on the first, the
            inter-token gap on every subsequent one. ``n`` is the
            chunk's token count when the payload carries one (token-
            level EngineOutput shapes); OpenAI chat/completions chunks
            are one token per chunk on every current engine path."""
            self.first_token()
            now = time.monotonic()
            if self._last_token_t is not None:
                gap = now - self._last_token_t
                if self.itl_max_s is None or gap > self.itl_max_s:
                    self.itl_max_s = gap
            self._last_token_t = now
            self.tokens += max(1, n)

        def finish(self, status: str = "success") -> None:
            self.metrics.inflight.dec(model=self.model)
            self.metrics.requests_total.inc(model=self.model, status=status)
            self.metrics.duration.observe(time.monotonic() - self.start, model=self.model)
            if (self.metrics.slo is not None and status == "success"
                    and self.first_token_seen):
                # only completed streams get a verdict: an error or
                # disconnect is not an SLO miss, it is its own failure
                self.metrics.slo.observe(
                    self.ttft_s, self.itl_max_s, self.tokens
                )

    def track(self, model: str) -> "ServiceMetrics._Timer":
        self.inflight.inc(model=model)
        return self._Timer(self, model)
