"""Token sequences and content-addressed KV blocks.

Every KV-cache block in the framework is identified by two hashes:

- ``block_hash``: a salted xxh64 over the block's token ids. Identical token
  contents produce identical block hashes regardless of position.
- ``sequence_hash``: a chained hash ``H(parent_sequence_hash, block_hash)``
  that identifies the block *in context* — i.e. the whole prefix ending at
  this block. Two requests share a KV prefix iff their sequence hashes match.

This mirrors the semantics of the reference implementation's token-hash crate
(reference: lib/tokens/src/lib.rs:16-120 and lib/llm/src/tokens.rs:21-417 —
salted BlockHash, parent-chained SequenceHash), re-designed as a single Python
module (the reference kept two divergent copies). The radix-tree KV indexer
(dynamo_tpu/kv_router/indexer.py) and the block manager key off
``sequence_hash``.

Hash function: XXH64 (not the reference's xxh3), because the framework keeps
two interoperable implementations — this pure-Python path and the native C++
hot path in dynamo_tpu/native — and XXH64 is simple enough to guarantee
bit-exact parity between them (asserted in tests/test_native.py). The salted
seed scheme is the reference's (indexer.rs:64, seed 1337). The batched
``compute_block_hashes`` dispatches to the C++ implementation when built.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np
import xxhash

# Seed matching the reference's router-side block hasher
# (reference: lib/llm/src/kv_router/indexer.rs:64 — seed 1337).
DEFAULT_SALT = b"dynamo-tpu"
ROUTER_SEED = 1337


def salt_hash(salt: bytes = DEFAULT_SALT) -> int:
    """Hash a salt into a 64-bit seed for block hashing.

    Deployments that must not share hash namespaces pass
    ``TokenSequence(..., salt=...)`` (or ``seed=salt_hash(salt)`` to the
    free functions) so identical token content hashes differently per salt.
    """
    return xxhash.xxh64_intdigest(salt)


def _tokens_to_bytes(token_ids: Sequence[int]) -> bytes:
    return np.asarray(token_ids, dtype=np.uint32).tobytes()


def compute_block_hash(token_ids: Sequence[int], seed: int = ROUTER_SEED) -> int:
    """Salted content hash of one block's token ids (position-independent)."""
    return xxhash.xxh64_intdigest(_tokens_to_bytes(token_ids), seed=seed)


def chain_hash(parent_sequence_hash: Optional[int], block_hash: int) -> int:
    """Chained prefix hash: identifies the whole sequence ending at this block."""
    if parent_sequence_hash is None:
        return block_hash
    buf = np.asarray([parent_sequence_hash, block_hash], dtype=np.uint64).tobytes()
    return xxhash.xxh64_intdigest(buf)


def compute_block_hashes(
    token_ids: Sequence[int], block_size: int, seed: int = ROUTER_SEED
) -> List[int]:
    """Sequence hashes for each *complete* block of ``token_ids``.

    This is the hot path used by the KV router on every scheduling decision
    (reference: lib/llm/src/kv_router/indexer.rs:123 compute_block_hash_for_seq):
    only full blocks are hashed; the ragged tail is ignored. Dispatches to the
    native C++ implementation (dynamo_tpu/native) when built; set
    ``DYNAMO_TPU_NATIVE=0`` to force pure Python.
    """
    fn = _get_native()
    if fn is not None:
        return fn(token_ids, block_size, seed)
    n_full = len(token_ids) // block_size
    out: List[int] = []
    parent: Optional[int] = None
    arr = np.asarray(token_ids[: n_full * block_size], dtype=np.uint32)
    for i in range(n_full):
        bh = xxhash.xxh64_intdigest(
            arr[i * block_size : (i + 1) * block_size].tobytes(), seed=seed
        )
        parent = chain_hash(parent, bh)
        out.append(parent)
    return out


# native dispatch is lazy: the first hashing call (not package import) pays
# the one-time C++ build check, and DYNAMO_TPU_NATIVE=0 opts out entirely
_native_hashes = None
_native_checked = False


def _get_native():
    global _native_hashes, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from . import native

            if not native.disabled_by_env() and native.available():
                _native_hashes = native.compute_block_hashes
        # dynlint: allow(silent-except) - optional-native probe; pure-Python fallback is the contract
        except Exception:  # pragma: no cover - broken toolchain
            pass
    return _native_hashes


@dataclasses.dataclass(frozen=True)
class TokenBlock:
    """An immutable, completely-filled block of tokens.

    ``sequence_hash`` = chain(parent_sequence_hash, block_hash) uniquely names
    the prefix [0, position*block_size + len(tokens)) of the owning sequence.
    """

    tokens: Tuple[int, ...]
    block_hash: int
    sequence_hash: int
    parent_sequence_hash: Optional[int]
    position: int  # block index within the sequence

    @property
    def block_size(self) -> int:
        return len(self.tokens)


class PartialTokenBlock:
    """Mutable tail block of a growing sequence; freezes into a TokenBlock."""

    def __init__(
        self,
        block_size: int,
        position: int,
        parent_sequence_hash: Optional[int],
        seed: int,
    ):
        self.block_size = block_size
        self.position = position
        self.parent_sequence_hash = parent_sequence_hash
        self.seed = seed
        self.tokens: List[int] = []

    def push(self, token_id: int) -> Optional[TokenBlock]:
        """Append one token. Returns the frozen block when it fills up."""
        self.tokens.append(int(token_id))
        if len(self.tokens) == self.block_size:
            return self.freeze()
        return None

    def freeze(self) -> TokenBlock:
        bh = compute_block_hash(self.tokens, self.seed)
        sh = chain_hash(self.parent_sequence_hash, bh)
        return TokenBlock(
            tokens=tuple(self.tokens),
            block_hash=bh,
            sequence_hash=sh,
            parent_sequence_hash=self.parent_sequence_hash,
            position=self.position,
        )

    def __len__(self) -> int:
        return len(self.tokens)


class TokenSequence:
    """A token sequence chunked into hash-chained blocks.

    Used by the engine's block allocator to track which KV blocks are
    complete (shareable / publishable as KV events) vs. the in-flight tail.
    """

    def __init__(
        self,
        token_ids: Iterable[int] = (),
        block_size: int = 16,
        seed: int = ROUTER_SEED,
        salt: Optional[bytes] = None,
    ):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self.seed = salt_hash(salt) if salt is not None else seed
        self.blocks: List[TokenBlock] = []
        self._tail = PartialTokenBlock(block_size, 0, None, self.seed)
        self.extend(token_ids)

    def extend(self, token_ids: Iterable[int]) -> List[TokenBlock]:
        """Append tokens; returns any blocks completed by this extension."""
        completed: List[TokenBlock] = []
        for t in token_ids:
            blk = self.push(t)
            if blk is not None:
                completed.append(blk)
        return completed

    def push(self, token_id: int) -> Optional[TokenBlock]:
        blk = self._tail.push(token_id)
        if blk is not None:
            self.blocks.append(blk)
            self._tail = PartialTokenBlock(
                self.block_size, blk.position + 1, blk.sequence_hash, self.seed
            )
        return blk

    @property
    def tail(self) -> PartialTokenBlock:
        return self._tail

    @property
    def token_ids(self) -> List[int]:
        out: List[int] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self._tail.tokens)
        return out

    def sequence_hashes(self) -> List[int]:
        return [b.sequence_hash for b in self.blocks]

    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self._tail)
