"""Content-addressed cold KV tier: disk spill that survives process death.

The third tier of the KV hierarchy (HBM → host RAM → here). Blocks are
keyed by their *chained sequence hash* (tokens.py) and stored one file
per block, so identical token prefixes written by ANY worker are
rehydratable by any other worker sharing the directory — including a
freshly respawned one after a recovery drain, whose HBM and host tiers
start empty. This is the reference's object-store KV tier (PAPER.md §1
layer 3 multi-tier block manager) grounded in a filesystem: a shared
mount or a FUSE'd object store both work, because every read is
checksum-verified and every write is atomic (tmp + rename).

File layout (``<dir>/<sequence_hash:016x>.kvb``)::

    [4-byte header len][msgpack header][k raw bytes][v raw bytes]

The header carries the sequence hash again (a renamed/misplaced file
must not serve under the wrong prefix), the array shape/dtype, and an
xxh64 checksum over the payload. A failed verification — wrong magic,
hash mismatch, short payload, checksum mismatch — is a MISS, never an
install: the corrupt file is quarantined (deleted) and counted.

Threading discipline: ``offer`` (the host-tier eviction hook) schedules
the file write on the event loop's executor and HOLDS the future (spill
I/O must never ride the loop — dynlint async-blocking / task-leak pins
this module); ``get``/``put``/``refresh`` are sync and belong on an
executor thread — the fabric's pull task is the only production caller.
``has``/``match_extension`` consult only the in-memory index (no disk
touch) so the scheduler's sync planning path stays cheap; the index can
be stale against other writers of a shared directory, which is safe
because the read path re-verifies and treats absence as a miss.
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import threading
from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import msgpack
import numpy as np
import xxhash

logger = logging.getLogger(__name__)

_MAGIC = "dynkv1"
_SUFFIX = ".kvb"
_MAX_HEADER = 1 << 20


def _fname(sequence_hash: int) -> str:
    return f"{sequence_hash & (2**64 - 1):016x}{_SUFFIX}"


def _checksum(k_raw: bytes, v_raw: bytes) -> int:
    h = xxhash.xxh64()
    h.update(k_raw)
    h.update(v_raw)
    return h.intdigest()


class KvColdTier:
    """Disk store of KV blocks keyed by sequence hash.

    ``capacity_blocks`` bounds the number of resident block files this
    process enforces, least-recently-accessed first (in-memory order;
    refresh() seeds it from mtimes, which get() also touches so other
    workers sharing the directory see accesses too).
    ``on_stored``/``on_removed`` (optional) mirror
    the allocator's KV event hooks so the router can learn cold-tier
    ownership (discounted scoring, kv_router/scheduler.py).
    """

    def __init__(
        self,
        directory: str,
        capacity_blocks: int,
        registry=None,
        on_stored=None,   # (hashes: List[int], parent: Optional[int]) -> None
        on_removed=None,  # (hashes: List[int]) -> None
    ):
        self.dir = directory
        self.capacity_blocks = capacity_blocks
        self.on_stored = on_stored or (lambda hashes, parent: None)
        self.on_removed = on_removed or (lambda hashes: None)
        os.makedirs(self.dir, exist_ok=True)
        # in-memory view of the directory: hash → payload bytes, in
        # access (LRU) order — capacity eviction pops the front without
        # re-statting the directory. Kept by this process's puts/
        # refreshes; the disk is the truth and the read path re-verifies.
        self._index: "OrderedDict[int, int]" = OrderedDict()
        # resident payload bytes, kept as a plain int beside the index:
        # the metrics gauge reads it from the loop while executor-side
        # put/refresh mutate the dict — summing the dict's values
        # mid-insert could raise, an int read can't
        self._bytes = 0
        # serializes executor-side mutation (put/get/refresh each run on
        # whatever executor thread their future landed on — a host-tier
        # drain schedules many offers at once): without it, concurrent
        # puts race the bytes read-modify-write and double-run capacity
        # enforcement. Loop-side reads (has/match_extension/_bytes) stay
        # lock-free — single-op dict/int reads are GIL-atomic.
        self._mutate = threading.Lock()
        # the serving event loop, captured at construction / the first
        # loop-side call (offer): the ownership hooks (on_stored/
        # on_removed → KV event publisher) are loop-bound, but put/get/
        # refresh run on executor threads — _emit marshals hook calls
        # back onto the loop
        try:
            self._loop: Optional[asyncio.AbstractEventLoop] = (
                asyncio.get_running_loop()
            )
        except RuntimeError:
            self._loop = None
        # spill writes in flight (offer); held so close() can drain them
        # and a failed write is logged instead of vanishing
        self._writes: set = set()
        if registry is None:
            from ..telemetry.registry import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        self._hits = registry.counter(
            "dynamo_kv_fabric_cold_tier_hits_total",
            "Cold-tier block reads that verified and rehydrated",
        )
        self._misses = registry.counter(
            "dynamo_kv_fabric_cold_tier_misses_total",
            "Cold-tier block reads that failed, labelled reason="
            "absent|corrupt (corrupt files are quarantined, never "
            "installed)",
        )
        self._evictions = registry.counter(
            "dynamo_kv_fabric_cold_tier_evictions_total",
            "Cold-tier block files evicted by the capacity bound "
            "(oldest access first)",
        )
        registry.callback_gauge(
            "dynamo_kv_fabric_cold_tier_bytes",
            "Payload bytes resident in this process's cold-tier index",
            # dynrace: domain(executor)
            lambda: float(self._bytes),
        )

    # ---------- sync index surface (scheduler planning path) ----------

    def __len__(self) -> int:
        return len(self._index)

    def has(self, sequence_hash: int) -> bool:
        return sequence_hash in self._index

    def match_extension(self, hashes: Sequence[int], start: int) -> List[int]:
        """Longest index-resident run of ``hashes`` starting at ``start``
        (same contract as KvHostTier.match_extension)."""
        out: List[int] = []
        for h in hashes[start:]:
            if h not in self._index:
                break
            out.append(h)
        return out

    # ---------- executor-side I/O ----------

    def refresh(self) -> int:
        """Rescan the directory into the index (sync; executor-bound).

        The respawn-warm path: a fresh worker opening a populated shared
        directory learns every resident prefix here — and ADVERTISES the
        delta through the ownership hooks, so routers and peer fabrics
        score the rehydratable inventory (without this, a respawned
        worker's cold tier is invisible to the cluster). Returns the
        number of indexed blocks."""
        found = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            logger.exception("cold tier dir unreadable: %s", self.dir)
            names = []
        for name in names:
            if not name.endswith(_SUFFIX):
                continue
            try:
                h = int(name[: -len(_SUFFIX)], 16)
                path = os.path.join(self.dir, name)
                found.append((os.path.getmtime(path), h,
                              os.path.getsize(path)))
            except (ValueError, OSError):
                continue  # foreign file; the read path would reject it too
        found.sort()  # oldest-access first = front of the LRU order
        index = OrderedDict((h, size) for _m, h, size in found)
        with self._mutate:
            prev = set(self._index)
            # keep entries this process wrote while the scan ran (a
            # put() landing between listdir and here must not be
            # dropped-and-retracted); entries whose files truly
            # vanished self-correct on read (FileNotFoundError → miss
            # + removal event)
            for h, size in self._index.items():
                if h not in index:
                    index[h] = size
            self._index = index
            self._bytes = sum(index.values())
        added = [int(h) for h in index if h not in prev]
        if added:
            self._emit(self.on_stored, added, None)
        return len(index)

    def put(self, sequence_hash: int, k: np.ndarray, v: np.ndarray,
            parent_hash: Optional[int] = None) -> None:
        """Write one block atomically (sync; executor-bound)."""
        k = np.ascontiguousarray(k)
        v = np.ascontiguousarray(v)
        k_raw, v_raw = k.tobytes(), v.tobytes()
        header = msgpack.packb({
            "magic": _MAGIC,
            "sequence_hash": int(sequence_hash),
            "parent_hash": None if parent_hash is None else int(parent_hash),
            "shape": list(k.shape),
            "dtype": k.dtype.name,
            "k_bytes": len(k_raw),
            "v_bytes": len(v_raw),
            "checksum": _checksum(k_raw, v_raw),
        }, use_bin_type=True)
        path = os.path.join(self.dir, _fname(sequence_hash))
        # file I/O OUTSIDE the lock (a spill write on a shared mount can
        # take tens of ms — the rehydrate path's LRU touch must not
        # queue behind it); the tmp name is thread-unique because
        # concurrent executor threads may spill concurrently. A same-
        # hash race is benign: content addressing makes both payloads
        # identical, and the accounting below is serialized.
        tmp = f"{path}.tmp-{os.getpid()}-{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(struct.pack(">I", len(header)))
            f.write(header)
            f.write(k_raw)
            f.write(v_raw)
        os.replace(tmp, path)  # atomic: readers see whole files or none
        with self._mutate:
            size = len(k_raw) + len(v_raw)
            self._bytes += size - (self._index.get(sequence_hash) or 0)
            self._index[sequence_hash] = size
            self._index.move_to_end(sequence_hash)  # newest = LRU back
            self._emit(self.on_stored, [int(sequence_hash)], parent_hash)
            self._enforce_capacity()

    def get(self, sequence_hash: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Read + verify one block (sync; executor-bound).

        Any verification failure is a miss: corrupt/truncated files are
        quarantined (deleted) and counted, NEVER installed."""
        path = os.path.join(self.dir, _fname(sequence_hash))
        try:
            with open(path, "rb") as f:
                raw_len = f.read(4)
                if len(raw_len) < 4:
                    raise ValueError("truncated header length")
                (hlen,) = struct.unpack(">I", raw_len)
                if hlen > _MAX_HEADER:
                    raise ValueError(f"header too large: {hlen}")
                header = msgpack.unpackb(f.read(hlen), raw=False)
                if (header.get("magic") != _MAGIC
                        or int(header.get("sequence_hash", -1))
                        != int(sequence_hash)):
                    raise ValueError("magic/hash mismatch")
                k_raw = f.read(header["k_bytes"])
                v_raw = f.read(header["v_bytes"])
                if (len(k_raw) != header["k_bytes"]
                        or len(v_raw) != header["v_bytes"]):
                    raise ValueError("truncated payload")
                if _checksum(k_raw, v_raw) != header["checksum"]:
                    raise ValueError("checksum mismatch")
                from ..transfer.framing import np_dtype

                shape = tuple(header["shape"])
                dtype = np_dtype(header["dtype"])
                k = np.frombuffer(k_raw, dtype=dtype).reshape(shape)
                v = np.frombuffer(v_raw, dtype=dtype).reshape(shape)
        except FileNotFoundError:
            # another worker sharing the directory evicted it: retract
            # the ownership advertisement too, or routers keep discount-
            # routing toward a hit that always misses
            with self._mutate:
                self._forget(sequence_hash)
            self._emit(self.on_removed, [int(sequence_hash)])
            self._misses.inc(reason="absent")
            return None
        except (ValueError, KeyError, TypeError, OSError,
                msgpack.exceptions.UnpackException) as e:
            logger.warning(
                "cold tier: quarantining corrupt block %s: %s",
                _fname(sequence_hash), e,
            )
            with self._mutate:
                self._drop(sequence_hash)
            self._misses.inc(reason="corrupt")
            return None
        with self._mutate:
            if sequence_hash in self._index:
                self._index.move_to_end(sequence_hash)  # LRU touch
        try:
            # mtime touch too: other workers sharing the directory (and
            # this process's next refresh) see the access order
            os.utime(path)
        except OSError:
            pass  # dynlint: allow(silent-except) - best-effort LRU stamp; eviction order degrades gracefully
        self._hits.inc()
        return k, v

    # ---------- host-tier eviction hook (loop-side) ----------

    def offer(self, sequence_hash: int, k: np.ndarray, v: np.ndarray,
              parent_hash: Optional[int] = None) -> None:
        """Spill one host-tier-evicted block.

        Called from the host tier's drain() on the event loop: the write
        rides the executor and the future is held (logged on failure,
        drained by close()). Without a running loop (sync unit tests,
        offline tools) the write happens inline."""
        if sequence_hash in self._index:
            return
        k = np.ascontiguousarray(k)
        v = np.ascontiguousarray(v)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self.put(sequence_hash, k, v, parent_hash)
            return
        self._loop = loop
        fut = loop.run_in_executor(
            None, self.put, sequence_hash, k, v, parent_hash
        )
        self._writes.add(fut)

        def _done(f) -> None:
            self._writes.discard(f)
            if not f.cancelled() and f.exception() is not None:
                logger.warning("cold tier spill failed: %s", f.exception())

        fut.add_done_callback(_done)

    async def close(self) -> None:
        """Drain in-flight spill writes."""
        writes = list(self._writes)
        if writes:
            await asyncio.gather(*writes, return_exceptions=True)

    # ---------- internals ----------

    def _emit(self, fn, *args) -> None:
        """Run an ownership hook (on_stored/on_removed) on the serving
        loop. put/get/_drop execute on executor threads, but the hooks
        feed loop-bound machinery (the KV event publisher's queue);
        loop-side and loopless (sync tests, offline tools) callers
        invoke directly."""
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            loop = self._loop
            if loop is not None and loop.is_running():
                loop.call_soon_threadsafe(fn, *args)
                return
        fn(*args)

    def _forget(self, sequence_hash: int) -> None:
        # caller holds _mutate
        size = self._index.pop(sequence_hash, None)
        if size:
            self._bytes -= size

    def _drop(self, sequence_hash: int) -> None:
        # caller holds _mutate (threading.Lock is not reentrant)
        self._forget(sequence_hash)
        try:
            os.unlink(os.path.join(self.dir, _fname(sequence_hash)))
        except OSError:
            pass  # dynlint: allow(silent-except) - another worker may have evicted the same file first
        self._emit(self.on_removed, [int(sequence_hash)])

    def _enforce_capacity(self) -> None:
        # caller holds _mutate. O(evicted), not O(capacity): the index
        # keeps access order in memory, so the victim is the front —
        # no per-put directory rescan (each stat can be a network round
        # trip on the shared/object-store mounts this tier targets)
        while len(self._index) > self.capacity_blocks:
            self._drop(next(iter(self._index)))
            self._evictions.inc()

    def metrics(self) -> dict:
        return {
            "cold_kv_blocks": len(self._index),
            "cold_kv_capacity": self.capacity_blocks,
            "cold_kv_bytes": self._bytes,
        }
