"""Host-memory KV tier: offload evicted HBM blocks, restore on prefix hit.

The TPU analog of the reference's multi-tier KV block manager (reference:
lib/llm/src/kv/storage.rs StorageType::{Device,Pinned,System} slabs,
lib/llm/src/kv/reuse.rs priority reuse/eviction, and the CUDA
scatter/gather copy kernel lib/llm/src/kernels/block_copy.cu) — the
subsystem behind the reference's "+40% TTFT from KV offload to system
memory" headline (docs/architecture.md:91). Here the device↔host movement
is the runner's jitted XLA gather/scatter over the paged cache plus
asynchronous D2H staging.

A block is offloaded *at HBM eviction time*: when the allocator pops a
reusable block to hand its slot to new data, the block's KV is still
intact, so it is read out to host RAM first, keyed by its chained sequence
hash. On a later prompt whose prefix extends past the HBM-cached blocks,
host-resident blocks are restored into freshly allocated slots instead of
being recomputed — turning a prefill recompute into one H2D copy.

Offload is staged, not synchronous (the analog of the reference's
``CopyStream::trigger_layer`` overlap, lib/llm/src/kv/layer.rs:100-1140):
``offload_batch`` only *dispatches* the device gather — legal because the
single device stream executes it before any later write to those slots —
and starts the D2H copy (``copy_to_host_async``); the decode loop never
blocks on device→host materialization. ``drain()`` (called by the
scheduler after the next step is already dispatched, and forced by
``restore``/allocator ``fence()``) turns finished copies into numpy and
makes them evictable/capacity-accounted. Staged blocks are matchable the
whole time — a hit between dispatch and drain is not lost.
"""

from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class KvHostTier:
    """Store of KV blocks in host RAM, keyed by sequence hash, with
    asynchronous device→host staging."""

    def __init__(
        self,
        gather_fn: Callable[[Sequence[int]], Tuple[np.ndarray, np.ndarray]],
        scatter_fn: Callable[[Sequence[int], np.ndarray, np.ndarray], None],
        capacity_blocks: int,
        on_evict: Optional[Callable[[int, np.ndarray, np.ndarray], None]] = None,
    ):
        self.gather_fn = gather_fn
        self.scatter_fn = scatter_fn
        self.capacity_blocks = capacity_blocks
        # capacity-eviction hook (the cold tier's spill entry,
        # kv/cold_tier.py KvColdTier.offer): called with
        # (sequence_hash, k, v) at the moment an entry leaves host RAM —
        # the last chance to keep the prefix rehydratable anywhere
        self.on_evict = on_evict
        # sequence_hash → (k [L,1,bs,KVH,D], v) host arrays; LRU order
        self.store: "OrderedDict[int, Tuple[np.ndarray, np.ndarray]]" = OrderedDict()
        # dispatched-but-unmaterialized gathers: (hashes, k_arr, v_arr)
        # where the arrays may be device-resident with a D2H in flight
        self._staged: List[Tuple[List[int], object, object]] = []
        self._staged_hashes: set = set()
        # telemetry
        self.offloaded_total = 0
        self.restored_total = 0
        self.evicted_total = 0

    def __len__(self) -> int:
        return len(self.store) + len(self._staged_hashes)

    def has(self, sequence_hash: int) -> bool:
        return sequence_hash in self.store or sequence_hash in self._staged_hashes

    def offload(self, sequence_hash: int, block_id: int) -> None:
        """Read one HBM block out to host before its slot is reused."""
        self.offload_batch([(sequence_hash, block_id)])

    def offload_batch(self, pairs: Sequence[Tuple[int, int]]) -> None:
        """Offload many evicted blocks with ONE bucketed device gather.

        Only dispatches: the gather is enqueued on the device stream (so
        it reads the slots before any later overwrite) and the D2H copy
        is started; materialization happens in ``drain``. Callers
        evicting several blocks in a burst (a long prompt's allocation)
        batch here so the device round-trip is paid once, not per block.
        """
        fresh = []
        for h, bid in pairs:
            if h in self.store:
                self.store.move_to_end(h)
            elif h not in self._staged_hashes:
                fresh.append((h, bid))
        if not fresh:
            return
        k, v = self.gather_fn([bid for _h, bid in fresh])
        for arr in (k, v):
            start = getattr(arr, "copy_to_host_async", None)
            if start is not None:
                start()
        hashes = [h for h, _bid in fresh]
        self._staged.append((hashes, k, v))
        self._staged_hashes.update(hashes)
        self.offloaded_total += len(fresh)

    def drain(self) -> None:
        """Materialize all staged offloads into the host store (blocks
        only on still-running D2H copies) and enforce capacity."""
        if not self._staged:
            return
        staged, self._staged = self._staged, []
        self._staged_hashes.clear()
        for hashes, k, v in staged:
            k = np.asarray(k)
            v = np.asarray(v)
            for i, h in enumerate(hashes):
                if h in self.store:
                    self.store.move_to_end(h)
                    continue
                # copy: a slice view would pin the whole (bucket-padded)
                # gather buffer, breaking the capacity_blocks accounting
                self.store[h] = (
                    np.ascontiguousarray(k[:, i : i + 1]),
                    np.ascontiguousarray(v[:, i : i + 1]),
                )
        while len(self.store) > self.capacity_blocks:
            h, (ek, ev) = self.store.popitem(last=False)
            self.evicted_total += 1
            if self.on_evict is not None:
                # spill to the cold tier BEFORE the arrays go away —
                # the hook is loop-safe (the cold tier's write rides
                # the executor; these host arrays are immutable)
                self.on_evict(h, ek, ev)

    def restore(self, hashes: Sequence[int], block_ids: Sequence[int]) -> None:
        """Write host-resident blocks back into freshly allocated HBM slots."""
        assert len(hashes) == len(block_ids)
        if not hashes:
            return
        if any(h in self._staged_hashes for h in hashes):
            self.drain()
        ks, vs = zip(*(self.store[h] for h in hashes))
        k = np.concatenate(ks, axis=1)
        v = np.concatenate(vs, axis=1)
        self.scatter_fn(list(block_ids), k, v)
        for h in hashes:
            self.store.move_to_end(h)
        self.restored_total += len(hashes)

    def match_extension(self, hashes: Sequence[int], start: int) -> List[int]:
        """Longest host-resident (stored or staged) run of ``hashes``
        starting at index ``start``."""
        out: List[int] = []
        for h in hashes[start:]:
            if not self.has(h):
                break
            out.append(h)
        return out

    def metrics(self) -> dict:
        return {
            "host_kv_blocks": len(self),
            "host_kv_staged": len(self._staged_hashes),
            "host_kv_capacity": self.capacity_blocks,
            "host_kv_offloaded_total": self.offloaded_total,
            "host_kv_restored_total": self.restored_total,
            "host_kv_evicted_total": self.evicted_total,
        }
