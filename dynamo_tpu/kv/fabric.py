"""Cluster KV fabric: cross-worker prefix pull + cold-tier rehydration.

Per-process prefix caching becomes a datacenter-wide cache: when the
ownership view says another worker already computed a longer prefix of
this prompt than any local tier holds, the scheduler PULLS those
committed KV blocks over the transfer plane (a read-only cousin of the
migration plane's reserve→install) instead of recomputing them; when
the cold tier (kv/cold_tier.py) holds the extension, the pull reads
checksummed spill files instead of the wire. Either way the un-matched
tail still prefills locally, and any failure — peer dead, timeout,
checksum miss, chaos injection — falls back to local recompute with a
byte-identical stream (the fallback never registered anything, so the
allocator state is exactly the no-fabric state).

Components:

- ``KvFabric`` — one per engine. Owns the *ownership view* (a
  ``KvIndexer`` fed with other workers' KV events — the same event
  stream the KV router indexes), the peer descriptor map, the cold
  tier, and the pull client/server halves.
- The serve half plugs into ``KvTransferServer(pull_source=...)``
  (disagg/transfer.py): a peer's ``pull`` frame resolves the longest
  locally-resident run of the requested hash chain (HBM blocks pinned
  for the duration, host-tier entries read from RAM) and streams it
  back chunk-by-chunk — gathers dispatch on the loop (they must
  serialize with the engine's own step programs), host syncs and byte
  packing ride the executor, mirroring the streamed-prefill discipline.
- The pull half (``KvFabric.pull``) scatters arriving frames into
  blocks the scheduler reserved, overlapping the device copy of frame
  i with the network read of frame i+1.

Fault sites: ``transfer_conn_drop`` (the serving side dies mid-stream)
and ``prefix_pull_stall`` (the pulling side stalls until the
scheduler's deadline cancels it) — both must end in the byte-identical
local fallback with zero leaked blocks (tests/test_kv_fabric.py).
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..telemetry.flight import flight_recorder
from ..transfer.framing import pack_frame, read_header
from ..transfer.ici import IciBackend
from ..transfer.plane import TransferMetrics, negotiate_backend, record_open
from ..transfer.tcp import TcpBackend
from ..utils import faults

logger = logging.getLogger(__name__)

# blocks per pull frame: bounds both sides' host buffers the same way
# the streamed-prefill and migration planes bound theirs
PULL_CHUNK_BLOCKS = 16


def fabric_key(namespace: str, component: str, engine_id: str) -> str:
    """Discovery-plane key a worker's pull server registers under
    (lease-scoped, like the KV transfer and migration descriptors)."""
    return f"{namespace}/components/{component}/kv_fabric/{engine_id}"


@dataclass
class PullPlan:
    """One planned prefix pull: the hash run to fetch and its source."""

    source: str                      # "peer" | "cold"
    hashes: List[int]                # sequence hashes, a run of the chain
    start_block: int                 # chain index of hashes[0]
    worker_id: Optional[str] = None  # peer pulls: the owning worker
    host: Optional[str] = None
    port: Optional[int] = None
    # payload path negotiated against the peer's discovery descriptor
    # at plan time (transfer/plane.py negotiate_backend); tcp is the
    # cross-pod/DCN fallback every pair supports
    backend: str = "tcp"

    @property
    def blocks(self) -> int:
        return len(self.hashes)


@dataclass
class _GrantEntry:
    sequence_hash: int
    kind: str                        # "hbm" | "host"
    block_id: Optional[int] = None   # hbm
    arrays: Optional[Tuple[np.ndarray, np.ndarray]] = None  # host


class PullGrant:
    """Server-side lease over the blocks one pull serves.

    HBM blocks are pinned at resolution (the allocator will neither
    evict nor reuse them mid-gather); ``release`` unpins — it MUST run
    exactly once, connection death included (the transfer server's
    ``finally`` owns that).
    """

    def __init__(self, fabric: "KvFabric", entries: List[_GrantEntry]):
        self._fabric = fabric
        self.entries = entries
        self._released = False

    @property
    def hashes(self) -> List[int]:
        return [e.sequence_hash for e in self.entries]

    def __len__(self) -> int:
        return len(self.entries)

    async def gather_frame(self, lo: int, hi: int):
        """Materialize entries [lo, hi) as one wire frame:
        ``(k_bytes, v_bytes, shape, dtype_name)`` over [L, n, bs, KVH, D].

        The device gather dispatches on the loop (it must serialize with
        the engine's own step dispatches over the shared cache buffers);
        the host sync, segment assembly, and byte packing ride the
        executor — the streamed-prefill pump's discipline.
        """
        chunk = self.entries[lo:hi]
        hbm_ids = [e.block_id for e in chunk if e.kind == "hbm"]
        runner = self._fabric.runner
        k_dev = v_dev = None
        if hbm_ids:
            k_dev, v_dev = runner.gather_blocks_device(hbm_ids)

        def _assemble():
            hbm_k = hbm_v = None
            if hbm_ids:
                hbm_k, hbm_v = runner.blocks_to_host(k_dev, v_dev)
            ks, vs, j = [], [], 0
            for e in chunk:
                if e.kind == "hbm":
                    ks.append(hbm_k[:, j:j + 1])
                    vs.append(hbm_v[:, j:j + 1])
                    j += 1
                else:
                    ks.append(e.arrays[0])
                    vs.append(e.arrays[1])
            k = np.ascontiguousarray(np.concatenate(ks, axis=1))
            v = np.ascontiguousarray(np.concatenate(vs, axis=1))
            return k.tobytes(), v.tobytes(), list(k.shape), k.dtype.name

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, _assemble)

    async def gather_frame_device(self, lo: int, hi: int):
        """Materialize entries [lo, hi) as DEVICE arrays for an ici
        chunk: the payload enters the collective straight from HBM, the
        host never sees block bytes. All-HBM runs (the common case for
        hot prefixes) are a single jitted gather; mixed runs device_put
        each host-tier entry off-loop and concatenate on device — still
        never a whole-frame host buffer."""
        chunk = self.entries[lo:hi]
        runner = self._fabric.runner
        hbm_ids = [e.block_id for e in chunk if e.kind == "hbm"]
        if len(hbm_ids) == len(chunk):
            return runner.gather_blocks_device(hbm_ids)
        k_dev = v_dev = None
        if hbm_ids:
            k_dev, v_dev = runner.gather_blocks_device(hbm_ids)

        def _stage():
            import jax

            return {
                i: (jax.device_put(e.arrays[0]), jax.device_put(e.arrays[1]))
                for i, e in enumerate(chunk) if e.kind == "host"
            }

        loop = asyncio.get_running_loop()
        staged = await loop.run_in_executor(None, _stage)
        import jax.numpy as jnp

        ks, vs, j = [], [], 0
        for i, e in enumerate(chunk):
            if e.kind == "hbm":
                ks.append(k_dev[:, j:j + 1])
                vs.append(v_dev[:, j:j + 1])
                j += 1
            else:
                ks.append(staged[i][0])
                vs.append(staged[i][1])
        # dispatch-only device concat (the loop never blocks on it)
        return jnp.concatenate(ks, axis=1), jnp.concatenate(vs, axis=1)

    def release(self) -> None:
        if self._released:
            return
        self._released = True
        hbm = [e.block_id for e in self.entries if e.kind == "hbm"]
        if hbm:
            self._fabric.allocator.unpin_blocks(hbm)


class KvFabric:
    """One per engine: ownership view + cold tier + pull client/server."""

    def __init__(
        self,
        runner,
        allocator,
        engine_id: str,
        block_size: int = 16,
        cold=None,                   # Optional[KvColdTier]
        peers: Optional[Callable[[], Dict[str, dict]]] = None,
        peer_pull: bool = True,
        min_pull_blocks: int = 1,
        pull_timeout_s: float = 30.0,
        chunk_blocks: int = PULL_CHUNK_BLOCKS,
        registry=None,
        flight=None,
        ici=None,                    # local collective plane (both halves)
    ):
        from ..kv_router.indexer import KvIndexer

        self.runner = runner
        self.allocator = allocator
        self.engine_id = engine_id
        self.block_size = block_size
        self.cold = cold
        # worker_id → {"host", "port"} descriptors of peer pull servers
        self.peers = peers or (lambda: {})
        # the cross-worker half is opt-in (--prefix-pull): a cold-tier-
        # only configuration plans cold rehydrates but never reaches
        # over the network (and the CLI wiring starts no pull server)
        self.peer_pull = peer_pull
        self.min_pull_blocks = max(1, min_pull_blocks)
        self.pull_timeout_s = pull_timeout_s
        self.chunk_blocks = max(1, chunk_blocks)
        self.flight = flight if flight is not None else flight_recorder()
        # the ownership view: remote workers' KV events, same stream the
        # router indexes (events for THIS engine are skipped — local
        # tiers already answer faster than any pull)
        self.indexer = KvIndexer(block_size)
        self.server = None           # KvTransferServer started by serve()
        # wiring-owned background tasks (event feed, peer refresh) held
        # here so close() cancels them — never fire-and-forget
        self._tasks: List[asyncio.Task] = []
        if registry is None:
            from ..telemetry.registry import MetricsRegistry

            registry = MetricsRegistry()
        self.registry = registry
        if cold is not None and cold.registry is not registry:
            registry.attach(cold.registry)
        self._pulls = registry.counter(
            "dynamo_kv_fabric_prefix_pull_total",
            "Prefix pulls, labelled source=peer|cold and "
            "outcome=committed|failed|empty (failed/empty fall back to "
            "local recompute, byte-identically)",
        )
        # the unified dynamo_transfer_* family (docs/transfer_plane.md),
        # labelled {plane=fabric, backend=tcp|ici|local} — replaces the
        # retired dynamo_kv_fabric_prefix_pull_{bytes_total,
        # duration_seconds} instruments; cold-tier rehydrates report
        # backend=local (bytes move without a wire)
        self._xfer = TransferMetrics(registry, plane="fabric")
        # the local collective plane, shared by both halves: the pull
        # half receives on it, the serve half sends on it. Wrapped in
        # the backend that owns bounded-recv + abandonment (an abandoned
        # plane negotiates tcp from then on).
        self.ici: Optional[IciBackend] = None
        if ici is not None:
            self.set_ici(ici)

    def set_ici(self, plane) -> None:
        """Attach the local collective plane (CLI wiring runs this before
        ``serve``): peer pulls then negotiate ici per peer pair, and this
        worker's serve half answers ici pulls device-to-device."""
        if plane is None or isinstance(plane, IciBackend):
            self.ici = plane
        else:
            self.ici = IciBackend(plane)

    # ---------- ownership view ----------

    def apply_event(self, event) -> None:
        """Feed one RouterEvent (kv_router/protocols.py) into the
        ownership view. Events from this engine are ignored."""
        if event.worker_id == self.engine_id:
            return
        self.indexer.apply_event(event)

    def remove_worker(self, worker_id: str) -> None:
        self.indexer.remove_worker(worker_id)

    # ---------- planning (sync; scheduler admission path) ----------

    def may_hold_any(self) -> bool:
        """Cheap admission gate: is there ANY ownership to plan
        against? The scheduler loop runs every ~1 ms — with an empty
        peer view and an empty cold index (the common single-worker
        case) the per-request probe/plan work must cost nothing."""
        return ((self.peer_pull and len(self.indexer.tree) > 0)
                or (self.cold is not None and len(self.cold) > 0))

    def plan(self, hashes: List[int], local_blocks: int,
             prompt_len: int) -> Optional[PullPlan]:
        """Best pull extending a ``local_blocks``-block local hit.

        At least one prompt token must stay un-cached (the engine needs
        logits to sample from), so the pull run is capped at
        ``(prompt_len - 1) // block_size`` total cached blocks. Returns
        None when no source beats the local tiers by
        ``min_pull_blocks``.
        """
        max_cached = max(0, (prompt_len - 1) // self.block_size)
        budget = max_cached - local_blocks
        if budget < self.min_pull_blocks:
            return None
        cold_run: List[int] = []
        if self.cold is not None:
            cold_run = self.cold.match_extension(hashes, local_blocks)[:budget]
        peer_plan = (self._best_peer_run(hashes, local_blocks, budget)
                     if self.peer_pull else None)
        # longer run wins; ties go to the cold tier (local disk beats a
        # network round trip at equal coverage)
        if (len(cold_run) >= self.min_pull_blocks
                and (peer_plan is None
                     or len(cold_run) >= peer_plan.blocks)):
            return PullPlan(
                source="cold",
                hashes=list(cold_run),
                start_block=local_blocks,
            )
        return peer_plan

    def _best_peer_run(self, hashes: List[int], local_blocks: int,
                       budget: int) -> Optional[PullPlan]:
        if len(self.indexer.tree) == 0:
            return None
        overlap = self.indexer.find_matches(hashes)
        peers = self.peers() or {}
        best: Optional[Tuple[int, str]] = None
        for wid, score in overlap.scores.items():
            if wid == self.engine_id or wid not in peers:
                continue
            run = min(score, local_blocks + budget) - local_blocks
            if run < self.min_pull_blocks:
                continue
            if best is None or run > best[0]:
                best = (run, wid)
        if best is None:
            return None
        run, wid = best
        desc = peers[wid]
        return PullPlan(
            source="peer",
            hashes=list(hashes[local_blocks:local_blocks + run]),
            start_block=local_blocks,
            worker_id=wid,
            host=desc.get("host"),
            port=desc.get("port"),
            # peer plays the SENDER on the collective plane when we pull
            backend=negotiate_backend(desc, self.ici, peer_role="sender"),
        )

    def rank_peers(self, peers: List[dict],
                   token_ids: List[int]) -> List[dict]:
        """Order peer descriptors by prefix overlap with ``token_ids``
        (descending; ties keep the input order) — the router-quality
        selection the recovery controller uses for migration targets.

        The ownership view is keyed by KV-event worker ids, which are a
        different namespace than the migration plane's engine ids — the
        descriptor's ``worker_id`` (stamped by the CLI wiring) is the
        join key; a descriptor without one scores 0."""
        from ..tokens import compute_block_hashes

        if not peers or len(self.indexer.tree) == 0:
            return list(peers)
        overlap = self.indexer.find_matches(
            compute_block_hashes(token_ids, self.block_size)
        )
        return sorted(
            peers,
            key=lambda p: -overlap.scores.get(
                p.get("worker_id") or p.get("engine_id", ""), 0),
        )

    # ---------- serve half (KvTransferServer pull_source) ----------

    def grant(self, hashes: List[int]) -> Optional[PullGrant]:
        """Resolve + pin the longest locally-resident run of ``hashes``.

        HBM blocks (allocator.by_hash) are pinned; host-tier entries are
        copied out of RAM by reference. Staged (not-yet-drained) host
        offloads are skipped — serving them would need a loop-side
        drain. Returns None when not even the first hash is resident.
        """
        entries: List[_GrantEntry] = []
        pinned: List[int] = []
        tier2 = self.allocator.tier2
        for h in hashes:
            bid = self.allocator.by_hash.get(h)
            if bid is not None:
                entries.append(_GrantEntry(h, "hbm", block_id=bid))
                pinned.append(bid)
                continue
            arrays = tier2.store.get(h) if tier2 is not None else None
            if arrays is not None:
                entries.append(_GrantEntry(h, "host", arrays=arrays))
                continue
            break
        if not entries:
            return None
        if pinned:
            self.allocator.pin_blocks(pinned)
        return PullGrant(self, entries)

    async def serve(self, host: str = "127.0.0.1"):
        """Start this fabric's pull server (a read-only KvTransferServer)
        and return it; its descriptor registers in discovery under
        ``fabric_key``."""
        from ..disagg.transfer import KvTransferServer

        self.server = await KvTransferServer(
            scatter=lambda *a: None,
            on_commit=lambda *a: None,
            pull_source=self.grant,
            host=host,
            # serve half of the collective plane: negotiated ici pulls
            # stream device-to-device; the descriptor advertises the
            # rank this worker sends from so pullers only pick ici when
            # their plane pairs with it
            ici_send=self.ici,
            ici_rank=None if self.ici is None else self.ici.sender_rank,
        ).start()
        return self.server

    def hold_task(self, task: asyncio.Task) -> None:
        """Adopt a wiring-layer task (event consumer, peer refresh) into
        this fabric's lifecycle."""
        self._tasks.append(task)

    async def close(self) -> None:
        for t in self._tasks:
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []
        if self.server is not None:
            await self.server.close()
        if self.cold is not None:
            await self.cold.close()

    # ---------- pull half (scheduler-owned task) ----------

    async def pull(self, plan: PullPlan, block_ids: List[int],
                   request_id: str = "", trace_id: Optional[str] = None,
                   ) -> int:
        """Execute one pull into reserved ``block_ids``.

        Returns the number of blocks actually installed — always a
        PREFIX of ``plan.hashes`` (the caller registers exactly that
        run and recomputes the rest). Raises on transport failure; the
        caller falls back to local recompute and frees the reservation.
        Nothing here registers blocks: a partially-scattered reservation
        is anonymous and dies with the fallback's free.
        """
        assert len(block_ids) >= len(plan.hashes)
        t0 = time.monotonic()
        outcome = "failed"
        served = 0
        backend = "local" if plan.source == "cold" else plan.backend
        try:
            if plan.source == "cold":
                served = await self._pull_cold(plan, block_ids)
            else:
                served = await self._pull_peer(plan, block_ids, trace_id)
            outcome = "committed" if served else "empty"
            return served
        finally:
            self._pulls.inc(source=plan.source, outcome=outcome)
            self._xfer.observe_duration(time.monotonic() - t0, backend)
            self.flight.record(
                "kv_fabric.pull", request_id=request_id, trace_id=trace_id,
                source=plan.source, worker=plan.worker_id,
                backend=backend,
                asked=plan.blocks, served=served, outcome=outcome,
            )

    async def _maybe_stall(self) -> None:
        # chaos site: the pull stalls mid-flight; the scheduler's
        # deadline must cancel it and fall back byte-identically
        if faults.fire("prefix_pull_stall"):
            await asyncio.sleep(3600.0)

    async def _pull_cold(self, plan: PullPlan,
                         block_ids: List[int]) -> int:
        loop = asyncio.get_running_loop()
        served = 0
        for lo in range(0, len(plan.hashes), self.chunk_blocks):
            await self._maybe_stall()
            chunk = plan.hashes[lo:lo + self.chunk_blocks]

            def _read(chunk=chunk):
                ks, vs = [], []
                for h in chunk:
                    got = self.cold.get(h)
                    if got is None:
                        break  # absent/corrupt → the run ends here
                    ks.append(got[0])
                    vs.append(got[1])
                if not ks:
                    return None
                k = np.ascontiguousarray(np.concatenate(ks, axis=1))
                v = np.ascontiguousarray(np.concatenate(vs, axis=1))
                import jax

                return jax.device_put(k), jax.device_put(v), len(ks)

            staged = await loop.run_in_executor(None, _read)
            if staged is None:
                break
            k_dev, v_dev, n = staged
            # cache-mutating scatter on the loop: serializes with the
            # scheduler's own dispatches over the shared cache buffers
            self.runner.scatter_blocks(
                block_ids[served:served + n], k_dev, v_dev
            )
            self._xfer.add_bytes(k_dev.nbytes + v_dev.nbytes, "local")
            served += n
            if n < len(chunk):
                break
        return served

    async def _pull_peer(self, plan: PullPlan, block_ids: List[int],
                         trace_id: Optional[str]) -> int:
        loop = asyncio.get_running_loop()
        backend = plan.backend
        if backend == "ici" and (self.ici is None or not self.ici.alive):
            # plane abandoned between plan and pull — tcp still works
            backend = "tcp"
        reader, writer = await asyncio.open_connection(plan.host, plan.port)
        record_open("fabric", backend, peer=plan.worker_id or "",
                    trace_id=trace_id)
        self._xfer.channel_opened(backend)
        try:
            pack_frame(writer, {
                "type": "pull",
                "hashes": [int(h) for h in plan.hashes],
                "chunk_blocks": self.chunk_blocks,
                "trace_id": trace_id,
                "backend": backend,
            })
            await writer.drain()
            served = 0
            while True:
                await self._maybe_stall()
                frame = await read_header(reader, "pull")
                if frame is None:
                    # serving side died mid-stream — the pull fails and
                    # the caller recomputes locally; nothing registered
                    raise ConnectionResetError(
                        "pull connection closed mid-stream"
                    )
                ftype = frame.get("type")
                if ftype == "pull_blocks":
                    k, v = await TcpBackend.recv_blocks(reader, frame)
                    n = k.shape[1]
                    if served + n > len(plan.hashes):
                        raise ValueError("peer served past the asked run")
                    # stage the H2D copy off-loop; scatter on the loop
                    # (coordinator._scatter's discipline) — the next
                    # frame's network read overlaps this device copy
                    k_dev, v_dev = await loop.run_in_executor(
                        None, self._device_put, k, v
                    )
                    self.runner.scatter_blocks(
                        block_ids[served:served + n], k_dev, v_dev
                    )
                    self._xfer.add_bytes(k.nbytes + v.nbytes, "tcp")
                    served += n
                elif ftype == "pull_ici_blocks":
                    # control-only header: the payload rides the
                    # collective, device-to-device — bounded, serialized
                    # receive with the seq cross-check (a mismatch means
                    # a mis-paired entry; the pull aborts and falls back
                    # rather than scatter bytes of unknown provenance)
                    if self.ici is None:
                        raise ValueError(
                            "peer sent an ici frame but this worker has "
                            "no collective plane"
                        )
                    n = int(frame["nblocks"])
                    if served + n > len(plan.hashes):
                        raise ValueError("peer served past the asked run")
                    k_dev, v_dev, seq = await self.ici.recv(n)
                    if seq != frame.get("seq", 0):
                        raise ValueError(
                            f"ici pull seq mismatch (header "
                            f"{frame.get('seq')}, payload {seq})"
                        )
                    self.runner.scatter_blocks(
                        block_ids[served:served + n], k_dev, v_dev
                    )
                    self._xfer.add_bytes(
                        int(k_dev.nbytes) + int(v_dev.nbytes), "ici"
                    )
                    served += n
                elif ftype == "pull_end":
                    return min(served, int(frame.get("served", served)))
                else:
                    raise ValueError(f"unknown pull frame {ftype!r}")
        finally:
            self._xfer.channel_closed(backend)
            writer.close()

    @staticmethod
    def _device_put(k: np.ndarray, v: np.ndarray):
        import jax

        return jax.device_put(k), jax.device_put(v)
