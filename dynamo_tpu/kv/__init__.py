"""Multi-tier KV cache management (HBM + host RAM offload tier)."""

from .host_tier import KvHostTier

__all__ = ["KvHostTier"]
