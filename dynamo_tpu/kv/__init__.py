"""Multi-tier KV cache management and the cluster KV fabric.

Tiers: HBM (engine/block_allocator.py) → host RAM (host_tier.py) →
content-addressed disk (cold_tier.py). The fabric (fabric.py) stitches
every worker's tiers into one datacenter-wide prefix cache: remote
prefix hits PULL committed blocks over the transfer plane instead of
recomputing, and cold-but-hot-again prefixes rehydrate from spill files
any worker (including a freshly respawned one) can read.
"""

from .cold_tier import KvColdTier
from .fabric import KvFabric, PullPlan, fabric_key
from .host_tier import KvHostTier

__all__ = ["KvColdTier", "KvFabric", "KvHostTier", "PullPlan", "fabric_key"]
