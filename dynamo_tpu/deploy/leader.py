"""Lease-based leader election for operator replicas.

Reference analog: the Go operator's controller-runtime leader election
(cmd/main.go ``LeaderElection`` flag) — a coordination.k8s.io/v1 Lease
is the lock; the holder renews it, everyone else retries, and a holder
that cannot renew must stop leading before the lease expires.

Same protocol here through a compare-and-swap client interface:
``read`` returns (lease-spec, version); ``write`` commits only if the
version still matches (optimistic concurrency). ``InMemoryLeases``
backs tests; ``KubectlLeases`` maps the CAS onto ``kubectl create``
(only-if-absent) and ``kubectl replace`` with resourceVersion (k8s
rejects a stale version as Conflict).

Clock discipline: expiry is judged with the LOCAL monotonic clock
against when *we* observed a renewTime change — never by parsing the
holder's wall-clock timestamp — so clock skew between replicas cannot
cause two leaders. A fresh observer therefore always waits a full
``lease_duration_s`` before its first takeover attempt.
"""

from __future__ import annotations

import json
import logging
import re
import subprocess
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Dict, Optional, Protocol, Tuple

logger = logging.getLogger(__name__)


class LeaseClient(Protocol):
    def read(self, namespace: str, name: str) -> Tuple[Optional[dict], Optional[str]]:
        """(lease spec, version), or (None, None) when absent."""
        ...

    def write(self, namespace: str, name: str, spec: dict,
              expected_version: Optional[str]) -> bool:
        """CAS commit. expected_version None = create-only-if-absent.
        Returns False on conflict (someone else wrote first)."""
        ...


class InMemoryLeases:
    """Test double with real CAS semantics."""

    def __init__(self) -> None:
        self._data: Dict[tuple, Tuple[dict, int]] = {}
        self._lock = threading.Lock()

    def read(self, namespace: str, name: str):
        with self._lock:
            entry = self._data.get((namespace, name))
            if entry is None:
                return None, None
            spec, version = entry
            return json.loads(json.dumps(spec)), str(version)

    def write(self, namespace: str, name: str, spec: dict,
              expected_version: Optional[str]) -> bool:
        with self._lock:
            entry = self._data.get((namespace, name))
            if expected_version is None:
                if entry is not None:
                    return False
                self._data[(namespace, name)] = (spec, 1)
                return True
            if entry is None or str(entry[1]) != expected_version:
                return False
            self._data[(namespace, name)] = (spec, entry[1] + 1)
            return True


class KubectlLeases:
    """coordination.k8s.io/v1 Lease CAS via kubectl."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    def _manifest(self, namespace: str, name: str, spec: dict,
                  version: Optional[str]) -> dict:
        meta: dict = {"name": name, "namespace": namespace}
        if version is not None:
            meta["resourceVersion"] = version
        return {"apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
                "metadata": meta, "spec": spec}

    def read(self, namespace: str, name: str):
        proc = subprocess.run(
            [self.kubectl, "get", "lease", name, "-n", namespace,
             "-o", "json"],
            capture_output=True, text=True,
        )
        if proc.returncode != 0:
            # "lease absent" and "API unreachable" must stay distinct: a
            # create attempt against a *present* lease during an API blip
            # would read as a lost election and depose a healthy leader
            if "notfound" in proc.stderr.lower().replace(" ", ""):
                return None, None
            raise RuntimeError(f"lease read failed: {proc.stderr.strip()}")
        obj = json.loads(proc.stdout)
        return obj.get("spec", {}), obj["metadata"].get("resourceVersion")

    # a genuine lost CAS race surfaces as kubectl's structured status
    # reason — "Error from server (Conflict): ..." / "(AlreadyExists)".
    # Match that token, not free-text substrings: an unrelated API error
    # whose message merely *contains* "conflict" must raise (transient
    # failure), not read as an authoritative loss that deposes a leader
    # still holding a valid lease.
    _CAS_REASON = re.compile(
        r"error from server \((conflict|alreadyexists)\)", re.IGNORECASE)

    def write(self, namespace: str, name: str, spec: dict,
              expected_version: Optional[str]) -> bool:
        verb = ["create"] if expected_version is None else ["replace"]
        manifest = self._manifest(namespace, name, spec, expected_version)
        proc = subprocess.run(
            [self.kubectl, *verb, "-f", "-"],
            input=json.dumps(manifest), capture_output=True, text=True,
        )
        if proc.returncode != 0:
            err = proc.stderr.strip()
            if self._CAS_REASON.search(err):
                logger.debug("lease write lost the CAS race: %s", err)
                return False
            raise RuntimeError(f"lease write failed: {err}")
        return True


class LeaderElector:
    """Acquire-then-renew loop around a CAS lease.

    ``run(stop, lead)`` blocks until leadership is won, calls ``lead``
    (which should run the control loop until ``stop``), and — if renewal
    is ever lost — sets ``stop`` so the caller exits and a restart
    rejoins the election as a follower. One elector per process.
    """

    def __init__(self, client: LeaseClient, identity: str,
                 name: str = "dynamo-tpu-operator",
                 namespace: str = "default",
                 lease_duration_s: float = 15.0,
                 renew_interval_s: float = 5.0,
                 renew_deadline_s: Optional[float] = None,
                 clock=time.monotonic):
        self.client = client
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.lease_duration_s = lease_duration_s
        self.renew_interval_s = renew_interval_s
        # how long renewal may keep FAILING (API unreachable) before the
        # leader steps down — must undercut lease_duration_s, or a
        # follower takes the expired lease while we still reconcile
        # (split brain). controller-runtime's RenewDeadline analog.
        self.renew_deadline_s = (
            renew_deadline_s if renew_deadline_s is not None
            else lease_duration_s * 2 / 3
        )
        self._clock = clock
        # (holder, renewTime) we last saw → local time we saw it
        self._observed: Optional[Tuple[tuple, float]] = None
        self._last_renew_written: Optional[datetime] = None

    def _spec(self, transitions: int) -> dict:
        # renewTime must be a valid MicroTime (the apiserver rejects
        # anything else), but observers only time its *changes* with
        # their own clocks (see module docstring) — so it just has to be
        # well-formed and distinct per renewal, never compared to a
        # remote clock. Strictly-increasing guard: a same-microsecond
        # (or backwards-stepping) wall clock would otherwise make a
        # renewal look like no renewal.
        now = datetime.now(timezone.utc)
        if self._last_renew_written is not None and now <= self._last_renew_written:
            now = self._last_renew_written + timedelta(microseconds=1)
        self._last_renew_written = now
        stamp = now.strftime("%Y-%m-%dT%H:%M:%S.%fZ")
        return {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": int(self.lease_duration_s),
            "leaseTransitions": transitions,
            "renewTime": stamp,
        }

    def try_acquire_or_renew(self) -> bool:
        """One CAS round-trip. True = we hold the lease now."""
        spec, version = self.client.read(self.namespace, self.name)
        now = self._clock()
        if spec is None:
            return self.client.write(
                self.namespace, self.name, self._spec(0), None)
        holder = spec.get("holderIdentity")
        fingerprint = (holder, spec.get("renewTime"))
        if holder == self.identity:
            return self.client.write(
                self.namespace, self.name,
                self._spec(spec.get("leaseTransitions", 0)),
                version)
        if self._observed is None or self._observed[0] != fingerprint:
            self._observed = (fingerprint, now)  # holder is alive; restart TTL
            return False
        if now - self._observed[1] < spec.get(
                "leaseDurationSeconds", self.lease_duration_s):
            return False
        # holder stopped renewing a full lease ago: take over
        took = self.client.write(
            self.namespace, self.name,
            self._spec(spec.get("leaseTransitions", 0) + 1), version)
        if took:
            logger.info("leader election: %s took over from expired %s",
                        self.identity, holder)
        return took

    def run(self, stop: threading.Event, lead) -> None:
        while not stop.is_set():
            try:
                acquired = self.try_acquire_or_renew()
            except Exception:
                # API blip while campaigning: stay a follower and retry
                logger.exception("lease acquire attempt failed")
                acquired = False
            if acquired:
                logger.info("leader election: %s is leader", self.identity)
                renewer = threading.Thread(
                    target=self._renew_until_lost, args=(stop,), daemon=True)
                renewer.start()
                try:
                    lead()
                finally:
                    stop.set()
                    renewer.join(timeout=self.renew_interval_s * 2)
                return
            stop.wait(self.renew_interval_s)

    def _renew_until_lost(self, stop: threading.Event) -> None:
        last_renewed = self._clock()
        while not stop.wait(self.renew_interval_s):
            try:
                if not self.try_acquire_or_renew():
                    # authoritative: someone else won the CAS
                    logger.error("leader election: %s lost the lease; "
                                 "stepping down", self.identity)
                    stop.set()
                    return
                last_renewed = self._clock()
            except Exception:
                # transient API failure: retry, but only inside the renew
                # deadline — past it a follower may legitimately take the
                # expired lease, so leading on is a split brain
                logger.exception("lease renewal attempt failed")
                if self._clock() - last_renewed > self.renew_deadline_s:
                    logger.error(
                        "leader election: %s could not renew within "
                        "%.0fs; stepping down", self.identity,
                        self.renew_deadline_s)
                    stop.set()
                    return
