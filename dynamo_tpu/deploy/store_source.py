"""api-store ↔ operator bridge: store records drive the reconciler.

Reference analog: the reference's api-store does not just register
records — creating a deployment there creates the cluster objects
(deploy/dynamo/api-store/ai_dynamo_store/api/deployments.py:30
``create_dynamo_deployment`` → api/k8s.py). Here the same coupling is a
*source*: the operator's control loop can list CRs from the store
(``--api-store-url``) instead of from the Kubernetes API, and writes
reconcile status back into the record — so ``llmctl deploy`` → store →
reconciler → cluster is one path, testable end-to-end against
``InMemoryKube`` with no cluster at all.

stdlib urllib (the operator binary and llmctl are sync; no aiohttp
client session/event loop to manage for four tiny REST verbs).
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request
from typing import List, Optional

from .operator import GROUP, KIND, VERSION

logger = logging.getLogger(__name__)


class ApiStoreClient:
    """Sync REST client for deploy/api_store.py."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, path: str, body: Optional[dict] = None):
        req = urllib.request.Request(
            f"{self.base_url}{path}", method=method,
            data=None if body is None else json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read().decode() or "null")

    # ---------- deployment CRUD (llmctl deploy) ----------

    def list(self) -> List[dict]:
        return self._request("GET", "/api/v1/deployments")["deployments"]

    def get(self, name: str) -> Optional[dict]:
        try:
            return self._request("GET", f"/api/v1/deployments/{name}")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise

    def create(self, name: str, spec: dict) -> dict:
        return self._request(
            "POST", "/api/v1/deployments", {"name": name, "spec": spec}
        )

    def update(self, name: str, spec: dict) -> dict:
        return self._request(
            "PUT", f"/api/v1/deployments/{name}", {"spec": spec}
        )

    def delete(self, name: str) -> None:
        self._request("DELETE", f"/api/v1/deployments/{name}")

    def set_status(self, name: str, status: dict) -> None:
        self._request(
            "PUT", f"/api/v1/deployments/{name}/status", {"status": status}
        )

    # ---------- operator source ----------

    def get_crs(self) -> Optional[List[dict]]:
        """Store records as CR dicts for the control loop; None when the
        store is unreachable (the loop skips the cycle — same contract as
        operator_main.get_crs, for the same finalize-everything hazard)."""
        try:
            return [record_to_cr(rec) for rec in self.list()]
        except Exception:
            logger.warning("api-store listing failed", exc_info=True)
            return None

    def write_status(self, cr: dict, status: dict) -> None:
        """Reconciler status sink: the record IS the CR's status home."""
        self.set_status(cr["metadata"]["name"], status)


def record_to_cr(rec: dict) -> dict:
    """Store record → DynamoTpuGraphDeployment CR dict.

    The record's spec is the CR spec verbatim; ``k8sNamespace`` (optional
    spec field) picks the target cluster namespace; the record's update
    timestamp stands in for metadata.generation so status readers can see
    whether the latest spec was observed."""
    spec = rec["spec"]
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": KIND,
        "metadata": {
            "name": rec["name"],
            "namespace": spec.get("k8sNamespace", "default"),
            "generation": int(rec.get("updated") or 0),
        },
        "spec": spec,
    }
