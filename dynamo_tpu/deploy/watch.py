"""Event-driven watch loop for the operator.

Reference analog: the Go controller's controller-runtime watch machinery
(deploy/dynamo/operator internal/controller — Reconcile is driven by
informer events, with a periodic resync). Same contract here without the
kubernetes client library: ``kubectl get --watch --output-watch-events``
is the event source, and every (re)connect starts with a full relist so
drift can never outlive a reconnect. The resync interval doubles as the
watch's request timeout — when it expires the stream ends, the loop
relists, and reconnects — which is exactly controller-runtime's resync
semantic expressed through kubectl.

The loop itself is transport-agnostic (it consumes any iterable of
watch-event dicts), so tests drive it from in-memory event lists.
"""

from __future__ import annotations

import codecs
import json
import logging
import subprocess
import time
from typing import Callable, Iterable, Iterator, List, Optional

from .operator import (GROUP, PLURAL, Reconciler, cr_key, relist_reconcile,
                       safe_finalize, safe_reconcile)

logger = logging.getLogger(__name__)


def iter_watch_events(chunks: Iterable[str]) -> Iterator[dict]:
    """Parse a stream of concatenated JSON watch events.

    kubectl emits pretty-printed JSON documents back to back (no
    delimiters beyond whitespace); chunks may split mid-document, so
    accumulate and decode greedily.
    """
    decoder = json.JSONDecoder()
    buf = ""
    for chunk in chunks:
        buf += chunk
        while True:
            stripped = buf.lstrip()
            if not stripped:
                buf = ""
                break
            try:
                event, end = decoder.raw_decode(stripped)
            except json.JSONDecodeError:
                buf = stripped  # incomplete document; wait for more
                break
            buf = stripped[end:]
            yield event


def watch_loop(
    reconciler: Reconciler,
    list_crs: Callable[[], Optional[List[dict]]],
    open_stream: Callable[[], Iterable[dict]],
    stop=None,                    # threading.Event-like; None = run forever
    reconnect_backoff_s: float = 2.0,
    max_backoff_s: float = 60.0,
) -> None:
    """Relist + reconcile, then apply watch events until the stream ends;
    repeat. DELETED events finalize; ADDED/MODIFIED reconcile; ERROR
    events (v1.Status payloads, e.g. 410 Gone on an expired
    resourceVersion) abandon the stream so the relist repairs state.

    A CR that disappears *between* streams — deleted while we were
    disconnected, so no DELETED event was ever observed — is caught by
    the relist diff, same as the poll loop. A cleanly-ended stream (the
    resync/request timeout on a quiet cluster) reconnects after the base
    delay; only failures grow the backoff.
    """
    seen: dict = {}
    backoff = reconnect_backoff_s
    while stop is None or not stop.is_set():
        listed = list_crs()
        if listed is None:
            # listing failed — never mistake an API error for "no CRs"
            if _wait(stop, backoff):
                return
            backoff = min(backoff * 2, max_backoff_s)
            continue
        seen = relist_reconcile(reconciler, listed, seen)
        backoff = reconnect_backoff_s  # the API is reachable again

        failed = False
        try:
            for event in open_stream():
                if stop is not None and stop.is_set():
                    return
                obj = event.get("object")
                etype = event.get("type")
                if not obj or etype == "BOOKMARK":
                    continue
                name = (obj.get("metadata") or {}).get("name")
                if etype == "ERROR" or not name:
                    # v1.Status error payload (410 Gone etc.): the stream
                    # is no longer trustworthy; relist and reconnect
                    logger.warning("watch: error event %s; relisting",
                                   json.dumps(event)[:200])
                    break
                key = cr_key(obj)
                if etype == "DELETED":
                    logger.info("watch: finalizing %s/%s", *key)
                    if safe_finalize(reconciler, obj):
                        seen.pop(key, None)
                    else:
                        # the CR stays in ``seen`` and is absent from
                        # every later listing → relist retries teardown
                        break
                else:  # ADDED / MODIFIED
                    seen[key] = obj
                    if not safe_reconcile(reconciler, obj):
                        # a quiet cluster would not produce another event
                        # for this CR until the resync timeout; abandon
                        # the stream so the relist retries within the
                        # base delay (the poll loop's 10s analog)
                        break
        except Exception:
            logger.exception("watch stream failed; relisting after %.0fs",
                             backoff)
            failed = True
        if _wait(stop, backoff if failed else reconnect_backoff_s):
            return
        if failed:
            backoff = min(backoff * 2, max_backoff_s)


def _wait(stop, seconds: float) -> bool:
    """True = stop requested."""
    if stop is not None:
        return stop.wait(seconds) if seconds else stop.is_set()
    if seconds:
        time.sleep(seconds)
    return False


def _decoded_chunks(raw) -> Iterator[str]:
    """Incrementally decode a BufferedReader's available bytes.

    ``read1`` returns as soon as *any* bytes are available — a
    TextIOWrapper.read(n) would block until n characters accumulate,
    stalling event delivery on quiet streams.
    """
    decode = codecs.getincrementaldecoder("utf-8")(errors="replace").decode
    while True:
        data = raw.read1(4096)
        if not data:
            return
        yield decode(data)


class KubectlWatchSource:
    """``open_stream`` over a real cluster: one kubectl watch process per
    call, bounded by the resync interval so the loop periodically
    relists (controller-runtime's resync)."""

    def __init__(self, kubectl: str = "kubectl",
                 namespace: Optional[str] = None,
                 resync_interval_s: float = 300.0):
        self.kubectl = kubectl
        self.namespace = namespace
        self.resync_interval_s = resync_interval_s

    def __call__(self) -> Iterator[dict]:
        args = [self.kubectl, "get", f"{PLURAL}.{GROUP}", "--watch",
                "--output-watch-events", "-o", "json",
                f"--request-timeout={int(self.resync_interval_s)}s"]
        args += (["-n", self.namespace] if self.namespace
                 else ["--all-namespaces"])
        proc = subprocess.Popen(args, stdout=subprocess.PIPE)
        try:
            yield from iter_watch_events(_decoded_chunks(proc.stdout))
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()  # kubectl ignored SIGTERM (stalled net read)
                proc.wait()
