"""Kubernetes operator for DynamoTpuGraphDeployment resources.

Reference analog: deploy/dynamo/operator — the Go controller that turns
a DynamoDeployment CR (artifact + per-service overrides,
api/v1alpha1/dynamodeployment_types.go:31-60) into child Deployments/
Services (internal/controller/dynamodeployment_controller.go). Same
shape here, TPU-native:

- ``render_manifests(cr)`` is a PURE function: CR spec → the desired
  child manifests (dynstore, frontend, one Deployment per service role,
  Services, a ConfigMap of engine flags). Workers request
  ``google.com/tpu`` resources and pin TPU node pools via GKE selectors.
- ``Reconciler`` diffs desired vs. observed through a pluggable
  ``KubeClient`` (apply/delete/list) and is idempotent — the control
  loop can run from a watch or a poll. ``InMemoryKube`` backs the tests;
  ``KubectlClient`` shells out to kubectl for real clusters (no
  kubernetes python client in the image, and the operator only needs
  apply/delete/get semantics).

The CRD itself ships as YAML in deploy/kubernetes/crd.yaml with example
CRs alongside.
"""

from __future__ import annotations

import json
import logging
import subprocess
import time
from typing import Dict, List, Optional, Protocol

logger = logging.getLogger(__name__)

GROUP = "dynamo.tpu"
VERSION = "v1alpha1"
KIND = "DynamoTpuGraphDeployment"
PLURAL = "dynamotpugraphdeployments"

MANAGED_BY = {"app.kubernetes.io/managed-by": "dynamo-tpu-operator"}


def managed_selector(instance: str) -> str:
    """labelSelector for one CR's managed children — the single source
    both cluster clients (kubectl + REST) list/prune by; a drifting copy
    would silently stop orphan pruning for one of them."""
    return (
        f"app.kubernetes.io/instance={instance},"
        f"app.kubernetes.io/managed-by="
        f"{MANAGED_BY['app.kubernetes.io/managed-by']}"
    )

# role → in=/out= argv of cli.run (the service binaries, SURVEY §2.6/2.7)
ROLE_ARGS = {
    "frontend": ["in=http", "out=none"],
    "processor": ["in=dyn://{ns}.processor.chat", "out=processor"],
    "worker": ["in=dyn://{ns}.backend.generate", "out=jax", "--token-level"],
    "decode": ["in=dyn://{ns}.backend.generate", "out=jax", "--token-level",
               "--remote-prefill"],
    "prefill": ["in=prefill", "out=jax"],
    # the SLA planner control-plane pod: observes the decode pool +
    # prefill queue, actuates router config and (via the api-store)
    # per-role replica counts
    "planner": ["in=planner", "out=none",
                "--worker-endpoint", "dyn://{ns}.backend.generate"],
    # the fleet telemetry hub pod: scrapes every discovery-registered
    # /metrics sidecar into history rings, serves /fleet/metrics +
    # /fleet/workers (dynamotop's data source) + /debug/incidents
    "hub": ["in=hub", "out=none"],
}

DYNSTORE_PORT = 4871
HTTP_PORT = 8080


def _labels(cr_name: str, service: str) -> Dict[str, str]:
    return {
        "app.kubernetes.io/name": "dynamo-tpu",
        "app.kubernetes.io/instance": cr_name,
        "app.kubernetes.io/component": service,
        **MANAGED_BY,
    }


def _owner_ref(cr: dict) -> dict:
    return {
        "apiVersion": f"{GROUP}/{VERSION}",
        "kind": KIND,
        "name": cr["metadata"]["name"],
        "uid": cr["metadata"].get("uid", ""),
        "controller": True,
    }


def _deployment(cr: dict, service: str, spec: dict) -> dict:
    name = cr["metadata"]["name"]
    ns = cr["metadata"].get("namespace", "default")
    graph_ns = cr["spec"].get("namespace", "public")
    image = spec.get("image") or cr["spec"].get("image", "dynamo-tpu:latest")
    role = spec.get("role", service)
    if role not in ROLE_ARGS and role != "dynstore":
        raise ValueError(f"unknown service role {role!r} for {service}")

    if role == "dynstore":
        command = ["python", "-m", "dynamo_tpu.runtime.transports.dynstore",
                   "--host", "0.0.0.0", "--port", str(DYNSTORE_PORT)]
        ports = [{"containerPort": DYNSTORE_PORT, "name": "dynstore"}]
    else:
        argv = [a.format(ns=graph_ns) for a in ROLE_ARGS[role]]
        command = ["python", "-m", "dynamo_tpu.cli.run", *argv,
                   "--store-host", f"{name}-dynstore",
                   "--store-port", str(DYNSTORE_PORT),
                   "--namespace", graph_ns]
        if spec.get("modelPath"):
            command += ["--model-path", spec["modelPath"]]
        if spec.get("modelName") or cr["spec"].get("modelName"):
            command += ["--model-name",
                        spec.get("modelName") or cr["spec"]["modelName"]]
        command += list(spec.get("extraArgs", []))
        ports = (
            [{"containerPort": HTTP_PORT, "name": "http"}]
            if role == "frontend" else []
        )

    container: dict = {
        "name": service,
        "image": image,
        "command": command,
        "ports": ports,
        "env": [
            {"name": "DYN_LOGGING_JSONL", "value": "1"},
            *[{"name": k, "value": str(v)}
              for k, v in (spec.get("env") or {}).items()],
        ],
    }
    pod_spec: dict = {"containers": [container]}

    tpus = spec.get("tpus", 0)
    if tpus:
        container["resources"] = {
            "requests": {"google.com/tpu": str(tpus)},
            "limits": {"google.com/tpu": str(tpus)},
        }
        pod_spec["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator":
                spec.get("tpuAccelerator", "tpu-v5-lite-podslice"),
            "cloud.google.com/gke-tpu-topology": spec.get("tpuTopology", "1x1"),
        }

    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": f"{name}-{service}",
            "namespace": ns,
            "labels": _labels(name, service),
            "ownerReferences": [_owner_ref(cr)],
        },
        "spec": {
            "replicas": spec.get("replicas", 1),
            "selector": {"matchLabels": _labels(name, service)},
            "template": {
                "metadata": {"labels": _labels(name, service)},
                "spec": pod_spec,
            },
        },
    }


def _service(cr: dict, service: str, port: int, port_name: str) -> dict:
    name = cr["metadata"]["name"]
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": f"{name}-{service}",
            "namespace": cr["metadata"].get("namespace", "default"),
            "labels": _labels(name, service),
            "ownerReferences": [_owner_ref(cr)],
        },
        "spec": {
            "selector": _labels(name, service),
            "ports": [{"port": port, "targetPort": port, "name": port_name}],
        },
    }


def render_manifests(cr: dict) -> List[dict]:
    """CR → desired child manifests. Pure; raises on invalid specs."""
    services: Dict[str, dict] = dict(cr["spec"].get("services") or {})
    manifests: List[dict] = []
    # every graph gets its control/message plane + frontend unless the CR
    # overrides them explicitly
    services.setdefault("dynstore", {"role": "dynstore"})
    services.setdefault("frontend", {"role": "frontend"})
    for service, spec in services.items():
        manifests.append(_deployment(cr, service, spec))
        role = spec.get("role", service)
        if role == "dynstore":
            manifests.append(_service(cr, service, DYNSTORE_PORT, "dynstore"))
        elif role == "frontend":
            manifests.append(_service(cr, service, HTTP_PORT, "http"))
    return manifests


def _key(m: dict) -> str:
    return f'{m["kind"]}/{m["metadata"].get("namespace", "default")}/{m["metadata"]["name"]}'


class KubeClient(Protocol):
    """The verbs the reconcile loop needs."""

    def apply(self, manifest: dict) -> None: ...

    def delete(self, kind: str, namespace: str, name: str) -> None: ...

    def list_managed(self, namespace: str, instance: str) -> List[dict]: ...

    def update_status(self, cr: dict, status: dict) -> None: ...


class InMemoryKube:
    """Test double with real apply/delete/list semantics."""

    def __init__(self) -> None:
        self.objects: Dict[str, dict] = {}
        # (namespace, name) → last written CR status
        self.statuses: Dict[tuple, dict] = {}

    def apply(self, manifest: dict) -> None:
        self.objects[_key(manifest)] = json.loads(json.dumps(manifest))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self.objects.pop(f"{kind}/{namespace}/{name}", None)

    def list_managed(self, namespace: str, instance: str) -> List[dict]:
        out = []
        for m in self.objects.values():
            labels = m["metadata"].get("labels", {})
            if (m["metadata"].get("namespace", "default") == namespace
                    and labels.get("app.kubernetes.io/instance") == instance
                    and labels.get("app.kubernetes.io/managed-by")
                    == MANAGED_BY["app.kubernetes.io/managed-by"]):
                out.append(m)
        return out

    def update_status(self, cr: dict, status: dict) -> None:
        key = (cr["metadata"].get("namespace", "default"),
               cr["metadata"]["name"])
        self.statuses[key] = json.loads(json.dumps(status))


class KubectlClient:
    """Real-cluster client via kubectl (present on operator pods)."""

    def __init__(self, kubectl: str = "kubectl"):
        self.kubectl = kubectl

    def _run(self, *args: str, stdin: Optional[str] = None) -> str:
        proc = subprocess.run(
            [self.kubectl, *args], input=stdin, capture_output=True,
            text=True, check=True,
        )
        return proc.stdout

    def apply(self, manifest: dict) -> None:
        self._run("apply", "-f", "-", stdin=json.dumps(manifest))

    def delete(self, kind: str, namespace: str, name: str) -> None:
        self._run("delete", kind.lower(), name, "-n", namespace,
                  "--ignore-not-found")

    def list_managed(self, namespace: str, instance: str) -> List[dict]:
        out = self._run(
            "get", "deployments,services", "-n", namespace,
            "-l", managed_selector(instance), "-o", "json",
        )
        return json.loads(out).get("items", [])

    def update_status(self, cr: dict, status: dict) -> None:
        """Write the CR's status subresource (the CRD enables it) so
        ``kubectl get`` shows reconcile health — reference analog:
        dynamodeployment_controller.go status/conditions handling."""
        self._run(
            "patch", f"{PLURAL}.{GROUP}", cr["metadata"]["name"],
            "-n", cr["metadata"].get("namespace", "default"),
            "--type=merge", "--subresource=status",
            "-p", json.dumps({"status": status}),
        )


class Reconciler:
    """Desired-state reconcile: render, apply changed, prune orphans.

    Reference analog: dynamodeployment_controller.go Reconcile — but as
    an explicit diff over manifests so the same function serves a watch
    loop, a poll loop, and the unit tests.
    """

    def __init__(self, client: KubeClient, status_writer=None):
        self.client = client
        # where CR status lands: the kube client's status subresource by
        # default; store-sourced CRs write back into their store record
        self._status_writer = status_writer
        # last applied spec per child, to skip no-op applies
        self._applied: Dict[str, str] = {}
        # last written status per CR: steady-state cycles must not patch
        # the API server every poll, and lastTransitionTime must mark the
        # actual transition (k8s condition convention)
        self._status_written: Dict[tuple, dict] = {}

    def reconcile(self, cr: dict) -> Dict[str, List[str]]:
        """Bring the cluster to the CR's desired state, then write the
        CR's status (observed generation, child counts, Reconciled
        condition). Returns a change summary {applied: [...],
        deleted: [...]} (for events/logs)."""
        name = cr["metadata"]["name"]
        ns = cr["metadata"].get("namespace", "default")
        try:
            desired = {_key(m): m for m in render_manifests(cr)}
            observed = {_key(o): o for o in self.client.list_managed(ns, name)}

            applied, deleted = [], []
            for key, manifest in desired.items():
                serialized = json.dumps(manifest, sort_keys=True)
                # re-apply on spec change AND on external deletion — the
                # cache alone would never repair drift (e.g. kubectl
                # delete of a child)
                if self._applied.get(key) != serialized or key not in observed:
                    self.client.apply(manifest)
                    self._applied[key] = serialized
                    applied.append(key)

            for key, obj in observed.items():
                if key not in desired:
                    self.client.delete(
                        obj["kind"],
                        obj["metadata"].get("namespace", "default"),
                        obj["metadata"]["name"],
                    )
                    self._applied.pop(key, None)
                    deleted.append(key)
        except Exception as e:
            self.write_status(cr, error=str(e))
            raise
        counts: Dict[str, int] = {}
        for m in desired.values():
            counts[m["kind"]] = counts.get(m["kind"], 0) + 1
        self.write_status(
            cr, children=counts,
            changed=bool(applied or deleted),
        )
        return {"applied": applied, "deleted": deleted}

    def write_status(self, cr: dict, children: Optional[Dict[str, int]] = None,
                     error: Optional[str] = None,
                     changed: bool = False) -> None:
        """Best-effort CR status write (failures must never fail the
        reconcile itself)."""
        condition = {
            "type": "Reconciled",
            "status": "False" if error else "True",
            "reason": "ReconcileError" if error else "ReconcileSucceeded",
            "message": error or (
                "children updated" if changed else "in sync"
            ),
            "lastTransitionTime": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }
        status = {
            "observedGeneration": cr["metadata"].get("generation"),
            "children": children or {},
            "conditions": [condition],
        }
        artifact = (cr.get("spec") or {}).get("artifact") or {}
        if artifact.get("version"):
            # artifact-pinned deploys surface what they run (sdk.build
            # content-addressed version) in the CR status
            status["artifactVersion"] = artifact["version"]
            if artifact.get("name"):
                status["artifactName"] = artifact["name"]
        cr_key = (cr["metadata"].get("namespace", "default"),
                  cr["metadata"]["name"])
        prev = self._status_written.get(cr_key)
        if prev is not None:
            prev_cond = prev["conditions"][0]
            if prev_cond["status"] == condition["status"]:
                # same condition state → keep the original transition
                # time; and if nothing else changed, skip the patch
                condition["lastTransitionTime"] = prev_cond["lastTransitionTime"]
                if prev == status:
                    return
        try:
            (self._status_writer or self.client.update_status)(cr, status)
            self._status_written[cr_key] = status
        except Exception:
            logger.exception(
                "status update failed for %s/%s",
                cr["metadata"].get("namespace", "default"),
                cr["metadata"]["name"],
            )

    def finalize(self, cr: dict) -> List[str]:
        """CR deleted: remove every managed child."""
        name = cr["metadata"]["name"]
        ns = cr["metadata"].get("namespace", "default")
        removed = []
        for observed in self.client.list_managed(ns, name):
            self.client.delete(
                observed["kind"],
                observed["metadata"].get("namespace", "default"),
                observed["metadata"]["name"],
            )
            self._applied.pop(_key(observed), None)
            removed.append(_key(observed))
        return removed


def cr_key(cr: dict) -> tuple:
    """(namespace, name) — same-named CRs in different namespaces are
    distinct graphs."""
    return (cr["metadata"].get("namespace", "default"), cr["metadata"]["name"])


def safe_reconcile(reconciler: Reconciler, cr: dict) -> bool:
    """Reconcile one CR; log instead of raising (one bad CR or one
    transient kubectl error must not take a control loop down). False =
    failed, caller should arrange a retry sooner than the next resync."""
    try:
        changes = reconciler.reconcile(cr)
        if changes["applied"] or changes["deleted"]:
            logger.info("reconciled %s/%s: %s", *cr_key(cr), changes)
        return True
    except Exception:
        logger.exception("reconcile failed for %s/%s", *cr_key(cr))
        return False


def safe_finalize(reconciler: Reconciler, cr: dict) -> bool:
    try:
        reconciler.finalize(cr)
        return True
    except Exception:
        logger.exception("finalize failed for %s/%s", *cr_key(cr))
        return False


def relist_reconcile(
    reconciler: Reconciler,
    listed: List[dict],
    seen: Dict[tuple, dict],
) -> Dict[tuple, dict]:
    """One full-state pass shared by the poll and watch loops: reconcile
    every listed CR, finalize every previously-seen CR that vanished
    from the listing. Returns the new ``seen`` map."""
    current = {cr_key(c): c for c in listed}
    for cr in current.values():
        safe_reconcile(reconciler, cr)
    for key, cr in seen.items():
        if key not in current:
            logger.info("finalizing deleted CR %s/%s", key[0], key[1])
            if not safe_finalize(reconciler, cr):
                # children remain for now; the CR stays absent from every
                # later listing, so the next pass retries the teardown
                current[key] = cr  # keep it in seen for the retry
    return current


def control_loop(
    reconciler: Reconciler,
    get_crs,                 # () -> List[dict] current CRs
    interval: float = 10.0,
    stop=None,               # threading.Event-like; None = run forever
) -> None:
    """Poll-based control loop (watch-based callers use watch.watch_loop
    instead; both share relist_reconcile)."""
    seen: Dict[tuple, dict] = {}
    while stop is None or not stop.is_set():
        listed = get_crs()
        if listed is not None:
            seen = relist_reconcile(reconciler, listed, seen)
        # listed None = listing failed — do NOT mistake it for "no CRs"
        # (which would finalize everything); retry next cycle
        if stop is not None and stop.wait(interval):
            break
        if stop is None:
            time.sleep(interval)
