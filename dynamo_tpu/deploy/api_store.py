"""api-store: REST CRUD over deployment records, backed by sqlite.

Reference analog: deploy/dynamo/api-store — the service the reference's
CLI and operator use to persist deployment artifacts/records. Same REST
surface shape (list/get/create/update/delete deployments as JSON
documents), stdlib sqlite3 for durability, aiohttp like the rest of the
framework's HTTP plane.

Run standalone:  python -m dynamo_tpu.deploy.api_store --port 8790
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sqlite3
import time
from typing import Optional

from aiohttp import web

logger = logging.getLogger(__name__)

DEFAULT_PORT = 8790


class DeploymentStore:
    """sqlite-backed document store: name → deployment spec (JSON)."""

    def __init__(self, path: str = ":memory:"):
        self.db = sqlite3.connect(path)
        self.db.execute(
            "CREATE TABLE IF NOT EXISTS deployments ("
            " name TEXT PRIMARY KEY,"
            " spec TEXT NOT NULL,"
            " created REAL NOT NULL,"
            " updated REAL NOT NULL,"
            " status TEXT)"
        )
        try:  # migrate pre-status databases in place
            self.db.execute("ALTER TABLE deployments ADD COLUMN status TEXT")
        except sqlite3.OperationalError:
            pass
        self.db.commit()

    @staticmethod
    def _record(row) -> dict:
        n, s, c, u, st = row
        return {
            "name": n, "spec": json.loads(s), "created": c, "updated": u,
            "status": json.loads(st) if st else None,
        }

    def list(self) -> list:
        rows = self.db.execute(
            "SELECT name, spec, created, updated, status FROM deployments"
            " ORDER BY name"
        ).fetchall()
        return [self._record(r) for r in rows]

    def get(self, name: str) -> Optional[dict]:
        row = self.db.execute(
            "SELECT name, spec, created, updated, status FROM deployments"
            " WHERE name=?",
            (name,),
        ).fetchone()
        return None if row is None else self._record(row)

    def set_status(self, name: str, status: dict) -> bool:
        """Reconciler write-back: the store plays the CR's status
        subresource for store-sourced deployments."""
        cur = self.db.execute(
            "UPDATE deployments SET status=? WHERE name=?",
            (json.dumps(status), name),
        )
        self.db.commit()
        return cur.rowcount > 0

    def put(self, name: str, spec: dict) -> dict:
        now = time.time()
        existing = self.get(name)
        if existing is None:
            self.db.execute(
                "INSERT INTO deployments (name, spec, created, updated)"
                " VALUES (?, ?, ?, ?)",
                (name, json.dumps(spec), now, now),
            )
        else:
            self.db.execute(
                "UPDATE deployments SET spec=?, updated=? WHERE name=?",
                (json.dumps(spec), now, name),
            )
        self.db.commit()
        return self.get(name)  # type: ignore[return-value]

    def delete(self, name: str) -> bool:
        cur = self.db.execute("DELETE FROM deployments WHERE name=?", (name,))
        self.db.commit()
        return cur.rowcount > 0


class ApiStoreService:
    """aiohttp REST frontend over a DeploymentStore."""

    def __init__(self, store: Optional[DeploymentStore] = None,
                 host: str = "0.0.0.0", port: int = DEFAULT_PORT):
        self.store = store or DeploymentStore()
        self.host = host
        self.port = port
        self.app = web.Application()
        self.app.router.add_get("/api/v1/deployments", self.handle_list)
        self.app.router.add_post("/api/v1/deployments", self.handle_create)
        self.app.router.add_get("/api/v1/deployments/{name}", self.handle_get)
        self.app.router.add_put("/api/v1/deployments/{name}", self.handle_update)
        self.app.router.add_delete("/api/v1/deployments/{name}", self.handle_delete)
        self.app.router.add_put(
            "/api/v1/deployments/{name}/status", self.handle_status
        )
        self.app.router.add_get("/health", self.handle_health)
        self._runner: Optional[web.AppRunner] = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        logger.info("api-store on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    # ---------- handlers ----------

    async def handle_list(self, request: web.Request) -> web.Response:
        return web.json_response({"deployments": self.store.list()})

    async def handle_get(self, request: web.Request) -> web.Response:
        record = self.store.get(request.match_info["name"])
        if record is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(record)

    async def handle_create(self, request: web.Request) -> web.Response:
        try:
            body = await request.json()
            name = body["name"]
            spec = body.get("spec", {})
        except (json.JSONDecodeError, KeyError, TypeError) as e:
            return web.json_response({"error": f"invalid body: {e}"}, status=400)
        if not isinstance(spec, dict):
            # same contract as PUT — a stored non-dict spec would blow up
            # every consumer that renders manifests from the record
            return web.json_response(
                {"error": "spec must be a JSON object"}, status=400
            )
        if self.store.get(name) is not None:
            return web.json_response(
                {"error": f"deployment {name!r} exists"}, status=409
            )
        return web.json_response(self.store.put(name, spec), status=201)

    async def handle_update(self, request: web.Request) -> web.Response:
        """PUT takes the SAME envelope as POST: {"spec": {...}} (name
        optional, must match the URL). Requiring the envelope — instead of
        guessing whether a body is a bare spec — keeps specs that happen to
        contain a top-level "spec" key unambiguous."""
        name = request.match_info["name"]
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return web.json_response({"error": f"invalid body: {e}"}, status=400)
        if not isinstance(body, dict) or "spec" not in body:
            return web.json_response(
                {"error": 'body must be {"spec": {...}}'}, status=400
            )
        if body.get("name") not in (None, name):
            return web.json_response(
                {"error": "body name does not match URL"}, status=400
            )
        spec = body["spec"]
        if not isinstance(spec, dict):
            return web.json_response(
                {"error": "spec must be a JSON object"}, status=400
            )
        if self.store.get(name) is None:
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(self.store.put(name, spec))

    async def handle_status(self, request: web.Request) -> web.Response:
        """Status subresource for store-sourced deployments (written by
        the operator's reconcile loop, read back via GET/list)."""
        name = request.match_info["name"]
        try:
            body = await request.json()
        except json.JSONDecodeError as e:
            return web.json_response({"error": f"invalid body: {e}"}, status=400)
        if not isinstance(body, dict) or not isinstance(body.get("status"), dict):
            return web.json_response(
                {"error": 'body must be {"status": {...}}'}, status=400
            )
        if not self.store.set_status(name, body["status"]):
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response(self.store.get(name))

    async def handle_delete(self, request: web.Request) -> web.Response:
        if not self.store.delete(request.match_info["name"]):
            return web.json_response({"error": "not found"}, status=404)
        return web.json_response({"deleted": True})

    async def handle_health(self, request: web.Request) -> web.Response:
        return web.json_response({"status": "ok"})


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo-tpu api-store")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument("--db", default="dynamo_api_store.sqlite")
    args = parser.parse_args()
    from ..utils.logging import setup_logging

    setup_logging(logging.INFO)

    async def run():
        service = ApiStoreService(DeploymentStore(args.db), args.host, args.port)
        await service.start()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
