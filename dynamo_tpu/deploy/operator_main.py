"""Operator binary: poll DynamoTpuGraphDeployment CRs via kubectl and
reconcile (the in-cluster entrypoint the helm chart deploys).

Reference analog: deploy/dynamo/operator cmd/main.go. The poll loop is
deliberate — kubectl handles auth/watch reconnection complexity, and
serving graphs change rarely; watch-driven callers can instead feed
``Reconciler.reconcile`` from their own event source.
"""

from __future__ import annotations

import argparse
import json
import logging
import subprocess
import threading

from ..utils.logging import setup_logging
from .operator import GROUP, PLURAL, KubectlClient, Reconciler, control_loop

logger = logging.getLogger(__name__)


def get_crs(kubectl: str = "kubectl", namespace: str | None = None):
    """List CRs, or None when the listing itself failed — the loop must
    skip that cycle; treating a transient API error as "no CRs" would
    finalize (delete) every managed child cluster-wide."""
    args = [kubectl, "get", f"{PLURAL}.{GROUP}", "-o", "json"]
    args += ["-n", namespace] if namespace else ["--all-namespaces"]
    try:
        out = subprocess.run(
            args, capture_output=True, text=True, check=True
        ).stdout
    except subprocess.CalledProcessError as e:
        logger.warning("listing CRs failed: %s", e.stderr.strip())
        return None
    return json.loads(out).get("items", [])


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo-tpu operator")
    parser.add_argument("--interval", type=float, default=10.0)
    parser.add_argument("--namespace", default=None,
                        help="watch one namespace (default: all)")
    parser.add_argument("--kubectl", default="kubectl")
    parser.add_argument(
        "--api-store-url", default=None,
        help="reconcile deployments registered in the api-store instead "
             "of (or in addition to) cluster CRs; reconcile status is "
             "written back into the store record",
    )
    args = parser.parse_args()
    setup_logging(logging.INFO)

    if args.api_store_url:
        from .store_source import ApiStoreClient

        store = ApiStoreClient(args.api_store_url)
        reconciler = Reconciler(
            KubectlClient(args.kubectl), status_writer=store.write_status
        )
        source = store.get_crs
        logger.info("operator sourcing CRs from api-store %s every %.0fs",
                    args.api_store_url, args.interval)
    else:
        reconciler = Reconciler(KubectlClient(args.kubectl))
        source = lambda: get_crs(args.kubectl, args.namespace)  # noqa: E731
        logger.info("operator watching %s.%s every %.0fs",
                    PLURAL, GROUP, args.interval)
    control_loop(
        reconciler,
        source,
        interval=args.interval,
        stop=threading.Event(),  # run until killed; Event never set
    )


if __name__ == "__main__":
    main()
