"""Operator binary: reconcile DynamoTpuGraphDeployment CRs via kubectl
(the in-cluster entrypoint the helm chart deploys).

Reference analog: deploy/dynamo/operator cmd/main.go. Two drive modes:
the default watch loop (kubectl --watch events + relist-on-reconnect,
matching controller-runtime's informer+resync semantics) and a plain
poll loop (--poll) for API servers where long watches are awkward.
``--leader-elect`` arbitrates replicas through a coordination.k8s.io
Lease, like the Go operator's LeaderElection flag.
"""

from __future__ import annotations

import argparse
import json
import logging
import subprocess
import threading

from ..utils.logging import setup_logging
from .operator import GROUP, PLURAL, KubectlClient, Reconciler, control_loop

logger = logging.getLogger(__name__)


def get_crs(kubectl: str = "kubectl", namespace: str | None = None):
    """List CRs, or None when the listing itself failed — the loop must
    skip that cycle; treating a transient API error as "no CRs" would
    finalize (delete) every managed child cluster-wide."""
    args = [kubectl, "get", f"{PLURAL}.{GROUP}", "-o", "json"]
    args += ["-n", namespace] if namespace else ["--all-namespaces"]
    try:
        out = subprocess.run(
            args, capture_output=True, text=True, check=True
        ).stdout
    except subprocess.CalledProcessError as e:
        logger.warning("listing CRs failed: %s", e.stderr.strip())
        return None
    return json.loads(out).get("items", [])


def main() -> None:
    parser = argparse.ArgumentParser(description="dynamo-tpu operator")
    parser.add_argument("--interval", type=float, default=10.0)
    parser.add_argument("--namespace", default=None,
                        help="watch one namespace (default: all)")
    parser.add_argument("--kubectl", default="kubectl")
    parser.add_argument(
        "--kube-client", choices=("kubectl", "api"), default="kubectl",
        help="cluster access: shell out to kubectl, or talk REST to the "
             "API server directly (in-cluster serviceaccount, or "
             "--kube-api-url for an explicit endpoint)",
    )
    parser.add_argument("--kube-api-url", default=None,
                        help="API server base URL (api mode; default: "
                             "in-cluster serviceaccount discovery)")
    parser.add_argument("--poll", action="store_true",
                        help="poll every --interval instead of watching")
    parser.add_argument("--resync-interval", type=float, default=300.0,
                        help="watch mode: relist+reconcile at least this "
                             "often (the watch's request timeout)")
    parser.add_argument("--leader-elect", action="store_true",
                        help="run only while holding the operator Lease")
    parser.add_argument("--leader-elect-namespace", default="default")
    parser.add_argument("--identity", default=None,
                        help="leader-election identity (default: hostname)")
    parser.add_argument(
        "--api-store-url", default=None,
        help="reconcile deployments registered in the api-store instead "
             "of (or in addition to) cluster CRs; reconcile status is "
             "written back into the store record",
    )
    args = parser.parse_args()
    setup_logging(logging.INFO)

    if args.kube_client == "api":
        from .kube_api import KubeApiClient

        kube = (
            KubeApiClient(args.kube_api_url) if args.kube_api_url
            else KubeApiClient.from_in_cluster()
        )
    else:
        kube = KubectlClient(args.kubectl)

    poll = args.poll
    if args.api_store_url:
        from .store_source import ApiStoreClient

        store = ApiStoreClient(args.api_store_url)
        reconciler = Reconciler(kube, status_writer=store.write_status)
        source = store.get_crs
        poll = True  # the store has no watch API; poll it
        logger.info("operator sourcing CRs from api-store %s every %.0fs",
                    args.api_store_url, args.interval)
    else:
        reconciler = Reconciler(kube)
        if args.kube_client == "api":
            source = lambda: kube.get_crs(args.namespace)  # noqa: E731
        else:
            source = lambda: get_crs(args.kubectl, args.namespace)  # noqa: E731
        logger.info("operator %s %s.%s via %s",
                    "polling" if poll else "watching", PLURAL, GROUP,
                    args.kube_client)

    stop = threading.Event()  # set only by a lost leader lease
    if poll:
        drive = lambda: control_loop(  # noqa: E731
            reconciler, source, interval=args.interval, stop=stop)
    else:
        from .watch import KubectlWatchSource, watch_loop

        if args.kube_client == "api":
            open_stream = lambda: kube.open_watch(  # noqa: E731
                args.namespace,
                timeout_seconds=int(args.resync_interval),
            )
        else:
            open_stream = KubectlWatchSource(
                args.kubectl, args.namespace,
                resync_interval_s=args.resync_interval,
            )
        drive = lambda: watch_loop(  # noqa: E731
            reconciler, source, open_stream, stop=stop)

    if args.leader_elect:
        import socket

        from .leader import KubectlLeases, LeaderElector

        if args.kube_client == "api":
            # lease CAS over the same REST client — no kubectl binary
            # needed in the image for any operator feature
            from .kube_api import KubeApiLeases

            leases = KubeApiLeases(kube)
        else:
            leases = KubectlLeases(args.kubectl)
        elector = LeaderElector(
            leases,
            identity=args.identity or socket.gethostname(),
            namespace=args.leader_elect_namespace,
        )
        elector.run(stop, drive)
    else:
        drive()


if __name__ == "__main__":
    main()
