"""Deployment plane: K8s operator, manifest rendering, api-store.

Reference analog: deploy/dynamo/{operator,api-store,helm} — the Go
operator reconciling DynamoDeployment CRDs into child Deployments, the
deployment-record REST store, and the helm platform chart. Here the
operator is Python (the rest of the framework's control plane already
is), built around pure manifest-rendering functions and a pluggable
cluster client so the reconcile logic is fully testable without a
cluster.
"""

from .operator import (
    InMemoryKube,
    KubectlClient,
    Reconciler,
    render_manifests,
)

__all__ = [
    "InMemoryKube",
    "KubectlClient",
    "Reconciler",
    "render_manifests",
]
