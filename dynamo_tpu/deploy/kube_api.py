"""Direct Kubernetes API-server client (no kubectl shell-out).

Reference analog: the reference's Go operator talks to the API server
through client-go (deploy/dynamo/operator); this is the same plane over
plain REST — urllib + the in-cluster serviceaccount contract — so
operator pods need no kubectl binary, and the client's semantics
(server-side apply, status subresource, labelSelector lists, watch
streams) can be exercised against a real-shaped fake API server in
tests instead of a subprocess mock.

Implements the deploy/operator.py ``KubeClient`` protocol plus the
watch-loop source contract:

- ``apply``: server-side apply (``PATCH ?fieldManager=...&force=true``,
  content type ``application/apply-patch+yaml`` — JSON is valid YAML),
  the modern idempotent upsert; force resolves manager conflicts the
  way a controller must (it owns its children).
- ``update_status``: merge-patch against the CR's ``/status``
  subresource — spec edits in the body are ignored by the server, the
  exact behavior the CRD's ``subresources.status`` enables.
- ``list_managed`` / ``get_crs``: labelSelector / CRD collection GETs.
- ``open_watch``: a ``?watch=1`` streaming GET yielding watch events,
  pluggable into deploy/watch.py ``watch_loop`` as ``open_stream``.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import ssl
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, Iterator, List, Optional, Tuple

from .operator import GROUP, KIND, PLURAL, VERSION, managed_selector

logger = logging.getLogger(__name__)

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# kind → (API prefix, plural). The operator only manages these children.
_KIND_PATHS: Dict[str, tuple] = {
    "Deployment": ("/apis/apps/v1", "deployments"),
    "Service": ("/api/v1", "services"),
}


class KubeApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"kube api {status}: {body[:300]}")
        self.status = status


class KubeApiClient:
    """Sync REST client for the operator's needs (KubeClient protocol)."""

    def __init__(
        self,
        base_url: str,
        token: Optional[str] = None,
        token_file: Optional[str] = None,
        ca_file: Optional[str] = None,
        field_manager: str = "dynamo-tpu-operator",
        timeout: float = 30.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.token = token
        # bound serviceaccount tokens expire (~1h) and the kubelet
        # rotates the mounted file — re-read per request, never cache
        self.token_file = token_file
        self.field_manager = field_manager
        self.timeout = timeout
        if ca_file:
            self._ctx: Optional[ssl.SSLContext] = ssl.create_default_context(
                cafile=ca_file
            )
        elif self.base_url.startswith("https"):
            self._ctx = ssl.create_default_context()
        else:
            self._ctx = None

    @classmethod
    def from_in_cluster(cls) -> "KubeApiClient":
        """The pod serviceaccount contract (KUBERNETES_SERVICE_HOST +
        mounted token/CA) — how the operator container authenticates."""
        host = os.environ.get("KUBERNETES_SERVICE_HOST")
        token_file = os.path.join(SA_DIR, "token")
        if not host or not os.path.exists(token_file):
            raise RuntimeError(
                "not running in a cluster (no KUBERNETES_SERVICE_HOST / "
                f"{token_file}); pass --kube-api-url for an explicit "
                "API-server endpoint"
            )
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        return cls(
            f"https://{host}:{port}", token_file=token_file,
            ca_file=os.path.join(SA_DIR, "ca.crt"),
        )

    # ---------- plumbing ----------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[dict] = None,
        content_type: str = "application/json",
        query: Optional[dict] = None,
        stream: bool = False,
        stream_timeout: Optional[float] = None,
    ):
        url = self.base_url + path
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(
            url, method=method,
            data=None if body is None else json.dumps(body).encode(),
        )
        if body is not None:
            req.add_header("Content-Type", content_type)
        token = self.token
        if self.token_file:
            with open(self.token_file) as f:
                token = f.read().strip()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        try:
            resp = urllib.request.urlopen(
                req, timeout=stream_timeout if stream else self.timeout,
                context=self._ctx,
            )
        except urllib.error.HTTPError as e:
            raise KubeApiError(e.code, e.read().decode(errors="replace"))
        if stream:
            return resp
        with resp:
            text = resp.read().decode()
        return json.loads(text) if text else None

    @staticmethod
    def _child_path(kind: str, namespace: str, name: Optional[str] = None) -> str:
        prefix, plural = _KIND_PATHS[kind]
        base = f"{prefix}/namespaces/{namespace}/{plural}"
        return f"{base}/{name}" if name else base

    @staticmethod
    def _cr_path(namespace: Optional[str], name: Optional[str] = None) -> str:
        base = (
            f"/apis/{GROUP}/{VERSION}/namespaces/{namespace}/{PLURAL}"
            if namespace else f"/apis/{GROUP}/{VERSION}/{PLURAL}"
        )
        return f"{base}/{name}" if name else base

    # ---------- KubeClient protocol ----------

    def apply(self, manifest: dict) -> None:
        kind = manifest["kind"]
        md = manifest["metadata"]
        self._request(
            "PATCH",
            self._child_path(kind, md.get("namespace", "default"), md["name"]),
            body=manifest,
            content_type="application/apply-patch+yaml",
            query={"fieldManager": self.field_manager, "force": "true"},
        )

    def delete(self, kind: str, namespace: str, name: str) -> None:
        try:
            self._request(
                "DELETE", self._child_path(kind.capitalize(), namespace, name)
            )
        except KubeApiError as e:
            if e.status != 404:  # --ignore-not-found semantics
                raise

    def list_managed(self, namespace: str, instance: str) -> List[dict]:
        items: List[dict] = []
        for kind, (prefix, plural) in _KIND_PATHS.items():
            out = self._request(
                "GET", self._child_path(kind, namespace),
                query={"labelSelector": managed_selector(instance)},
            )
            api_version = prefix.removeprefix("/apis/").removeprefix("/api/")
            for obj in (out or {}).get("items", []):
                # list responses omit per-item kind/apiVersion; the
                # reconciler keys children by kind, so restore them
                obj.setdefault("kind", kind)
                obj.setdefault("apiVersion", api_version)
                items.append(obj)
        return items

    def update_status(self, cr: dict, status: dict) -> None:
        self._request(
            "PATCH",
            self._cr_path(cr["metadata"].get("namespace", "default"),
                          cr["metadata"]["name"]) + "/status",
            body={"status": status},
            content_type="application/merge-patch+json",
        )

    # ---------- CR source (poll + watch loops) ----------

    def get_crs(self, namespace: Optional[str] = None) -> Optional[List[dict]]:
        """None on API failure (a dead API must never read as 'no CRs' —
        the loops treat None as skip-cycle, [] as finalize-everything)."""
        try:
            out = self._request("GET", self._cr_path(namespace))
            items = (out or {}).get("items", [])
            for obj in items:
                obj.setdefault("kind", KIND)
                obj.setdefault("apiVersion", f"{GROUP}/{VERSION}")
            return items
        except (KubeApiError, OSError, http.client.HTTPException,
                json.JSONDecodeError) as e:
            # IncompleteRead on a truncated body is an HTTPException, not
            # an OSError; a garbled body is a JSONDecodeError — both are
            # "API failed this cycle", never allowed to kill the loop
            logger.warning("CR list failed: %s", e)
            return None

    # ---------- coordination.k8s.io Leases (leader election) ----------

    _LEASE_BASE = "/apis/coordination.k8s.io/v1"

    def read_lease(
        self, namespace: str, name: str
    ) -> Tuple[Optional[dict], Optional[str]]:
        """LeaseClient.read: (spec, resourceVersion), or (None, None) when
        absent. Any non-404 failure RAISES — 'lease absent' and 'API
        unreachable' must stay distinct or a blip deposes a healthy
        leader (deploy/leader.py)."""
        try:
            obj = self._request(
                "GET",
                f"{self._LEASE_BASE}/namespaces/{namespace}/leases/{name}",
            )
        except KubeApiError as e:
            if e.status == 404:
                return None, None
            raise
        return obj.get("spec", {}), obj["metadata"].get("resourceVersion")

    def write_lease(self, namespace: str, name: str, spec: dict,
                    expected_version: Optional[str]) -> bool:
        """LeaseClient.write: CAS commit. POST when expected_version is
        None (create-only — 409 AlreadyExists = lost the race), PUT with
        resourceVersion otherwise (409 Conflict = lost the race). Other
        failures raise (transient, NOT an authoritative loss)."""
        body = {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": name, "namespace": namespace},
            "spec": spec,
        }
        base = f"{self._LEASE_BASE}/namespaces/{namespace}/leases"
        try:
            if expected_version is None:
                self._request("POST", base, body=body)
            else:
                body["metadata"]["resourceVersion"] = expected_version
                self._request("PUT", f"{base}/{name}", body=body)
        except KubeApiError as e:
            if e.status == 409:
                logger.debug("lease write lost the CAS race: %s", e)
                return False
            raise
        return True

    def open_watch(
        self, namespace: Optional[str] = None,
        timeout_seconds: int = 300,
    ) -> Iterator[dict]:
        """``watch_loop`` open_stream source: yields watch event dicts
        ({type, object}) until the server closes the stream."""
        # client-side socket timeout slightly past the server's request
        # timeout: a silently dropped connection (LB idle reset, node
        # failover) must end the stream so watch_loop can relist —
        # without it `for raw in resp` would block forever
        resp = self._request(
            "GET", self._cr_path(namespace),
            query={"watch": "1", "timeoutSeconds": str(timeout_seconds)},
            stream=True, stream_timeout=timeout_seconds + 30.0,
        )
        try:
            for raw in resp:  # the API server streams one JSON per line
                line = raw.decode(errors="replace").strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except json.JSONDecodeError:
                    logger.warning("watch: undecodable line %r", line[:120])
                    return
        finally:
            resp.close()


class KubeApiLeases:
    """deploy/leader.py LeaseClient over the REST client — leader
    election without a kubectl binary in the image (the kubectl analog
    is leader.KubectlLeases)."""

    def __init__(self, client: KubeApiClient):
        self.client = client

    def read(self, namespace: str, name: str):
        return self.client.read_lease(namespace, name)

    def write(self, namespace: str, name: str, spec: dict,
              expected_version: Optional[str]) -> bool:
        return self.client.write_lease(namespace, name, spec,
                                       expected_version)
