"""SLA policy engine: rolling-window signals → typed control actions.

This is the decision half of the closed loop (PAPER.md §1 layer 9 — the
planner the reference's K8s controllers feed): pure functions of a
:class:`~dynamo_tpu.planner.signals.SignalStore` plus the policy's own
hysteresis state. It never touches the cluster, the router, or the HTTP
edge — it only *emits* :data:`Action` values; planner/actuation.py turns
them into replica patches, router-config pushes, and admission-limit
changes. That split is what makes the loop testable: scripted metric
feeds in, pinned action sequences out (tests/test_planner.py).

Flap resistance is structural, not incidental:

- **hysteresis** — every scale trigger has separate up and down
  thresholds; the band between them is a dead zone where nothing moves.
- **cooldown** — after any action on a role, that role is frozen for
  ``scale_up_cooldown_s`` / ``scale_down_cooldown_s`` (down is slower:
  shedding capacity is the riskier direction under a spike).
- **bounds** — replica targets clamp to [min_replicas, max_replicas];
  the admission shed level never reaches the highest priority class.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Mapping, Optional, Union

from .signals import SignalStore

# canonical signal names (one vocabulary shared by sources, policy, and
# docs/planner.md — drift here means the policy silently sees nothing)
SIG_PREFILL_QUEUE_WAIT = "prefill.queue_wait_s"
SIG_PREFILL_QUEUE_DEPTH = "prefill.queue_depth"
SIG_DECODE_SLOT_BUSY = "decode.slot_busy_ratio"
SIG_DECODE_WAITING = "decode.waiting"
SIG_KV_USAGE = "kv.usage_ratio"
SIG_WATCHDOG_TRIPS = "watchdog.trips"
SIG_ADMISSION_QUEUE_DEPTH = "admission.queue_depth"
SIG_ADMISSION_INFLIGHT_RATIO = "admission.inflight_ratio"
# user-visible latency (telemetry/slo.py SloTracker.snapshot — the HTTP
# edge's per-request TTFT/ITL verdicts as rolling attainment fractions)
SIG_SLO_ATTAINMENT = "slo.attainment"
SIG_SLO_TTFT_ATTAINMENT = "slo.ttft_attainment"
SIG_SLO_ITL_ATTAINMENT = "slo.itl_attainment"
SIG_SLO_GOODPUT = "slo.goodput_tokens_per_s"


@dataclasses.dataclass(frozen=True)
class ScaleAction:
    """Patch one role's worker-pool replica count."""

    role: str              # "prefill" | "decode"
    target_replicas: int
    current_replicas: int
    reason: str

    @property
    def direction(self) -> str:
        return "up" if self.target_replicas > self.current_replicas else "down"


@dataclasses.dataclass(frozen=True)
class RebalanceAction:
    """Retune the disagg router's local/remote prefill split."""

    max_local_prefill_length: int
    max_prefill_queue_size: int
    reason: str


@dataclasses.dataclass(frozen=True)
class AdmissionAction:
    """Tighten/relax the HTTP edge: shed level + concurrency limit.

    ``shed_level`` counts priority classes shed from the bottom: 0 sheds
    nothing, 1 sheds the lowest class, and so on. The policy never
    emits a level that would shed the highest class. ``limit`` is None
    when the admission concurrency limit should stay as configured.
    """

    shed_level: int
    limit: Optional[int]   # max concurrently admitted; None = leave as-is
    reason: str


Action = Union[ScaleAction, RebalanceAction, AdmissionAction]


@dataclasses.dataclass
class PolicyConfig:
    """Thresholds and pacing for :class:`SlaPolicy`.

    Defaults are deliberately conservative; the CLI exposes the
    operationally interesting ones as ``--planner-*`` flags.
    """

    window_s: float = 10.0               # aggregate window for triggers

    # ----- prefill pool (queue-wait is the SLA-facing signal) -----
    prefill_queue_wait_up_s: float = 1.0
    prefill_queue_wait_down_s: float = 0.1
    prefill_queue_depth_up: float = 4.0

    # ----- decode pool (slot occupancy + admission backlog) -----
    decode_busy_up: float = 0.9
    decode_busy_down: float = 0.3
    decode_waiting_up: float = 4.0

    # ----- scaling pacing/bounds -----
    min_replicas: int = 1
    max_replicas: int = 8
    scale_step: int = 1
    scale_up_cooldown_s: float = 30.0
    scale_down_cooldown_s: float = 120.0

    # ----- disagg rebalance (remote-prefill threshold) -----
    rebalance_cooldown_s: float = 30.0
    min_local_prefill_length: int = 250
    max_local_prefill_length: int = 16000
    rebalance_factor: float = 2.0        # threshold moves multiplicatively

    # ----- admission control -----
    # SLO-driven saturation: attainment (SLO-met fraction of completed
    # requests over the window) below this floor counts as saturation —
    # the control loop acts on USER-VISIBLE latency, not queue proxies.
    # Only consulted when the slo.* signals are registered (an edge
    # serving without --slo-* flags feeds nothing and nothing changes).
    slo_attainment_floor: float = 0.9
    saturation_kv_usage: float = 0.95
    saturation_busy: float = 0.95
    saturation_waiting: float = 8.0
    saturation_admission_queue: float = 4.0  # at full edge concurrency
    shed_step_cooldown_s: float = 5.0    # between shed-level increases
    relax_after_clear_s: float = 30.0    # healthy this long → relax a level
    max_shed_level: int = 2              # never sheds the highest class
    admitted_limit: Optional[int] = None  # None = leave the edge's limit alone


class SlaPolicy:
    """Deterministic policy: ``decide(signals, replicas)`` → actions.

    Holds only pacing state (last action times, current shed level /
    rebalance threshold) — all load state lives in the SignalStore, so a
    restarted planner re-derives its view from the next few scrapes.
    """

    def __init__(
        self,
        config: Optional[PolicyConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        initial_local_prefill_length: int = 1000,
        initial_prefill_queue_size: int = 2,
    ):
        self.config = config or PolicyConfig()
        self.clock = clock
        self._last_scale_t: dict = {}        # role → monotonic t of last action
        self._last_scale_dir: dict = {}      # role → "up" | "down"
        self._last_rebalance_t: Optional[float] = None
        self._last_shed_change_t: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._prev = None  # decide()'s pacing snapshot, for rollback()
        self.shed_level = 0
        self.local_prefill_length = initial_local_prefill_length
        self.prefill_queue_size = initial_prefill_queue_size

    # ---------- helpers ----------

    def _cooled(self, role: str, direction: str) -> bool:
        last = self._last_scale_t.get(role)
        if last is None:
            return True
        cd = (self.config.scale_up_cooldown_s if direction == "up"
              else self.config.scale_down_cooldown_s)
        return self.clock() - last >= cd

    def _mark_scaled(self, role: str, direction: str) -> None:
        self._last_scale_t[role] = self.clock()
        self._last_scale_dir[role] = direction

    def _scale(self, role: str, replicas: Mapping[str, int], direction: str,
               reason: str) -> Optional[ScaleAction]:
        current = replicas.get(role)
        if current is None:
            return None  # role not deployed — nothing to scale
        if not self._cooled(role, direction):
            return None
        step = self.config.scale_step
        target = current + step if direction == "up" else current - step
        target = max(self.config.min_replicas,
                     min(self.config.max_replicas, target))
        if target == current:
            return None
        self._mark_scaled(role, direction)
        return ScaleAction(role=role, target_replicas=target,
                           current_replicas=current, reason=reason)

    # ---------- the decision ----------

    def decide(self, signals: SignalStore,
               replicas: Mapping[str, int]) -> List[Action]:
        """One policy pass. Deterministic given the store, the replica
        map, and the injected clock."""
        cfg = self.config
        w = cfg.window_s
        actions: List[Action] = []
        # snapshot the pacing state so an action NO actuator applies can
        # be rolled back (rollback()) — otherwise the policy's view
        # (shed level, router threshold, cooldowns) silently diverges
        # from reality for the rest of the process lifetime
        self._prev = (
            dict(self._last_scale_t), self._last_rebalance_t,
            self._last_shed_change_t, self.shed_level,
            self.local_prefill_length, self._clear_since,
        )

        # --- prefill pool: queue wait is the SLA signal; queue depth is
        # an independent trigger (the standalone planner often has only
        # the depth poll — the wait histogram lives on the workers) ---
        queue_wait = signals.mean(SIG_PREFILL_QUEUE_WAIT, w)
        queue_depth = signals.latest(SIG_PREFILL_QUEUE_DEPTH, 0.0)
        depth_mean = signals.mean(SIG_PREFILL_QUEUE_DEPTH, w)
        wait_s = "—" if queue_wait is None else f"{queue_wait:.2f}s"
        if ((queue_wait is not None
                and queue_wait > cfg.prefill_queue_wait_up_s)
                or queue_depth > cfg.prefill_queue_depth_up):
            a = self._scale(
                "prefill", replicas, "up",
                f"prefill queue wait {wait_s} depth {queue_depth:.0f}")
            if a:
                actions.append(a)
        elif ((queue_wait is None or
                queue_wait < cfg.prefill_queue_wait_down_s)
                and depth_mean == 0 and queue_depth == 0):
            # idle needs a full idle window, not one empty-depth sample
            a = self._scale(
                "prefill", replicas, "down",
                f"prefill idle (wait {wait_s}, empty queue)")
            if a:
                actions.append(a)

        # --- decode pool: slot occupancy + admission backlog ---
        busy = signals.mean(SIG_DECODE_SLOT_BUSY, w)
        waiting = signals.latest(SIG_DECODE_WAITING, 0.0)
        if busy is not None and (
                busy > cfg.decode_busy_up or waiting > cfg.decode_waiting_up):
            a = self._scale(
                "decode", replicas, "up",
                f"decode busy {busy:.2f} waiting {waiting:.0f}")
            if a:
                actions.append(a)
        elif busy is not None and busy < cfg.decode_busy_down and waiting == 0:
            a = self._scale(
                "decode", replicas, "down",
                f"decode idle (busy {busy:.2f})")
            if a:
                actions.append(a)

        # --- disagg rebalance: shift the local/remote split toward the
        # side with headroom ---
        rebalance = self._decide_rebalance(signals)
        if rebalance:
            actions.append(rebalance)

        # --- admission: shed under saturation, relax when clear ---
        admission = self._decide_admission(signals)
        if admission:
            actions.append(admission)

        return actions

    def rollback(self, action: Action) -> None:
        """Undo the pacing state an emitted-but-unapplied action
        committed, so the decision retries next cycle instead of the
        policy believing a change that never landed."""
        prev = getattr(self, "_prev", None)
        if prev is None:
            return
        scale_t, rebalance_t, shed_t, shed_level, local_len, clear = prev
        if isinstance(action, ScaleAction):
            if action.role in scale_t:
                self._last_scale_t[action.role] = scale_t[action.role]
            else:
                self._last_scale_t.pop(action.role, None)
        elif isinstance(action, RebalanceAction):
            self.local_prefill_length = local_len
            self._last_rebalance_t = rebalance_t
        elif isinstance(action, AdmissionAction):
            self.shed_level = shed_level
            self._last_shed_change_t = shed_t
            self._clear_since = clear

    def _decide_rebalance(self, signals: SignalStore) -> Optional[RebalanceAction]:
        cfg = self.config
        now = self.clock()
        if (self._last_rebalance_t is not None
                and now - self._last_rebalance_t < cfg.rebalance_cooldown_s):
            return None
        queue_depth = signals.latest(SIG_PREFILL_QUEUE_DEPTH)
        busy = signals.mean(SIG_DECODE_SLOT_BUSY, cfg.window_s)
        if queue_depth is None or busy is None:
            return None
        new_len = self.local_prefill_length
        reason = ""
        if (queue_depth > self.prefill_queue_size
                and busy < cfg.decode_busy_up):
            # prefill pool backed up while decode has headroom: raise the
            # threshold so more prefills stay local
            new_len = min(cfg.max_local_prefill_length,
                          int(self.local_prefill_length
                              * cfg.rebalance_factor))
            reason = (f"prefill queue {queue_depth:.0f} deep, decode busy "
                      f"{busy:.2f} — keep more prefill local")
        elif queue_depth == 0 and busy > cfg.decode_busy_up:
            # decode saturated while the prefill queue is drained: lower
            # the threshold so long prefills go remote again
            new_len = max(cfg.min_local_prefill_length,
                          int(self.local_prefill_length
                              / cfg.rebalance_factor))
            reason = (f"decode busy {busy:.2f}, prefill queue empty — "
                      f"send more prefill remote")
        if new_len == self.local_prefill_length:
            return None
        self.local_prefill_length = new_len
        self._last_rebalance_t = now
        return RebalanceAction(
            max_local_prefill_length=new_len,
            max_prefill_queue_size=self.prefill_queue_size,
            reason=reason,
        )

    def _saturated(self, signals: SignalStore) -> Optional[str]:
        """Non-empty reason string when the serving plane is saturated."""
        cfg = self.config
        w = cfg.window_s
        kv = signals.latest(SIG_KV_USAGE)
        if kv is not None and kv >= cfg.saturation_kv_usage:
            return f"kv usage {kv:.2f}"
        busy = signals.mean(SIG_DECODE_SLOT_BUSY, w)
        waiting = signals.latest(SIG_DECODE_WAITING, 0.0)
        if (busy is not None and busy >= cfg.saturation_busy
                and waiting >= cfg.saturation_waiting):
            return f"decode busy {busy:.2f} with {waiting:.0f} waiting"
        if signals.delta(SIG_WATCHDOG_TRIPS, w) > 0:
            return "watchdog tripped"
        # user-visible latency: the share of completed requests meeting
        # their TTFT/ITL targets fell through the floor — saturation by
        # the only definition the user can feel
        slo = signals.mean(SIG_SLO_ATTAINMENT, w)
        if (cfg.slo_attainment_floor > 0 and slo is not None
                and slo < cfg.slo_attainment_floor):
            return (f"slo attainment {slo:.2f} below floor "
                    f"{cfg.slo_attainment_floor:.2f}")
        # the edge's own state: a deep admission queue at full
        # concurrency IS saturation even when no engine signal reaches
        # this planner (the pure-frontend configuration)
        edge_q = signals.latest(SIG_ADMISSION_QUEUE_DEPTH)
        edge_busy = signals.mean(SIG_ADMISSION_INFLIGHT_RATIO, w)
        if (edge_q is not None and edge_busy is not None
                and edge_busy >= 1.0
                and edge_q >= cfg.saturation_admission_queue):
            return (f"admission queue {edge_q:.0f} deep at full "
                    f"concurrency")
        return None

    def _decide_admission(self, signals: SignalStore) -> Optional[AdmissionAction]:
        cfg = self.config
        now = self.clock()
        reason = self._saturated(signals)
        if reason:
            self._clear_since = None
            if self.shed_level >= cfg.max_shed_level:
                return None
            if (self._last_shed_change_t is not None
                    and now - self._last_shed_change_t
                    < cfg.shed_step_cooldown_s):
                return None
            self.shed_level += 1
            self._last_shed_change_t = now
            return AdmissionAction(
                shed_level=self.shed_level, limit=cfg.admitted_limit,
                reason=f"saturated: {reason}",
            )
        # healthy — relax one level after a sustained clear period
        if self.shed_level == 0:
            self._clear_since = None
            return None
        if self._clear_since is None:
            self._clear_since = now
            return None
        if now - self._clear_since < cfg.relax_after_clear_s:
            return None
        self.shed_level -= 1
        self._clear_since = now
        self._last_shed_change_t = now
        return AdmissionAction(
            shed_level=self.shed_level, limit=cfg.admitted_limit,
            reason="load cleared",
        )
