"""Rolling-window signal store: the planner's view of live telemetry.

Every planner input — scraped worker snapshots, admission-controller
state, registry series like prefill queue-wait or watchdog trips — lands
here as a named time series of ``(t, value)`` samples. The policy engine
(planner/policy.py) then asks window questions ("mean queue wait over
the last 10s", "did the watchdog trip counter move?") instead of acting
on single scrapes, which is what makes hysteresis possible: one noisy
sample must never flap a replica count.

The clock is injectable so policy tests can script a feed
deterministically (scripted samples at scripted times → pinned action
sequences), matching the FakeRunner discipline the decode-pipeline
tests use.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Deque, Dict, Mapping, Optional, Tuple


class SignalStore:
    """Bounded per-series sample windows with time-window aggregates."""

    def __init__(
        self,
        window_s: float = 120.0,
        max_samples: int = 1024,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.window_s = window_s
        self.max_samples = max_samples
        self.clock = clock
        self._series: Dict[str, Deque[Tuple[float, float]]] = {}

    # ---------- writing ----------

    def observe(self, name: str, value: float, t: Optional[float] = None) -> None:
        if t is None:
            t = self.clock()
        series = self._series.get(name)
        if series is None:
            series = self._series[name] = collections.deque(
                maxlen=self.max_samples)
        series.append((t, float(value)))
        self._prune(series, t)

    def observe_many(self, values: Mapping[str, float],
                     t: Optional[float] = None) -> None:
        if t is None:
            t = self.clock()
        for name, value in values.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue  # sources may carry non-numeric snapshot fields
            self.observe(name, value, t=t)

    def _prune(self, series: Deque[Tuple[float, float]], now: float) -> None:
        cutoff = now - self.window_s
        while series and series[0][0] < cutoff:
            series.popleft()

    # ---------- reading ----------

    def names(self):
        return sorted(self._series)

    def _window(self, name: str, window_s: Optional[float]):
        series = self._series.get(name)
        if not series:
            return []
        now = self.clock()
        self._prune(series, now)
        cutoff = now - (window_s if window_s is not None else self.window_s)
        return [v for (t, v) in series if t >= cutoff]

    def latest(self, name: str, default: Optional[float] = None):
        """Newest sample INSIDE the store window — a source that stopped
        reporting goes blind after ``window_s`` instead of serving its
        last value forever (the policy skips, rather than acts on, a
        dead signal)."""
        series = self._series.get(name)
        if not series:
            return default
        self._prune(series, self.clock())
        if not series:
            return default
        return series[-1][1]

    def age(self, name: str) -> Optional[float]:
        """Seconds since the newest sample; None if the series is empty."""
        series = self._series.get(name)
        if not series:
            return None
        return self.clock() - series[-1][0]

    def mean(self, name: str, window_s: Optional[float] = None,
             default: Optional[float] = None):
        vals = self._window(name, window_s)
        if not vals:
            return default
        return sum(vals) / len(vals)

    def max(self, name: str, window_s: Optional[float] = None,
            default: Optional[float] = None):
        vals = self._window(name, window_s)
        if not vals:
            return default
        return max(vals)

    def min(self, name: str, window_s: Optional[float] = None,
            default: Optional[float] = None):
        vals = self._window(name, window_s)
        if not vals:
            return default
        return min(vals)

    def delta(self, name: str, window_s: Optional[float] = None) -> float:
        """newest - oldest inside the window: the move of a cumulative
        counter (watchdog trips, shed count) over the window; 0.0 when
        fewer than two samples exist."""
        vals = self._window(name, window_s)
        if len(vals) < 2:
            return 0.0
        return vals[-1] - vals[0]
